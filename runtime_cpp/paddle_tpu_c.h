/* paddle_tpu C inference API.
 *
 * Role parity: reference `paddle/fluid/inference/capi_exp/pd_inference_api.h`
 * (stable C ABI over AnalysisPredictor, consumed by C hosts and the Go
 * wrapper). Here the predictor executes a StableHLO AOT artifact through
 * PJRT; this C layer embeds the Python runtime (or attaches to an already
 * running interpreter) and exposes the same create / set-input / run /
 * get-output lifecycle with plain C types.
 *
 * Thread-safety: calls grab the GIL; one predictor per thread recommended
 * (clone via PD_PredictorCreate per thread, like the reference's
 * AnalysisPredictor::Clone guidance).
 */
#ifndef PADDLE_TPU_C_H_
#define PADDLE_TPU_C_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Predictor PD_Predictor;

/* Optional: initialize the embedded Python runtime explicitly.
 * repo_root is prepended to sys.path (may be NULL if paddle_tpu is already
 * importable). No-op when called from inside a running interpreter
 * (e.g. a ctypes host). Returns 0 on success. */
int PD_Init(const char* repo_root);

/* Load an AOT inference artifact saved by paddle.static.save_inference_model
 * (model_prefix as in Config(prefix)). NULL on failure (see PD_LastError). */
PD_Predictor* PD_PredictorCreate(const char* model_prefix);

/* Copy a float32 input into the named input handle. shape has ndim dims. */
int PD_PredictorSetInputFloat(PD_Predictor* p, const char* name,
                              const float* data, const int64_t* shape,
                              int ndim);

/* Execute. Returns 0 on success. */
int PD_PredictorRun(PD_Predictor* p);

/* Number of elements of the named output (after Run). Negative on error. */
int64_t PD_PredictorOutputNumel(PD_Predictor* p, const char* name);

/* Output rank and shape. shape must hold at least 8 entries. */
int PD_PredictorOutputShape(PD_Predictor* p, const char* name,
                            int64_t* shape, int* ndim);

/* Copy the named float32 output into buf (buf_elems capacity). */
int PD_PredictorGetOutputFloat(PD_Predictor* p, const char* name, float* buf,
                               int64_t buf_elems);

/* First input/output names (convenience, single-io models). Returned pointer
 * is owned by the predictor and valid until the next call. */
const char* PD_PredictorInputName(PD_Predictor* p, int index);
const char* PD_PredictorOutputName(PD_Predictor* p, int index);

void PD_PredictorDestroy(PD_Predictor* p);

/* Last error message (thread-local, empty string if none). */
const char* PD_LastError(void);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_C_H_ */
