// Host-embedding PS kernels — the reference's memory_sparse_table.cc /
// sparse_sgd_rule.cc hot path (batched pull gather, SelectedRows-style
// sparse optimizer scatter, duplicate-id grad merge) as native multi-
// threaded routines over the RAM/memmap row store.
//
// Bit-exactness contract with the numpy fallback
// (incubate/host_embedding.py):
//   * pte_unique matches np.unique(ids, return_inverse=True): sorted
//     unique ids, int64 inverse.
//   * pte_gather is a row memcpy — trivially exact.
//   * pte_merge sums duplicate rows IN INPUT ORDER with float32 adds,
//     matching np.add.at's unbuffered in-order scalar loop. Threading
//     partitions by DESTINATION row (each output row is accumulated by
//     exactly one thread, still in input order), so the result is
//     deterministic and thread-count independent.
//   * pte_sgd is elementwise float32 (row - (float)lr * g), the same IEEE
//     ops numpy performs.
//   * pte_adagrad accumulates each row's sum(g^2) as a SEQUENTIAL double
//     sum (the fallback mirrors this with a float64 cumsum, which forces
//     numpy into the same sequential order), then applies the float32
//     rowwise rule.
//
// C ABI (pte_*) consumed via ctypes; every call validates ids against
// [0, nrows) and returns -1 instead of faulting on a bad id.
#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t z) {
  z += 0x9E3779B97F4A7C15ull;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

// Persistent worker pool: per-call std::thread spawn costs ~50us/thread,
// which would eat the entire win on millisecond-scale batches. Lazily
// started detached daemon workers park on a condition variable between
// calls; one batch job (fn over thread indices 1..T-1, caller runs 0) at a
// time, enforced by run_mu_ — the Python layer's trainer and PS-worker
// threads DO call kernels concurrently (they serialize on different
// locks), and an unserialized second run() would overwrite fn_/want_
// mid-job.
class Pool {
 public:
  static Pool& get() {
    // intentionally leaked: a static destructor would tear down the mutex/
    // condvar while detached workers still wait on them, hanging exit
    static Pool* p = new Pool();
    return *p;
  }

  // run fn(t) for t in [0, threads); fn(0) on the caller
  void run(int64_t threads, const std::function<void(int64_t)>& fn) {
    if (threads <= 1) {
      fn(0);
      return;
    }
    std::lock_guard<std::mutex> job(run_mu_);
    ensure(threads - 1);
    {
      std::unique_lock<std::mutex> lk(mu_);
      fn_ = &fn;
      want_ = threads - 1;
      done_ = 0;
      ++gen_;
      cv_.notify_all();
    }
    fn(0);
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&] { return done_ == want_; });
    fn_ = nullptr;
  }

 private:
  void ensure(int64_t n) {
    std::unique_lock<std::mutex> lk(mu_);
    while (static_cast<int64_t>(nworkers_) < n) {
      int64_t idx = nworkers_++;
      std::thread([this, idx] { worker(idx); }).detach();
    }
  }

  void worker(int64_t idx) {
    uint64_t seen = 0;
    for (;;) {
      const std::function<void(int64_t)>* fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return gen_ != seen && idx < want_; });
        seen = gen_;
        fn = fn_;
      }
      (*fn)(idx + 1);
      std::unique_lock<std::mutex> lk(mu_);
      if (++done_ == want_) done_cv_.notify_all();
    }
  }

  std::mutex run_mu_;  // serializes whole jobs across calling threads
  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  const std::function<void(int64_t)>* fn_ = nullptr;
  int64_t want_ = 0, done_ = 0, nworkers_ = 0;
  uint64_t gen_ = 0;
};

// run fn(t) for t in [0, threads) on the persistent pool
template <typename F>
void parallel_for_threads(int64_t threads, F fn) {
  Pool::get().run(threads, fn);
}

inline int64_t clamp_threads(int64_t nthreads, int64_t work_items) {
  int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 1;
  int64_t t = std::min(nthreads > 0 ? nthreads : 1, hw);
  // pool wakeup ~5us/thread: still not worth it for tiny batches
  if (work_items < (1 << 13)) return 1;
  return std::max<int64_t>(1, std::min(t, work_items / (1 << 11)));
}

// Hash-sharded unique index over a REUSED generation-stamped scratch table.
// A fresh hash table per call costs more than the hashing itself (8MB of
// page faults + clears per batch); instead one process-wide open-addressing
// table is kept warm and slots are validated by a generation stamp, so a
// new batch "clears" the table by bumping one counter.
//
// The table is split into power-of-two slot shards. Shard t dedups the ids
// whose hash lands in its range (every occurrence of an id belongs to
// exactly one shard, probes stay inside the shard, so no cross-thread
// writes), then the shard slots are repointed at the ids' SORTED positions.
// Result — sorted uniq + id->pos lookups — is deterministic and
// thread-count independent.
//
// NOT reentrant: callers serialize (the Python side holds the table lock;
// the embedding layer's prefetch worker and trainer thread both route
// through it). A mutex enforces that assumption cheaply.
struct ShardedIndex {
  std::vector<int64_t> keys;
  std::vector<int64_t> vals;
  std::vector<uint32_t> stamp;
  std::vector<std::vector<int64_t>> local;  // per-shard uniq collectors
  std::vector<int64_t> uniq;                // sorted
  std::vector<int64_t> pos_scratch;         // merge's destination positions
  uint32_t gen = 0;
  uint64_t cap = 0;
  int64_t nshards = 1;
  uint64_t shard_mask = 0;
  std::mutex mu;

  static ShardedIndex& get() {
    static ShardedIndex* s = new ShardedIndex();  // leaked, like the pool
    return *s;
  }

  inline int64_t shard_of(uint64_t h) const {
    return static_cast<int64_t>(
        (static_cast<unsigned __int128>(h) * static_cast<uint64_t>(nshards)) >>
        64);
  }

  inline uint64_t first_slot(uint64_t h, int64_t shard) const {
    return static_cast<uint64_t>(shard) * (shard_mask + 1) + (h & shard_mask);
  }

  void reserve(int64_t n, int64_t threads) {
    int64_t shards = 1;
    while (shards * 2 <= threads) shards *= 2;
    uint64_t want = 16;
    // 4x headroom absorbs zipf-skewed shard occupancy without growth
    while (want < static_cast<uint64_t>(n) * 4) want <<= 1;
    if (want > cap || shards != nshards) {
      cap = std::max(want, cap);
      nshards = shards;
      shard_mask = cap / nshards - 1;
      keys.resize(cap);
      vals.resize(cap);
      stamp.assign(cap, 0);
      gen = 0;
      local.resize(nshards);
    }
    if (++gen == 0) {  // stamp wraparound: one real clear every 2^32 calls
      std::fill(stamp.begin(), stamp.end(), 0);
      gen = 1;
    }
  }

  // dedup + sort + repoint; false on a negative id
  bool build(const int64_t* ids, int64_t n, int64_t nthreads) {
    reserve(n, clamp_threads(nthreads, n));
    std::atomic<bool> bad{false};
    std::atomic<bool> full{false};
    parallel_for_threads(nshards, [&](int64_t t) {
      std::vector<int64_t>& u = local[t];
      u.clear();
      for (int64_t i = 0; i < n; ++i) {
        int64_t id = ids[i];
        if (id < 0) {
          bad.store(true, std::memory_order_relaxed);
          return;
        }
        uint64_t h = splitmix64(static_cast<uint64_t>(id));
        if (shard_of(h) != t) continue;
        uint64_t base = static_cast<uint64_t>(t) * (shard_mask + 1);
        uint64_t s = first_slot(h, t);
        uint64_t probes = 0;
        while (stamp[s] == gen && keys[s] != id) {
          s = base + ((s - base + 1) & shard_mask);
          if (++probes > shard_mask) {  // shard full (extreme hash skew)
            full.store(true, std::memory_order_relaxed);
            return;
          }
        }
        if (stamp[s] != gen) {
          stamp[s] = gen;
          keys[s] = id;
          u.push_back(id);
        }
      }
    });
    if (bad.load()) return false;
    if (full.load()) {
      // retry with double the capacity; terminates (cap grows past 8n,
      // where a full shard is impossible even fully skewed)
      cap *= 2;
      shard_mask = cap / nshards - 1;
      keys.resize(cap);
      vals.resize(cap);
      stamp.assign(cap, 0);
      gen = 1;
      return build(ids, n, nthreads);
    }
    size_t nu = 0;
    for (auto& u : local) nu += u.size();
    uniq.clear();
    uniq.reserve(nu);
    for (auto& u : local) uniq.insert(uniq.end(), u.begin(), u.end());
    std::sort(uniq.begin(), uniq.end());
    // repoint each shard's slots at the sorted positions
    parallel_for_threads(nshards, [&](int64_t t) {
      for (int64_t p = 0; p < static_cast<int64_t>(uniq.size()); ++p) {
        uint64_t h = splitmix64(static_cast<uint64_t>(uniq[p]));
        if (shard_of(h) != t) continue;
        uint64_t base = static_cast<uint64_t>(t) * (shard_mask + 1);
        uint64_t s = first_slot(h, t);
        while (keys[s] != uniq[p]) s = base + ((s - base + 1) & shard_mask);
        vals[s] = p;
      }
    });
    return true;
  }

  inline int64_t pos_of(int64_t id) const {
    uint64_t h = splitmix64(static_cast<uint64_t>(id));
    int64_t t = shard_of(h);
    uint64_t base = static_cast<uint64_t>(t) * (shard_mask + 1);
    uint64_t s = first_slot(h, t);
    while (stamp[s] == gen && keys[s] != id)
      s = base + ((s - base + 1) & shard_mask);
    return stamp[s] == gen ? vals[s] : -1;
  }
};

}  // namespace

extern "C" {

// sorted unique + inverse (np.unique(ids, return_inverse=True) semantics).
// uniq_out needs capacity n, inv_out capacity n. Returns n_uniq, -1 on a
// negative id.
int64_t pte_unique(const int64_t* ids, int64_t n, int64_t* uniq_out,
                   int64_t* inv_out, int64_t nthreads) {
  if (n <= 0) return 0;
  ShardedIndex& idx = ShardedIndex::get();
  std::lock_guard<std::mutex> lk(idx.mu);
  if (!idx.build(ids, n, nthreads)) return -1;
  std::memcpy(uniq_out, idx.uniq.data(), idx.uniq.size() * sizeof(int64_t));
  int64_t threads = clamp_threads(nthreads, n);
  parallel_for_threads(threads, [&](int64_t t) {
    int64_t lo = n * t / threads, hi = n * (t + 1) / threads;
    for (int64_t i = lo; i < hi; ++i) inv_out[i] = idx.pos_of(ids[i]);
  });
  return static_cast<int64_t>(idx.uniq.size());
}

// out[i] = table[ids[i]] (row memcpy, parallel over rows)
int pte_gather_f32(const float* table, int64_t nrows, int64_t dim,
                   const int64_t* ids, int64_t n, float* out,
                   int64_t nthreads) {
  for (int64_t i = 0; i < n; ++i)
    if (ids[i] < 0 || ids[i] >= nrows) return -1;
  int64_t threads = clamp_threads(nthreads, n * dim / 64);
  size_t row_bytes = static_cast<size_t>(dim) * sizeof(float);
  parallel_for_threads(threads, [&](int64_t t) {
    int64_t lo = n * t / threads, hi = n * (t + 1) / threads;
    for (int64_t i = lo; i < hi; ++i)
      std::memcpy(out + i * dim, table + ids[i] * dim, row_bytes);
  });
  return 0;
}

// table[ids[i]] -= (float)lr * grad[i]  (ids must be unique: rows are
// touched in parallel)
int pte_sgd_f32(float* table, int64_t nrows, int64_t dim, const int64_t* ids,
                int64_t n, const float* grad, float lr, int64_t nthreads) {
  for (int64_t i = 0; i < n; ++i)
    if (ids[i] < 0 || ids[i] >= nrows) return -1;
  int64_t threads = clamp_threads(nthreads, n * dim / 16);
  parallel_for_threads(threads, [&](int64_t t) {
    int64_t lo = n * t / threads, hi = n * (t + 1) / threads;
    for (int64_t i = lo; i < hi; ++i) {
      float* row = table + ids[i] * dim;
      const float* g = grad + i * dim;
      for (int64_t j = 0; j < dim; ++j) row[j] = row[j] - lr * g[j];
    }
  });
  return 0;
}

// rowwise Adagrad (reference sparse_sgd_rule.cc SparseAdaGradSGDRule):
//   accum[id] += mean(g^2)   (sequential double sum -> float)
//   table[id] -= (lr / (sqrt(accum[id]) + eps)) * g
int pte_adagrad_f32(float* table, float* accum, int64_t nrows, int64_t dim,
                    const int64_t* ids, int64_t n, const float* grad, float lr,
                    float eps, int64_t nthreads) {
  for (int64_t i = 0; i < n; ++i)
    if (ids[i] < 0 || ids[i] >= nrows) return -1;
  int64_t threads = clamp_threads(nthreads, n * dim / 16);
  parallel_for_threads(threads, [&](int64_t t) {
    int64_t lo = n * t / threads, hi = n * (t + 1) / threads;
    for (int64_t i = lo; i < hi; ++i) {
      const float* g = grad + i * dim;
      double s = 0.0;
      for (int64_t j = 0; j < dim; ++j)
        s += static_cast<double>(g[j]) * static_cast<double>(g[j]);
      float g2 = static_cast<float>(s / static_cast<double>(dim));
      float a = accum[ids[i]] + g2;
      accum[ids[i]] = a;
      float scale = lr / (std::sqrt(a) + eps);
      float* row = table + ids[i] * dim;
      for (int64_t j = 0; j < dim; ++j) row[j] = row[j] - scale * g[j];
    }
  });
  return 0;
}

// Coalesce duplicate-id sparse grads: uniq_out = sorted unique ids,
// merged_out[pos] = sum of grads[i] over ids[i] == uniq_out[pos], summed in
// INPUT ORDER with float32 adds (np.add.at semantics). Parallel over
// destination rows. Returns n_uniq, -1 on a negative id.
int64_t pte_merge_f32(const int64_t* ids, int64_t n, const float* grads,
                      int64_t dim, int64_t* uniq_out, float* merged_out,
                      int64_t nthreads) {
  if (n <= 0) return 0;
  ShardedIndex& idx = ShardedIndex::get();
  std::lock_guard<std::mutex> lk(idx.mu);
  if (!idx.build(ids, n, nthreads)) return -1;
  int64_t nu = static_cast<int64_t>(idx.uniq.size());
  std::memcpy(uniq_out, idx.uniq.data(), nu * sizeof(int64_t));
  // precompute destination positions once (reused scratch, read-only below)
  idx.pos_scratch.resize(n);
  int64_t* pos = idx.pos_scratch.data();
  {
    int64_t threads = clamp_threads(nthreads, n);
    parallel_for_threads(threads, [&](int64_t t) {
      int64_t lo = n * t / threads, hi = n * (t + 1) / threads;
      for (int64_t i = lo; i < hi; ++i) pos[i] = idx.pos_of(ids[i]);
    });
  }
  int64_t threads = clamp_threads(nthreads, n * dim / 16);
  parallel_for_threads(threads, [&](int64_t t) {
    // thread t owns destination rows [lo, hi): every input row lands in
    // exactly one partition, zeroed then accumulated in input order
    int64_t lo = nu * t / threads, hi = nu * (t + 1) / threads;
    std::memset(merged_out + lo * dim, 0,
                static_cast<size_t>(hi - lo) * dim * sizeof(float));
    for (int64_t i = 0; i < n; ++i) {
      int64_t p = pos[i];
      if (p < lo || p >= hi) continue;
      float* dst = merged_out + p * dim;
      const float* g = grads + i * dim;
      for (int64_t j = 0; j < dim; ++j) dst[j] += g[j];
    }
  });
  return nu;
}

}  // extern "C"
