// Bounded MPMC blocking queue of byte buffers — the C++ core of the data
// pipeline. TPU-native equivalent of the reference's feed-path queue
// (paddle/fluid/operators/reader/blocking_queue.h,
//  lod_tensor_blocking_queue.h) and the BufferedReader's staging slots
// (operators/reader/buffered_reader.cc): producers (dataloader workers) copy
// collated batches in without holding the GIL; the consumer pops and hands
// the buffer to PJRT for async H2D.
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace {

struct Buffer {
  std::vector<uint8_t> data;
};

class BlockingQueue {
 public:
  explicit BlockingQueue(size_t capacity) : capacity_(capacity) {}

  // returns 0 on success, -1 if closed
  int Push(const uint8_t* bytes, size_t n) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return closed_ || q_.size() < capacity_; });
    if (closed_) return -1;
    q_.emplace_back();
    q_.back().data.assign(bytes, bytes + n);
    not_empty_.notify_one();
    return 0;
  }

  // returns size of popped buffer, 0 if closed-and-empty. Two-phase: Pop
  // reserves, CopyOut copies into caller storage, Release frees.
  int64_t PopSize() {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return closed_ || !q_.empty(); });
    if (q_.empty()) return 0;  // closed and drained
    return static_cast<int64_t>(q_.front().data.size());
  }

  int64_t PopInto(uint8_t* out, size_t out_cap) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return closed_ || !q_.empty(); });
    if (q_.empty()) return 0;
    Buffer b = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    size_t n = b.data.size();
    if (n > out_cap) return -1;
    std::memcpy(out, b.data.data(), n);
    return static_cast<int64_t>(n);
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  size_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return q_.size();
  }

 private:
  size_t capacity_;
  bool closed_ = false;
  std::deque<Buffer> q_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
};

}  // namespace

extern "C" {

void* ptq_create(int64_t capacity) { return new BlockingQueue(static_cast<size_t>(capacity)); }

int ptq_push(void* q, const uint8_t* bytes, int64_t n) {
  return static_cast<BlockingQueue*>(q)->Push(bytes, static_cast<size_t>(n));
}

int64_t ptq_pop_size(void* q) { return static_cast<BlockingQueue*>(q)->PopSize(); }

int64_t ptq_pop_into(void* q, uint8_t* out, int64_t cap) {
  return static_cast<BlockingQueue*>(q)->PopInto(out, static_cast<size_t>(cap));
}

void ptq_close(void* q) { static_cast<BlockingQueue*>(q)->Close(); }

int64_t ptq_size(void* q) { return static_cast<int64_t>(static_cast<BlockingQueue*>(q)->Size()); }

void ptq_destroy(void* q) { delete static_cast<BlockingQueue*>(q); }

}  // extern "C"
