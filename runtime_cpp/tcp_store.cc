// TCPStore — key/value rendezvous over TCP.
// TPU-native equivalent of the reference's torch-style store
// (paddle/fluid/distributed/store/tcp_store.{h,cc}, tcp_utils.cc) used for
// multi-host bootstrap; replaces the comm-id plumbing
// (platform/gen_comm_id_helper.cc) for anything the JAX coordination service
// doesn't cover (e.g. user-level barriers, elastic membership).
//
// Protocol (all little-endian):
//   request : op:u8 | klen:u32 | key | vlen:u32 | value
//   ops     : 0=SET 1=GET 2=ADD(value=i64 delta) 3=WAIT 4=DELETE
//   response: status:u8 (0 ok, 1 missing) | vlen:u32 | value
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

bool ReadFull(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port) {}

  bool Start() {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) return false;
    int one = 1;
    ::setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) return false;
    if (::listen(fd_, 128) != 0) return false;
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  void Stop() {
    stopping_ = true;
    if (fd_ >= 0) {
      ::shutdown(fd_, SHUT_RDWR);
      ::close(fd_);
      fd_ = -1;
    }
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : handlers_)
      if (t.joinable()) t.join();
  }

  ~StoreServer() { Stop(); }

 private:
  void AcceptLoop() {
    while (!stopping_) {
      int cfd = ::accept(fd_, nullptr, nullptr);
      if (cfd < 0) break;
      handlers_.emplace_back([this, cfd] { Handle(cfd); });
    }
  }

  void Handle(int cfd) {
    int one = 1;
    ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    while (!stopping_) {
      uint8_t op;
      uint32_t klen, vlen;
      if (!ReadFull(cfd, &op, 1) || !ReadFull(cfd, &klen, 4)) break;
      std::string key(klen, '\0');
      if (klen && !ReadFull(cfd, key.data(), klen)) break;
      if (!ReadFull(cfd, &vlen, 4)) break;
      std::string val(vlen, '\0');
      if (vlen && !ReadFull(cfd, val.data(), vlen)) break;

      uint8_t status = 0;
      std::string out;
      switch (op) {
        case 0: {  // SET
          std::lock_guard<std::mutex> lk(mu_);
          kv_[key] = val;
          cv_.notify_all();
          break;
        }
        case 1: {  // GET
          std::lock_guard<std::mutex> lk(mu_);
          auto it = kv_.find(key);
          if (it == kv_.end()) {
            status = 1;
          } else {
            out = it->second;
          }
          break;
        }
        case 2: {  // ADD
          int64_t delta = 0;
          std::memcpy(&delta, val.data(), std::min<size_t>(8, val.size()));
          std::lock_guard<std::mutex> lk(mu_);
          int64_t cur = 0;
          auto it = kv_.find(key);
          if (it != kv_.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string enc(8, '\0');
          std::memcpy(enc.data(), &cur, 8);
          kv_[key] = enc;
          out = enc;
          cv_.notify_all();
          break;
        }
        case 3: {  // WAIT (blocks until key exists)
          std::unique_lock<std::mutex> lk(mu_);
          cv_.wait(lk, [&] { return stopping_ || kv_.count(key) > 0; });
          if (stopping_) {
            status = 1;
          } else {
            out = kv_[key];
          }
          break;
        }
        case 4: {  // DELETE
          std::lock_guard<std::mutex> lk(mu_);
          kv_.erase(key);
          break;
        }
        default:
          status = 1;
      }
      uint32_t olen = static_cast<uint32_t>(out.size());
      if (!WriteFull(cfd, &status, 1) || !WriteFull(cfd, &olen, 4)) break;
      if (olen && !WriteFull(cfd, out.data(), olen)) break;
    }
    ::close(cfd);
  }

  int port_;
  int fd_ = -1;
  volatile bool stopping_ = false;
  std::thread accept_thread_;
  std::vector<std::thread> handlers_;
  std::map<std::string, std::string> kv_;
  std::mutex mu_;
  std::condition_variable cv_;
};

class StoreClient {
 public:
  bool Connect(const char* host, int port, int timeout_ms) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) return false;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return false;
    // retry-connect within timeout (server may start later)
    int waited = 0;
    while (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      ::close(fd_);
      if (waited >= timeout_ms) return false;
      ::usleep(100 * 1000);
      waited += 100;
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    }
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return true;
  }

  // returns status(0/1), fills out
  int Request(uint8_t op, const std::string& key, const std::string& val, std::string* out) {
    uint32_t klen = static_cast<uint32_t>(key.size());
    uint32_t vlen = static_cast<uint32_t>(val.size());
    if (!WriteFull(fd_, &op, 1) || !WriteFull(fd_, &klen, 4)) return -1;
    if (klen && !WriteFull(fd_, key.data(), klen)) return -1;
    if (!WriteFull(fd_, &vlen, 4)) return -1;
    if (vlen && !WriteFull(fd_, val.data(), vlen)) return -1;
    uint8_t status;
    uint32_t olen;
    if (!ReadFull(fd_, &status, 1) || !ReadFull(fd_, &olen, 4)) return -1;
    out->resize(olen);
    if (olen && !ReadFull(fd_, out->data(), olen)) return -1;
    return status;
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  int fd_ = -1;
};

}  // namespace

extern "C" {

void* pts_server_create(int port) {
  auto* s = new StoreServer(port);
  if (!s->Start()) {
    delete s;
    return nullptr;
  }
  return s;
}

void pts_server_destroy(void* s) { delete static_cast<StoreServer*>(s); }

void* pts_client_create(const char* host, int port, int timeout_ms) {
  auto* c = new StoreClient();
  if (!c->Connect(host, port, timeout_ms)) {
    delete c;
    return nullptr;
  }
  return c;
}

void pts_client_destroy(void* c) { delete static_cast<StoreClient*>(c); }

// returns status; out buffer must hold out_cap; actual length in *out_len
int pts_request(void* c, int op, const char* key, const uint8_t* val, int64_t vlen,
                uint8_t* out, int64_t out_cap, int64_t* out_len) {
  std::string o;
  int status = static_cast<StoreClient*>(c)->Request(
      static_cast<uint8_t>(op), key, std::string(reinterpret_cast<const char*>(val), static_cast<size_t>(vlen)), &o);
  if (status < 0) return -1;
  if (static_cast<int64_t>(o.size()) > out_cap) return -2;
  std::memcpy(out, o.data(), o.size());
  *out_len = static_cast<int64_t>(o.size());
  return status;
}

}  // extern "C"
