// Host staging arena — aligned slab allocator with freelist reuse.
// TPU-native stand-in for the reference's host allocators
// (paddle/fluid/memory/allocation/auto_growth_best_fit_allocator.h best-fit
// with growth; pinned allocator for H2D staging): batches are collated into
// arena slabs (64-byte aligned, madvise-friendly) so repeated steps reuse
// identical-size buffers without malloc churn before PJRT H2D transfer.
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

namespace {

class Arena {
 public:
  explicit Arena(size_t align) : align_(align) {}

  ~Arena() {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : free_)
      for (void* p : kv.second) std::free(p);
    for (auto& kv : live_) std::free(kv.first);
  }

  void* Alloc(size_t n) {
    size_t rounded = RoundUp(n);
    std::lock_guard<std::mutex> lk(mu_);
    auto it = free_.find(rounded);
    if (it != free_.end() && !it->second.empty()) {
      void* p = it->second.back();
      it->second.pop_back();
      live_[p] = rounded;
      reused_++;
      return p;
    }
    void* p = nullptr;
    if (posix_memalign(&p, align_, rounded) != 0) return nullptr;
    live_[p] = rounded;
    allocated_ += rounded;
    return p;
  }

  void Free(void* p) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = live_.find(p);
    if (it == live_.end()) return;
    free_[it->second].push_back(p);
    live_.erase(it);
  }

  int64_t BytesAllocated() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int64_t>(allocated_);
  }

  int64_t ReuseCount() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int64_t>(reused_);
  }

 private:
  size_t RoundUp(size_t n) {
    // size-class rounding: next power of two above 4KiB, else page-rounded
    size_t page = 4096;
    if (n <= page) return page;
    size_t p = page;
    while (p < n) p <<= 1;
    return p;
  }

  size_t align_;
  std::mutex mu_;
  std::map<size_t, std::vector<void*>> free_;
  std::map<void*, size_t> live_;
  size_t allocated_ = 0;
  size_t reused_ = 0;
};

}  // namespace

extern "C" {

void* pta_create(int64_t align) { return new Arena(static_cast<size_t>(align)); }

void pta_destroy(void* a) { delete static_cast<Arena*>(a); }

void* pta_alloc(void* a, int64_t n) { return static_cast<Arena*>(a)->Alloc(static_cast<size_t>(n)); }

void pta_free(void* a, void* p) { static_cast<Arena*>(a)->Free(p); }

int64_t pta_bytes(void* a) { return static_cast<Arena*>(a)->BytesAllocated(); }

int64_t pta_reused(void* a) { return static_cast<Arena*>(a)->ReuseCount(); }

}  // extern "C"
