// Native BERT tokenizer — the reference's faster_tokenizer_op
// (paddle/fluid/operators/string/faster_tokenizer_op.cc) role: tokenization
// is host-side string work, native for throughput on the feed path.
//
// Pipeline (BasicTokenizer + WordPiece, matching the Python fallback in
// paddle_tpu/text/faster_tokenizer.py exactly):
//   1. UTF-8 iterate; drop control chars and U+FFFD; whitespace → ' '
//   2. optional ASCII lowercase
//   3. CJK ideographs get surrounding spaces (char-level tokens)
//   4. split on whitespace, then split punctuation into single tokens
//   5. WordPiece: greedy longest-match-first, continuations "##x", [UNK]
//      when nothing matches or the word exceeds 100 bytes
//
// C ABI (ptk_*) consumed via ctypes; ids written into caller buffers.
#include <cstdint>
#include <cstring>
#include <fstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Tokenizer {
  std::unordered_map<std::string, int64_t> vocab;
  bool lower = true;
  int64_t unk = 0;
};

inline bool is_ws(uint32_t c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r';
}

inline bool is_control(uint32_t c) {
  if (c == '\t' || c == '\n' || c == '\r') return false;
  return c < 0x20 || c == 0x7f;
}

inline bool is_cjk(uint32_t c) {
  return (c >= 0x4E00 && c <= 0x9FFF) || (c >= 0x3400 && c <= 0x4DBF) ||
         (c >= 0x20000 && c <= 0x2A6DF) || (c >= 0x2A700 && c <= 0x2B73F) ||
         (c >= 0x2B740 && c <= 0x2B81F) || (c >= 0x2B820 && c <= 0x2CEAF) ||
         (c >= 0xF900 && c <= 0xFAFF) || (c >= 0x2F800 && c <= 0x2FA1F);
}

inline bool is_punct(uint32_t c) {
  // ASCII punctuation ranges (BERT treats them all as split points) plus
  // general unicode punctuation blocks
  if ((c >= 33 && c <= 47) || (c >= 58 && c <= 64) || (c >= 91 && c <= 96) ||
      (c >= 123 && c <= 126))
    return true;
  return (c >= 0x2000 && c <= 0x206F) || (c >= 0x3000 && c <= 0x303F) ||
         (c >= 0xFF00 && c <= 0xFF0F) || (c >= 0xFF1A && c <= 0xFF20) ||
         (c >= 0xFF3B && c <= 0xFF40) || (c >= 0xFF5B && c <= 0xFF65);
}

// decode one UTF-8 code point at s[i]; advances i
inline uint32_t next_cp(const std::string& s, size_t& i) {
  unsigned char b = s[i];
  uint32_t cp = 0;
  int extra = 0;
  if (b < 0x80) {
    cp = b;
  } else if ((b >> 5) == 0x6) {
    cp = b & 0x1F; extra = 1;
  } else if ((b >> 4) == 0xE) {
    cp = b & 0x0F; extra = 2;
  } else if ((b >> 3) == 0x1E) {
    cp = b & 0x07; extra = 3;
  } else {
    ++i;
    return 0xFFFD;
  }
  size_t start = i++;
  for (int k = 0; k < extra; ++k) {
    if (i >= s.size() || (static_cast<unsigned char>(s[i]) >> 6) != 0x2) {
      i = start + 1;
      return 0xFFFD;
    }
    cp = (cp << 6) | (static_cast<unsigned char>(s[i]) & 0x3F);
    ++i;
  }
  return cp;
}

inline void append_cp(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out += static_cast<char>(cp);
  } else if (cp < 0x800) {
    out += static_cast<char>(0xC0 | (cp >> 6));
    out += static_cast<char>(0x80 | (cp & 0x3F));
  } else if (cp < 0x10000) {
    out += static_cast<char>(0xE0 | (cp >> 12));
    out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
    out += static_cast<char>(0x80 | (cp & 0x3F));
  } else {
    out += static_cast<char>(0xF0 | (cp >> 18));
    out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
    out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
    out += static_cast<char>(0x80 | (cp & 0x3F));
  }
}

std::vector<std::string> basic_tokenize(const Tokenizer& t, const std::string& text) {
  std::string clean;
  clean.reserve(text.size() * 2);
  size_t i = 0;
  while (i < text.size()) {
    uint32_t cp = next_cp(text, i);
    if (cp == 0 || cp == 0xFFFD || is_control(cp)) continue;
    if (is_ws(cp)) {
      clean += ' ';
      continue;
    }
    if (t.lower && cp >= 'A' && cp <= 'Z') cp += 32;
    if (is_cjk(cp)) {
      clean += ' ';
      append_cp(clean, cp);
      clean += ' ';
      continue;
    }
    if (is_punct(cp)) {
      clean += ' ';
      append_cp(clean, cp);
      clean += ' ';
      continue;
    }
    append_cp(clean, cp);
  }
  std::vector<std::string> out;
  size_t pos = 0;
  while (pos < clean.size()) {
    while (pos < clean.size() && clean[pos] == ' ') ++pos;
    size_t end = pos;
    while (end < clean.size() && clean[end] != ' ') ++end;
    if (end > pos) out.emplace_back(clean.substr(pos, end - pos));
    pos = end;
  }
  return out;
}

void wordpiece(const Tokenizer& t, const std::string& word,
               std::vector<int64_t>* ids) {
  if (word.size() > 100) {
    ids->push_back(t.unk);
    return;
  }
  // substring matches may only start/end at CODEPOINT boundaries — byte
  // slicing could split a multi-byte char and diverge from the python twin
  std::vector<size_t> bounds;
  for (size_t i = 0; i < word.size();) {
    bounds.push_back(i);
    next_cp(word, i);
  }
  bounds.push_back(word.size());
  std::vector<int64_t> pieces;
  size_t start = 0;  // index into bounds
  size_t n = bounds.size() - 1;  // number of codepoints
  while (start < n) {
    size_t end = n;
    int64_t cur = -1;
    while (end > start) {
      std::string sub =
          word.substr(bounds[start], bounds[end] - bounds[start]);
      if (start > 0) sub = "##" + sub;
      auto it = t.vocab.find(sub);
      if (it != t.vocab.end()) {
        cur = it->second;
        break;
      }
      --end;
    }
    if (cur < 0) {
      ids->push_back(t.unk);
      return;
    }
    pieces.push_back(cur);
    start = end;
  }
  ids->insert(ids->end(), pieces.begin(), pieces.end());
}

}  // namespace

extern "C" {

void* ptk_create(const char* vocab_path, int do_lower_case) {
  std::ifstream f(vocab_path);
  if (!f.good()) return nullptr;
  auto* t = new Tokenizer();
  t->lower = do_lower_case != 0;
  std::string line;
  int64_t idx = 0;
  while (std::getline(f, line)) {
    while (!line.empty() && (line.back() == '\n' || line.back() == '\r'))
      line.pop_back();
    t->vocab.emplace(line, idx++);
  }
  auto unk = t->vocab.find("[UNK]");
  t->unk = unk != t->vocab.end() ? unk->second : 0;
  return t;
}

void ptk_destroy(void* h) { delete static_cast<Tokenizer*>(h); }

int64_t ptk_vocab_size(void* h) {
  return static_cast<int64_t>(static_cast<Tokenizer*>(h)->vocab.size());
}

int64_t ptk_token_id(void* h, const char* token) {
  auto& t = *static_cast<Tokenizer*>(h);
  auto it = t.vocab.find(token);
  return it != t.vocab.end() ? it->second : -1;
}

// tokenize text into ids (no special tokens); returns count written (<= cap)
int64_t ptk_encode(void* h, const char* text, int64_t* out, int64_t cap) {
  auto& t = *static_cast<Tokenizer*>(h);
  std::vector<int64_t> ids;
  for (const auto& w : basic_tokenize(t, text)) wordpiece(t, w, &ids);
  int64_t n = static_cast<int64_t>(ids.size());
  if (n > cap) n = cap;
  std::memcpy(out, ids.data(), n * sizeof(int64_t));
  return n;
}

}  // extern "C"
