// Standalone C++ host consuming the paddle_tpu C inference ABI
// (paddle_tpu_c.h) from OUTSIDE Python — the role of the reference's
// second-language wrapper over the C API (inference/goapi/: a Go host
// driving capi_exp; Go tooling isn't in this image, so the proof-of-ABI
// consumer is a plain C++ binary that embeds the runtime via PD_Init).
//
// Usage: capi_demo <model_prefix> <repo_root> <d0> [d1 ...]
// Feeds a deterministic ramp input, runs, prints one JSON line with the
// output count / checksum / head so the test harness can verify values.
#include "paddle_tpu_c.h"

#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <vector>

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr, "usage: %s <model_prefix> <repo_root> <d0> [d1 ...]\n",
                 argv[0]);
    return 2;
  }
  const char* prefix = argv[1];
  const char* repo_root = argv[2];
  std::vector<int64_t> shape;
  int64_t numel = 1;
  for (int i = 3; i < argc; ++i) {
    shape.push_back(std::atoll(argv[i]));
    numel *= shape.back();
  }

  if (PD_Init(repo_root) != 0) {
    std::fprintf(stderr, "PD_Init failed: %s\n", PD_LastError());
    return 1;
  }
  PD_Predictor* p = PD_PredictorCreate(prefix);
  if (!p) {
    std::fprintf(stderr, "create failed: %s\n", PD_LastError());
    return 1;
  }
  const char* in_name = PD_PredictorInputName(p, 0);

  // deterministic ramp, mirrored by the python test
  std::vector<float> x(static_cast<size_t>(numel));
  for (int64_t i = 0; i < numel; ++i)
    x[static_cast<size_t>(i)] = static_cast<float>(i % 17) * 0.25f - 2.0f;

  if (PD_PredictorSetInputFloat(p, in_name, x.data(), shape.data(),
                                static_cast<int>(shape.size())) != 0) {
    std::fprintf(stderr, "set input failed: %s\n", PD_LastError());
    return 1;
  }
  if (PD_PredictorRun(p) != 0) {
    std::fprintf(stderr, "run failed: %s\n", PD_LastError());
    return 1;
  }
  const char* out_name = PD_PredictorOutputName(p, 0);
  int64_t n_out = PD_PredictorOutputNumel(p, out_name);
  if (n_out < 0) {
    std::fprintf(stderr, "output numel failed: %s\n", PD_LastError());
    return 1;
  }
  std::vector<float> y(static_cast<size_t>(n_out));
  if (PD_PredictorGetOutputFloat(p, out_name, y.data(), n_out) != 0) {
    std::fprintf(stderr, "get output failed: %s\n", PD_LastError());
    return 1;
  }
  double sum = 0.0;
  for (float v : y) sum += v;
  std::printf("{\"numel\": %" PRId64 ", \"sum\": %.6f, \"head\": [", n_out, sum);
  for (int i = 0; i < 4 && i < n_out; ++i)
    std::printf("%s%.6f", i ? ", " : "", y[static_cast<size_t>(i)]);
  std::printf("]}\n");
  PD_PredictorDestroy(p);
  return 0;
}
