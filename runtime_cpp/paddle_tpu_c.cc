// C inference API implementation — embeds (or attaches to) the Python
// runtime and drives paddle_tpu.inference.Predictor through the CPython API.
// See paddle_tpu_c.h for the contract; role parity with the reference's
// capi_exp/pd_inference_api (C ABI over the predictor lifecycle).
#include "paddle_tpu_c.h"

#include <Python.h>

#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

void set_py_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = where;
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      msg += ": ";
      msg += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

struct GIL {
  PyGILState_STATE st;
  GIL() : st(PyGILState_Ensure()) {}
  ~GIL() { PyGILState_Release(st); }
};

}  // namespace

struct PD_Predictor {
  PyObject* predictor = nullptr;   // paddle_tpu.inference.Predictor
  std::string scratch_name;        // storage for returned name pointers
};

extern "C" {

int PD_Init(const char* repo_root) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
  }
  GIL gil;
  if (repo_root != nullptr) {
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    PyObject* root = PyUnicode_FromString(repo_root);
    if (sys_path != nullptr && root != nullptr) {
      PyList_Insert(sys_path, 0, root);
    }
    Py_XDECREF(root);
  }
  return 0;
}

PD_Predictor* PD_PredictorCreate(const char* model_prefix) {
  if (!Py_IsInitialized()) {
    PD_Init(nullptr);
  }
  GIL gil;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (mod == nullptr) {
    set_py_error("import paddle_tpu.inference");
    return nullptr;
  }
  PyObject* cfg_cls = PyObject_GetAttrString(mod, "Config");
  PyObject* create = PyObject_GetAttrString(mod, "create_predictor");
  PyObject* cfg = cfg_cls ? PyObject_CallFunction(cfg_cls, "s", model_prefix) : nullptr;
  PyObject* pred = (create && cfg) ? PyObject_CallFunctionObjArgs(create, cfg, nullptr) : nullptr;
  Py_XDECREF(cfg_cls);
  Py_XDECREF(create);
  Py_XDECREF(cfg);
  Py_DECREF(mod);
  if (pred == nullptr) {
    set_py_error("create_predictor");
    return nullptr;
  }
  auto* p = new PD_Predictor();
  p->predictor = pred;
  return p;
}

static PyObject* get_handle(PD_Predictor* p, const char* name, bool input) {
  PyObject* h = PyObject_CallMethod(
      p->predictor, input ? "get_input_handle" : "get_output_handle", "s", name);
  if (h == nullptr) set_py_error("get_handle");
  return h;
}

int PD_PredictorSetInputFloat(PD_Predictor* p, const char* name,
                              const float* data, const int64_t* shape,
                              int ndim) {
  GIL gil;
  int64_t numel = 1;
  for (int i = 0; i < ndim; ++i) numel *= shape[i];
  PyObject* np = PyImport_ImportModule("numpy");
  if (np == nullptr) { set_py_error("import numpy"); return -1; }
  // build numpy array via frombuffer(bytes).reshape(shape).copy()
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), numel * sizeof(float));
  PyObject* arr = bytes ? PyObject_CallMethod(np, "frombuffer", "Os", bytes, "float32") : nullptr;
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SetItem(shp, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* reshaped = arr ? PyObject_CallMethod(arr, "reshape", "O", shp) : nullptr;
  PyObject* owned = reshaped ? PyObject_CallMethod(reshaped, "copy", nullptr) : nullptr;
  Py_XDECREF(bytes);
  Py_XDECREF(arr);
  Py_XDECREF(shp);
  Py_XDECREF(reshaped);
  Py_DECREF(np);
  if (owned == nullptr) { set_py_error("build input array"); return -1; }
  PyObject* h = get_handle(p, name, true);
  PyObject* r = h ? PyObject_CallMethod(h, "copy_from_cpu", "O", owned) : nullptr;
  Py_XDECREF(owned);
  Py_XDECREF(h);
  if (r == nullptr) { set_py_error("copy_from_cpu"); return -1; }
  Py_DECREF(r);
  return 0;
}

int PD_PredictorRun(PD_Predictor* p) {
  GIL gil;
  PyObject* r = PyObject_CallMethod(p->predictor, "run", nullptr);
  if (r == nullptr) { set_py_error("run"); return -1; }
  Py_DECREF(r);
  return 0;
}

static PyObject* output_numpy(PD_Predictor* p, const char* name) {
  PyObject* h = get_handle(p, name, false);
  if (h == nullptr) return nullptr;
  PyObject* arr = PyObject_CallMethod(h, "copy_to_cpu", nullptr);
  Py_DECREF(h);
  if (arr == nullptr) set_py_error("copy_to_cpu");
  return arr;
}

int64_t PD_PredictorOutputNumel(PD_Predictor* p, const char* name) {
  GIL gil;
  PyObject* arr = output_numpy(p, name);
  if (arr == nullptr) return -1;
  PyObject* size = PyObject_GetAttrString(arr, "size");
  int64_t n = size ? PyLong_AsLongLong(size) : -1;
  Py_XDECREF(size);
  Py_DECREF(arr);
  return n;
}

int PD_PredictorOutputShape(PD_Predictor* p, const char* name, int64_t* shape,
                            int* ndim) {
  GIL gil;
  PyObject* arr = output_numpy(p, name);
  if (arr == nullptr) return -1;
  PyObject* shp = PyObject_GetAttrString(arr, "shape");
  if (shp == nullptr) { Py_DECREF(arr); set_py_error("shape"); return -1; }
  Py_ssize_t n = PyTuple_Size(shp);
  if (n > 8) n = 8;
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape[i] = PyLong_AsLongLong(PyTuple_GetItem(shp, i));
  }
  *ndim = static_cast<int>(n);
  Py_DECREF(shp);
  Py_DECREF(arr);
  return 0;
}

int PD_PredictorGetOutputFloat(PD_Predictor* p, const char* name, float* buf,
                               int64_t buf_elems) {
  GIL gil;
  PyObject* arr = output_numpy(p, name);
  if (arr == nullptr) return -1;
  // float32 contiguous bytes
  PyObject* f32 = PyObject_CallMethod(arr, "astype", "s", "float32");
  PyObject* contig = f32 ? PyObject_CallMethod(f32, "ravel", nullptr) : nullptr;
  PyObject* bytes = contig ? PyObject_CallMethod(contig, "tobytes", nullptr) : nullptr;
  Py_XDECREF(f32);
  Py_XDECREF(contig);
  Py_DECREF(arr);
  if (bytes == nullptr) { set_py_error("tobytes"); return -1; }
  char* src = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(bytes, &src, &len);
  int64_t elems = len / static_cast<int64_t>(sizeof(float));
  if (elems > buf_elems) {
    Py_DECREF(bytes);
    set_error("output larger than buffer");
    return -1;
  }
  memcpy(buf, src, elems * sizeof(float));
  Py_DECREF(bytes);
  return 0;
}

static const char* io_name(PD_Predictor* p, int index, bool input) {
  GIL gil;
  PyObject* names = PyObject_CallMethod(
      p->predictor, input ? "get_input_names" : "get_output_names", nullptr);
  if (names == nullptr) { set_py_error("get_names"); return nullptr; }
  PyObject* item = PySequence_GetItem(names, index);
  Py_DECREF(names);
  if (item == nullptr) { set_py_error("name index"); return nullptr; }
  p->scratch_name = PyUnicode_AsUTF8(item);
  Py_DECREF(item);
  return p->scratch_name.c_str();
}

const char* PD_PredictorInputName(PD_Predictor* p, int index) {
  return io_name(p, index, true);
}

const char* PD_PredictorOutputName(PD_Predictor* p, int index) {
  return io_name(p, index, false);
}

void PD_PredictorDestroy(PD_Predictor* p) {
  if (p == nullptr) return;
  {
    GIL gil;
    Py_XDECREF(p->predictor);
  }
  delete p;
}

const char* PD_LastError(void) { return g_last_error.c_str(); }

}  // extern "C"
