// Host event recorder — fixed-size ring of (name_id, t_start, t_end, tid).
// TPU-native equivalent of the reference's HostTracer / HostEventRecorder
// (paddle/fluid/platform/profiler/host_event_recorder.h): RecordEvent
// push/pop with nanosecond timestamps, drained by the Python profiler into
// chrome-trace JSON. Lock-free per-slot via an atomic cursor.
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Event {
  uint32_t name_id;
  uint32_t tid;
  uint64_t t0;
  uint64_t t1;
};

// Structured span record: same fixed-width ring discipline as Event, plus
// the span/parent ids the Python span tracer assigns — the C++ side stays a
// dumb timing sink; nesting and attributes are reconstructed at export.
struct SpanEvent {
  uint32_t name_id;
  uint32_t tid;
  uint64_t t0;
  uint64_t t1;
  uint64_t span_id;
  uint64_t parent_id;
};

class Recorder {
 public:
  explicit Recorder(size_t capacity)
      : events_(capacity), cursor_(0), spans_(capacity), span_cursor_(0) {}

  uint32_t InternName(const char* name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = name_ids_.find(name);
    if (it != name_ids_.end()) return it->second;
    uint32_t id = static_cast<uint32_t>(names_.size());
    names_.emplace_back(name);
    name_ids_[name] = id;
    return id;
  }

  void Record(uint32_t name_id, uint32_t tid, uint64_t t0, uint64_t t1) {
    size_t i = cursor_.fetch_add(1, std::memory_order_relaxed) % events_.size();
    events_[i] = Event{name_id, tid, t0, t1};
  }

  // Copy out up to n events (most recent wraparound window); returns count.
  int64_t Drain(Event* out, size_t n) {
    size_t total = cursor_.load(std::memory_order_relaxed);
    size_t avail = total < events_.size() ? total : events_.size();
    size_t count = avail < n ? avail : n;
    for (size_t k = 0; k < count; ++k) out[k] = events_[(total - avail + k) % events_.size()];
    return static_cast<int64_t>(count);
  }

  void RecordSpan(uint32_t name_id, uint32_t tid, uint64_t t0, uint64_t t1,
                  uint64_t span_id, uint64_t parent_id) {
    size_t i = span_cursor_.fetch_add(1, std::memory_order_relaxed) % spans_.size();
    spans_[i] = SpanEvent{name_id, tid, t0, t1, span_id, parent_id};
  }

  int64_t DrainSpans(SpanEvent* out, size_t n) {
    size_t total = span_cursor_.load(std::memory_order_relaxed);
    size_t avail = total < spans_.size() ? total : spans_.size();
    size_t count = avail < n ? avail : n;
    for (size_t k = 0; k < count; ++k)
      out[k] = spans_[(total - avail + k) % spans_.size()];
    return static_cast<int64_t>(count);
  }

  const char* Name(uint32_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    return id < names_.size() ? names_[id].c_str() : "";
  }

  void Reset() {
    cursor_.store(0);
    span_cursor_.store(0);
  }

 private:
  std::vector<Event> events_;
  std::atomic<size_t> cursor_;
  std::vector<SpanEvent> spans_;
  std::atomic<size_t> span_cursor_;
  std::vector<std::string> names_;
  std::unordered_map<std::string, uint32_t> name_ids_;
  std::mutex mu_;
};

uint64_t NowNs() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

}  // namespace

extern "C" {

void* ptt_create(int64_t capacity) { return new Recorder(static_cast<size_t>(capacity)); }

void ptt_destroy(void* r) { delete static_cast<Recorder*>(r); }

uint32_t ptt_intern(void* r, const char* name) { return static_cast<Recorder*>(r)->InternName(name); }

uint64_t ptt_now_ns() { return NowNs(); }

void ptt_record(void* r, uint32_t name_id, uint32_t tid, uint64_t t0, uint64_t t1) {
  static_cast<Recorder*>(r)->Record(name_id, tid, t0, t1);
}

// out layout per event: name_id u32 | tid u32 | t0 u64 | t1 u64 (24 bytes)
int64_t ptt_drain(void* r, uint8_t* out, int64_t max_events) {
  std::vector<Event> tmp(static_cast<size_t>(max_events));
  int64_t n = static_cast<Recorder*>(r)->Drain(tmp.data(), tmp.size());
  std::memcpy(out, tmp.data(), static_cast<size_t>(n) * sizeof(Event));
  return n;
}

void ptt_span_record(void* r, uint32_t name_id, uint32_t tid, uint64_t t0,
                     uint64_t t1, uint64_t span_id, uint64_t parent_id) {
  static_cast<Recorder*>(r)->RecordSpan(name_id, tid, t0, t1, span_id, parent_id);
}

// out layout per span: name_id u32 | tid u32 | t0 u64 | t1 u64 | span_id u64
// | parent_id u64 (40 bytes)
int64_t ptt_span_drain(void* r, uint8_t* out, int64_t max_spans) {
  std::vector<SpanEvent> tmp(static_cast<size_t>(max_spans));
  int64_t n = static_cast<Recorder*>(r)->DrainSpans(tmp.data(), tmp.size());
  std::memcpy(out, tmp.data(), static_cast<size_t>(n) * sizeof(SpanEvent));
  return n;
}

const char* ptt_name(void* r, uint32_t id) { return static_cast<Recorder*>(r)->Name(id); }

void ptt_reset(void* r) { static_cast<Recorder*>(r)->Reset(); }

}  // extern "C"
