"""Benchmark harness — prints ONE JSON line for the driver.

Flagship config: GPT (BASELINE.md north star is GPT-3 1.3B on a v4-32 pod;
single-chip bench runs a ~350M-parameter GPT at seq 1024 in bf16 through the
fused compiled train step). Metric: tokens/sec/chip.

The reference publishes no in-tree numbers (BASELINE.md) — vs_baseline is
reported against this project's own recorded best (bench_baseline.json),
1.0 on first run.
"""
from __future__ import annotations

import json
import os
import sys
import time


def main():
    t_start = time.time()
    import numpy as np
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    if on_tpu:
        cfg = GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
            max_position_embeddings=1024, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq, steps = 8, 1024, 10
    else:  # smoke fallback (driver runs on real TPU)
        cfg = GPTConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            max_position_embeddings=256, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq, steps = 8, 256, 10

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.bfloat16()  # MXU-native dtype
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    def loss_fn(m, ids, labels):
        return m.loss(ids, labels)

    step = paddle.jit.compile_train_step(model, loss_fn, opt)

    rng = np.random.RandomState(0)

    def make_batch():
        ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
        labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
        return ids, labels

    ids, labels = make_batch()
    # warmup / compile
    loss = step(ids, labels)
    loss2 = step(ids, labels)
    float(loss2.item())

    t0 = time.time()
    for _ in range(steps):
        loss = step(ids, labels)
    final = float(loss.item())  # forces sync
    dt = time.time() - t0

    tokens_per_sec = batch * seq * steps / dt
    n_params = sum(p.size for p in model.parameters())

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")
    vs_baseline = 1.0
    try:
        platform = jax.devices()[0].platform
        best = None
        if os.path.exists(baseline_path):
            base = json.load(open(baseline_path))
            if base.get("value") and base.get("platform") == platform:
                best = float(base["value"])
                vs_baseline = tokens_per_sec / best
        if on_tpu and (best is None or tokens_per_sec > best):
            # ratchet: the recorded best only ever goes up, so a future
            # regression is always visible as vs_baseline < 1.0
            json.dump(
                {"value": tokens_per_sec, "unit": "tokens/sec/chip", "platform": platform},
                open(baseline_path, "w"),
            )
    except Exception:
        pass

    print(
        json.dumps(
            {
                "metric": f"GPT-{n_params/1e6:.0f}M bf16 train throughput (b{batch}xs{seq}, fused step)",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(vs_baseline, 3),
                "loss": round(final, 4),
                "platform": jax.devices()[0].platform,
                "wall_s": round(time.time() - t_start, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
