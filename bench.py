"""Benchmark harness — prints ONE JSON line for the driver.

Primary metric (BASELINE.md north star): GPT bf16 fused-train-step
tokens/sec/chip (single-chip proxy of the GPT-3 1.3B hybrid config; ~355M at
seq 1024 fits one v5e chip). vs_baseline compares against this project's own
recorded best (bench_baseline.json — the reference publishes no in-tree
numbers), ratcheting upward on new bests.

The one JSON line also carries `extra_metrics` covering the other BASELINE
configs measurable on one chip: ResNet-50 AOT inference imgs/sec/chip via the
paddle_tpu.inference Predictor (the deployment path), LeNet eager steps/sec
(per-op dispatch overhead), and the GPT step's model-FLOPs utilization.
"""
from __future__ import annotations

import contextlib
import json
import os
import shutil
import signal
import sys
import tempfile
import time

_V5E_PEAK_BF16 = 197e12  # bf16 FLOP/s per v5e chip

# Wall-clock budget: the driver kills the whole process at its own timeout
# (rc=124, no JSON line — round 5 lost its bench this way). Stay under it:
# configs that would start past the budget are skipped, a config that runs
# long is interrupted via SIGALRM, and the JSON line always prints with
# whatever completed.
_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "600"))


class _BenchTimeout(BaseException):
    # BaseException: the alarm usually lands inside library code wrapped in
    # broad `except Exception` fallbacks (e.g. the lazy-flush replay path),
    # which must not swallow the budget interrupt — the one-shot itimer is
    # already consumed and nothing would re-arm it.
    pass


@contextlib.contextmanager
def _alarm(seconds):
    """Interrupt the body after ``seconds`` (best effort — a signal lands
    once control returns to Python bytecode). No-op where SIGALRM is
    unavailable (non-main thread / non-POSIX)."""
    if seconds <= 0:
        raise _BenchTimeout("budget exhausted")
    try:
        prev = signal.signal(signal.SIGALRM, lambda *_: (_ for _ in ()).throw(_BenchTimeout()))
    except (ValueError, AttributeError, OSError):
        yield
        return
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, prev)


def _gap_probe():
    """Dispatch-gap instrumentation (ROADMAP item 2): host idle time between
    device steps, measured as the attributed block time (lazy_block_ns —
    every sanctioned host wait on the device feeds it) per timed step.
    Returns finish(steps) -> ms/step."""
    from paddle_tpu import profiler

    c0 = profiler.counters().get("lazy_block_ns", 0)

    def finish(steps):
        c1 = profiler.counters().get("lazy_block_ns", 0)
        return round((c1 - c0) / max(steps, 1) / 1e6, 3)

    return finish


def bench_gpt(paddle, jax, np, on_tpu):
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

    if on_tpu:
        cfg = GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
            max_position_embeddings=1024, hidden_dropout=0.0, attention_dropout=0.0,
            # unfused CE is ~6% faster at b8 (fits comfortably); the fused
            # path exists for memory-bound configs (1.3B below). Round-5
            # block sweep re-confirmed: fused loses at every block_rows
            # (4096: 43.9k, 8192: 43.6k vs 45.1k unfused, same session).
            # Round-4 optimization search (interleaved in-process A/B, hard
            # syncs): flash-vs-exact attention ±0.1%, fused CE −5%, b16/b32
            # batches −5..−50% (exact attn collapses at b16+; flash holds),
            # optimizer+dispatch ≈ 0 ms (full step == fwd+bwd time).
            # Round-5 decomposition of the 185 ms step (raw-jax replica,
            # per-component ablations on-chip): matmul core 91 ms at 82% of
            # peak, attention 68 ms (37% of step for 6.6% of FLOPs), head+CE
            # 28 ms, LN 7 ms, gelu 2 ms. The flash kernel itself accounts
            # for ~48 ms and already beats stock jax pallas flash 3.6x and
            # splash 3.7x at this shape; the round-5 kernel A/B sweep
            # (multi-row programs, chunk-fused loops, native-layout two-pass,
            # streamed grid, merged backward — all committed behind flags in
            # ops/pallas/flash_attention.py) found the per-head D=64 score
            # matmul pinned near 30 TF/s at short T regardless of structure
            # (the same matmul reaches ~95 TF/s in steady state at T>=4096).
            # The remaining "fused transformer layer" levers (projections
            # inside the kernel) would trade 82%-efficient XLA matmuls for
            # that same pinned regime — the committed A/Bs say it loses.
            fused_lm_loss=False,
        )
        # 30 timed steps: at ~190ms/step the ±4% run-to-run variance seen at
        # 10 steps tightens to ~±1.5% against the ratcheted baseline
        batch, seq, steps = 8, 1024, 30
    else:  # smoke fallback (driver runs on real TPU)
        cfg = GPTConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            max_position_embeddings=256, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq, steps = 8, 256, 10

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.bfloat16()  # MXU-native dtype
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    def loss_fn(m, ids, labels):
        return m.loss(ids, labels)

    step = paddle.jit.compile_train_step(model, loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    loss = step(ids, labels)  # compile
    loss = step(ids, labels)
    float(loss.item())

    gap = _gap_probe()
    t0 = time.time()
    for _ in range(steps):
        loss = step(ids, labels)
    final = float(loss.item())  # forces sync
    dt = time.time() - t0

    tokens_per_sec = batch * seq * steps / dt
    n_params = sum(p.size for p in model.parameters())
    # train FLOPs/token ≈ 6N (fwd+bwd matmuls) + 6·L·d·T (causal attention)
    flops_per_token = 6.0 * n_params + 6.0 * cfg.num_layers * cfg.hidden_size * seq
    mfu = tokens_per_sec * flops_per_token / _V5E_PEAK_BF16 if on_tpu else None
    return {
        "name": f"GPT-{n_params/1e6:.0f}M bf16 train (b{batch}xs{seq}, fused step)",
        "tokens_per_sec": round(tokens_per_sec, 1),
        "loss": round(final, 4),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "dispatch_gap_ms_per_step": gap(steps),
    }


def _gpt_train_tokens_per_sec(paddle, np, cfg, batch, seq, steps):
    from paddle_tpu.models.gpt import GPTForPretraining

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = paddle.jit.compile_train_step(model, lambda m, i, l: m.loss(i, l), opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    loss = step(ids, labels)
    loss = step(ids, labels)
    float(loss.item())
    t0 = time.time()
    for _ in range(steps):
        loss = step(ids, labels)
    final = float(loss.item())
    dt = time.time() - t0
    n_params = sum(p.size for p in model.parameters())
    return batch * seq * steps / dt, n_params, final


def bench_gpt_1p3b(paddle, jax, np, on_tpu):
    """North-star config: GPT-3 1.3B training on ONE chip (BASELINE.json
    1.3B-class). b2 WITHOUT remat + Pallas flash attention + fused LM-head
    CE: flash removes the T² score residuals, so the full activation set
    fits HBM next to the f32 AdamW state — no recompute tax. Measured MFU
    0.66 vs 0.54 for the round-3 b4+remat config."""
    from paddle_tpu.models.gpt import gpt3_1p3b

    if not on_tpu:
        return {"name": "GPT-1.3B single-chip", "skipped": "cpu"}
    cfg = gpt3_1p3b(
        hidden_dropout=0.0, attention_dropout=0.0, remat=False,
        attention_impl="flash", use_mp_layers=False,
    )
    batch, seq, steps = 2, 2048, 8
    tps, n_params, final = _gpt_train_tokens_per_sec(paddle, np, cfg, batch, seq, steps)
    flops_per_token = 6.0 * n_params + 6.0 * cfg.num_layers * cfg.hidden_size * seq
    return {
        "name": f"GPT-1.3B bf16 train (b{batch}xs{seq}, flash, no remat, fused-CE, single chip)",
        "tokens_per_sec": round(tps, 1),
        "mfu": round(tps * flops_per_token / _V5E_PEAK_BF16, 4),
        "loss": round(final, 4),
    }


def bench_gpt_8k_flash(paddle, jax, np, on_tpu):
    """Long-sequence point: 8k tokens through the Pallas flash-attention
    kernel (fwd+bwd), where exact attention's T² scores would dominate.
    No remat: flash keeps activations small enough to skip the recompute
    tax even at 8k (measured MFU 0.38 vs 0.30 with remat). Round-5: unfused
    CE +5% (41.1k vs 39.2k tok/s); attention is 66% of the step here and
    the kernel (12.6 ms/layer fwd+bwd) beats stock jax flash 6.5x and
    splash 8.6x at this shape — the PV/dq matmuls' N=64 lane ceiling
    (~50 TF/s) bounds further gains, so ~0.39-0.41 MFU is the honest
    plateau for D=64 heads on v5e."""
    from paddle_tpu.models.gpt import GPTConfig

    if not on_tpu:
        return {"name": "GPT 8k flash", "skipped": "cpu"}
    cfg = GPTConfig(
        vocab_size=50304, hidden_size=1024, num_layers=12, num_heads=16,
        max_position_embeddings=8192, hidden_dropout=0.0,
        attention_dropout=0.0, attention_impl="flash", remat=False,
        use_mp_layers=False,
        # round-5 A/B: at b2s8192 the full activation set fits HBM, and the
        # unfused CE measured 41.1k vs 39.2k tok/s fused (+5%)
        fused_lm_loss=False,
    )
    batch, seq, steps = 2, 8192, 10
    tps, n_params, final = _gpt_train_tokens_per_sec(paddle, np, cfg, batch, seq, steps)
    flops_per_token = 6.0 * n_params + 6.0 * cfg.num_layers * cfg.hidden_size * seq
    return {
        "name": f"GPT-{n_params/1e6:.0f}M bf16 train (b{batch}xs8192, flash attention)",
        "tokens_per_sec": round(tps, 1),
        "mfu": round(tps * flops_per_token / _V5E_PEAK_BF16, 4),
        "loss": round(final, 4),
    }


def _bf16_wrap(paddle, model):
    """Cast f32 inputs to bf16 at the graph edge so the whole inference body
    runs MXU-native bf16 (weights converted via model.bfloat16())."""
    import paddle_tpu.nn as nn

    class BF16Wrap(nn.Layer):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, x):
            return paddle.cast(self.inner(paddle.cast(x, "bfloat16")), "float32")

    model.bfloat16()
    w = BF16Wrap(model)
    w.eval()
    return w


def bench_resnet50_aot(paddle, jax, np, on_tpu):
    """ResNet-50 bf16 AOT inference through the deployment path
    (save → Predictor). bf16 data flow measured +15% over f32 on v5e."""
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu.static import InputSpec
    from paddle_tpu.inference import Config, create_predictor

    paddle.seed(0)
    model = _bf16_wrap(paddle, resnet50().eval())
    # b64 measured ~1.3x the b32 imgs/s on v5e (utilization, same latency
    # class); serving batch is a throughput knob, keep both paths at b64
    batch = 64 if on_tpu else 4
    steps = 20 if on_tpu else 3

    d = tempfile.mkdtemp()
    prefix = os.path.join(d, "resnet50")
    paddle.static.save_inference_model(
        prefix, [InputSpec([batch, 3, 224, 224], "float32", name="image")], model
    )
    pred = create_predictor(Config(prefix))
    shutil.rmtree(d, ignore_errors=True)  # artifact is in memory now (~200 MB on disk)
    x = np.random.RandomState(0).randn(batch, 3, 224, 224).astype(np.float32)
    # device-resident input via the zero-copy handle: measures the chip, not
    # this environment's tunneled host↔device link (real hardware feeds via
    # DMA; the tunnel's 19 MB/batch host copy is a harness artifact)
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.share_external_data(jax.device_put(jax.numpy.asarray(x)))
    out_h = pred.get_output_handle(pred.get_output_names()[0])
    pred.run()
    out_h.copy_to_cpu()  # block: compile is async through the remote compiler
    pred.run()
    out_h.copy_to_cpu()
    dt = None
    for _ in range(2):  # best-of-2: sheds one-off host/tunnel stalls
        t0 = time.time()
        for _ in range(steps):
            pred.run()
        out_h.copy_to_cpu().sum()
        elapsed = time.time() - t0
        dt = elapsed if dt is None else min(dt, elapsed)
    return {
        "name": f"ResNet-50 bf16 AOT inference (b{batch}, Predictor, device-resident input)",
        "imgs_per_sec": round(batch * steps / dt, 1),
    }


def bench_resnet50_int8(paddle, jax, np, on_tpu):
    """ResNet-50 int8 serving (PTQ → int8 swap → bf16 inter-layer flow →
    Predictor) — the slim→AnalysisPredictor int8 capability.

    PAIRED measurement: int8 and bf16 predictors run in ALTERNATING timed
    segments, so host/tunnel load variance hits both equally and the
    reported ``int8_speedup`` is load-invariant (round-4's driver run showed
    1.003x while idle runs showed 1.23x — pure per-run dispatch variance).
    Ceiling note (round-5 microbench, committed): XLA int8 convs on v5e run
    1.1-1.3x their bf16 counterparts (e.g. 3x3 512ch: 91.7 TOP/s vs 71.8
    TFLOP/s), NOT the 2x the 394-TOPS peak implies — the serving speedup is
    bounded by that, and b256 int8 conv lowering REGRESSES (0.81x), so b64
    is the serving batch."""
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu.static import InputSpec
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.quantization import PostTrainingQuantization, convert_to_int8_inference

    batch = 64 if on_tpu else 4
    steps = 20 if on_tpu else 3

    class Calib(paddle.io.Dataset):
        def __len__(self):
            return 2

        def __getitem__(self, i):
            return np.random.RandomState(i).randn(3, 224, 224).astype(np.float32)

    def build(int8):
        paddle.seed(0)
        model = resnet50()
        model.eval()
        if int8:
            loader = paddle.io.DataLoader(Calib(), batch_size=2, num_workers=0)
            ptq = PostTrainingQuantization(model, data_loader=loader, batch_nums=1)
            ptq.quantize()
            convert_to_int8_inference(model, ptq)
        model = _bf16_wrap(paddle, model)  # int8 weights untouched (non-float)
        d = tempfile.mkdtemp()
        prefix = os.path.join(d, "resnet50_q" if int8 else "resnet50_f")
        paddle.static.save_inference_model(
            prefix, [InputSpec([batch, 3, 224, 224], "float32", name="image")], model
        )
        pred = create_predictor(Config(prefix))
        shutil.rmtree(d, ignore_errors=True)
        x = np.random.RandomState(0).randn(batch, 3, 224, 224).astype(np.float32)
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.share_external_data(jax.device_put(jax.numpy.asarray(x)))
        out_h = pred.get_output_handle(pred.get_output_names()[0])
        pred.run(); out_h.copy_to_cpu()
        pred.run(); out_h.copy_to_cpu()
        return pred, out_h

    pred_q, out_q = build(True)
    pred_f, out_f = build(False)

    def segment(pred, out_h):
        t0 = time.time()
        for _ in range(steps):
            pred.run()
        out_h.copy_to_cpu().sum()
        return time.time() - t0

    dt_q = dt_f = None
    for _ in range(3):  # alternating best-of-3: load-paired A/B
        e_q = segment(pred_q, out_q)
        e_f = segment(pred_f, out_f)
        dt_q = e_q if dt_q is None else min(dt_q, e_q)
        dt_f = e_f if dt_f is None else min(dt_f, e_f)
    return {
        "name": f"ResNet-50 int8 AOT inference (b{batch}, Predictor, paired A/B)",
        "imgs_per_sec": round(batch * steps / dt_q, 1),
        "bf16_paired_imgs_per_sec": round(batch * steps / dt_f, 1),
        "int8_speedup": round(dt_f / dt_q, 3),
    }


def bench_lenet_eager(paddle, jax, np, on_tpu):
    """LeNet eager train step — per-op dispatch overhead (first E2E slice)."""
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    lossf = paddle.nn.CrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(64, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (64,)))
    steps = 30 if on_tpu else 10

    def one_step():
        loss = lossf(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    one_step()
    one_step()
    gap = _gap_probe()
    t0 = time.time()
    for _ in range(steps):
        loss = one_step()
    float(loss.item())
    dt = time.time() - t0
    return {
        "name": "LeNet eager train (b64, lazy batched dispatch)",
        "steps_per_sec": round(steps / dt, 2),
        "dispatch_gap_ms_per_step": gap(steps),
    }


def bench_vit_l_aot(paddle, jax, np, on_tpu):
    """ViT-L/16 bf16 AOT inference (BASELINE.json config 5 class: large
    vision transformer through the deployment path)."""
    from paddle_tpu.vision.models import vit_l_16
    from paddle_tpu.static import InputSpec
    from paddle_tpu.inference import Config, create_predictor

    if not on_tpu:
        return {"name": "ViT-L AOT", "skipped": "cpu"}
    paddle.seed(0)
    model = _bf16_wrap(paddle, vit_l_16().eval())
    batch, steps = 16, 20
    d = tempfile.mkdtemp()
    prefix = os.path.join(d, "vitl")
    paddle.static.save_inference_model(
        prefix, [InputSpec([batch, 3, 224, 224], "float32", name="image")], model
    )
    pred = create_predictor(Config(prefix))
    shutil.rmtree(d, ignore_errors=True)
    x = np.random.RandomState(0).randn(batch, 3, 224, 224).astype(np.float32)
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.share_external_data(jax.device_put(jax.numpy.asarray(x)))
    out_h = pred.get_output_handle(pred.get_output_names()[0])
    pred.run(); out_h.copy_to_cpu()
    pred.run(); out_h.copy_to_cpu()
    dt = None
    for _ in range(2):  # best-of-2: sheds one-off host/tunnel stalls
        t0 = time.time()
        for _ in range(steps):
            pred.run()
        out_h.copy_to_cpu().sum()
        elapsed = time.time() - t0
        dt = elapsed if dt is None else min(dt, elapsed)
    return {
        "name": f"ViT-L/16 bf16 AOT inference (b{batch}, Predictor)",
        "imgs_per_sec": round(batch * steps / dt, 1),
    }


def bench_yolov3_aot(paddle, jax, np, on_tpu):
    """YOLOv3-DarkNet53 bf16 AOT detection inference (the PP-YOLOE BASELINE
    row's YOLO-family point): backbone + FPN heads + yolo_box decode +
    matrix NMS, ALL in one static-shape Predictor graph."""
    from paddle_tpu.vision.models import yolov3_darknet53, YOLOv3Postprocess
    from paddle_tpu.static import InputSpec
    from paddle_tpu.inference import Config, create_predictor

    if not on_tpu:
        return {"name": "YOLOv3 AOT", "skipped": "cpu"}
    paddle.seed(0)
    model = yolov3_darknet53(num_classes=80)
    model.eval()
    post = YOLOv3Postprocess(model, img_hw=(416, 416))
    post = _bf16_wrap(paddle, post)
    batch, steps = 8, 20
    d = tempfile.mkdtemp()
    prefix = os.path.join(d, "yolov3")
    paddle.static.save_inference_model(
        prefix, [InputSpec([batch, 3, 416, 416], "float32", name="image")], post
    )
    pred = create_predictor(Config(prefix))
    shutil.rmtree(d, ignore_errors=True)
    x = np.random.RandomState(0).randn(batch, 3, 416, 416).astype(np.float32)
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.share_external_data(jax.device_put(jax.numpy.asarray(x)))
    out_h = pred.get_output_handle(pred.get_output_names()[0])
    pred.run(); out_h.copy_to_cpu()
    pred.run(); out_h.copy_to_cpu()
    dt = None
    for _ in range(2):
        t0 = time.time()
        for _ in range(steps):
            pred.run()
        out_h.copy_to_cpu().sum()
        elapsed = time.time() - t0
        dt = elapsed if dt is None else min(dt, elapsed)
    return {
        "name": f"YOLOv3-DarkNet53 bf16 AOT detection (b{batch}x416, Predictor+matrixNMS)",
        "imgs_per_sec": round(batch * steps / dt, 1),
    }


def bench_llama_1b(paddle, jax, np, on_tpu):
    """Llama ~1B train step, single-chip proxy of the TP config (BASELINE
    config 4 class: the model's mp_layers carry the Megatron pspecs the
    dryrun executes at mp=8; here the same program runs at world 1)."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if not on_tpu:
        return {"name": "Llama-1B train", "skipped": "cpu"}
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=2048, num_layers=16, num_heads=16,
        max_position_embeddings=2048,
    )
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = paddle.jit.compile_train_step(model, lambda m, i, l: m.loss(i, l), opt)
    rng = np.random.RandomState(0)
    batch, seq, steps = 2, 2048, 8
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    loss = step(ids, labels)
    loss = step(ids, labels)
    float(loss.item())
    t0 = time.time()
    for _ in range(steps):
        loss = step(ids, labels)
    final = float(loss.item())
    dt = time.time() - t0
    n_params = sum(p.size for p in model.parameters())
    tps = batch * seq * steps / dt
    flops_per_token = 6.0 * n_params + 6.0 * cfg.num_layers * cfg.hidden_size * seq
    return {
        "name": f"Llama-{n_params/1e9:.1f}B bf16 train (b{batch}xs{seq}, TP-layered, single chip)",
        "tokens_per_sec": round(tps, 1),
        "mfu": round(tps * flops_per_token / _V5E_PEAK_BF16, 4),
        "loss": round(final, 4),
    }


def bench_dp8_gpt(paddle, jax, np, on_tpu):
    """DP=8 GPT fused train step with the communication-optimized sync
    (ZeRO-1 sharded weight update + bucketed gradient reduce-scatter,
    FLAGS_shard_weight_update). Runs only when the process sees >= 8
    devices (a real multichip slice, or the dryrun harness's virtual CPU
    mesh); the single-chip driver reports it skipped."""
    devs = jax.devices()
    if len(devs) < 8:
        return {"name": "GPT DP=8 sharded-weight-update train",
                "skipped": f"needs 8 devices, have {len(devs)}"}
    from jax.sharding import Mesh
    from paddle_tpu import profiler
    from paddle_tpu.distributed.engine import HybridParallelEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

    if on_tpu:
        cfg = GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
            max_position_embeddings=1024, hidden_dropout=0.0,
            attention_dropout=0.0, fused_lm_loss=False,
        )
        batch, seq, steps = 64, 1024, 10
    else:
        cfg = GPTConfig(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            max_position_embeddings=64, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq, steps = 16, 64, 5
    paddle.set_flags({"FLAGS_shard_weight_update": True})
    paddle.seed(0)
    model = GPTForPretraining(cfg)
    if on_tpu:
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    mesh = Mesh(np.asarray(devs[:8]), ("dp",))
    eng = HybridParallelEngine(model, opt, lambda m, i, l: m.loss(i, l), mesh=mesh)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    eng.train_step(ids, labels)
    float(eng.train_step(ids, labels).item())
    c0 = profiler.counters()
    t0 = time.time()
    for _ in range(steps):
        loss = eng.train_step(ids, labels)
    final = float(loss.item())
    dt = time.time() - t0
    c1 = profiler.counters()
    return {
        "name": f"GPT DP=8 sharded-weight-update train (b{batch}xs{seq})",
        "tokens_per_sec": round(batch * seq * steps / dt, 1),
        "loss": round(final, 4),
        "wus_enabled": int(eng._wus is not None),
        "dp_sync_bytes_per_step": (c1.get("dp_sync_bytes", 0) - c0.get("dp_sync_bytes", 0)) // steps,
    }


def bench_profiler_overhead(paddle, jax, np, on_tpu):
    """Telemetry tax on the hot path (ISSUE-5 acceptance: <2%): a hot
    record+flush loop (one lazy_flush span + flight-ring append per
    iteration) timed with NO profiler vs a constructed-but-CLOSED one.
    Interleaved min-of-N segments, so host load variance hits both arms."""
    from paddle_tpu import profiler

    iters = 150 if on_tpu else 100

    def loop(n):
        t = paddle.to_tensor(np.ones(256, np.float32))
        for _ in range(n):
            t = t + 1.0
            t.numpy()  # materialization point: flush + span every iteration

    loop(30)  # warm the flush executable cache

    def segment():
        t0 = time.time()
        loop(iters)
        return time.time() - t0

    p = profiler.Profiler(timer_only=True)
    p.start()
    p.stop()  # CLOSED; flight recorder still on — the disabled path
    absent, closed = [], []
    # paired segments with ALTERNATING order: CPU-frequency drift and the
    # first-in-pair warmup tax otherwise read as fake overhead (an A/A run
    # of this loop shows ~4% between identical arms when the order is fixed)
    for i in range(8):
        a, b = (absent, closed) if i % 2 == 0 else (closed, absent)
        a.append(segment())
        b.append(segment())
    overhead = min(closed) / min(absent) - 1.0
    return {
        "name": f"profiler disabled-path overhead (lazy dispatch loop x{iters})",
        "overhead_pct": round(overhead * 100.0, 2),
        "absent_us_per_iter": round(min(absent) / iters * 1e6, 2),
        "closed_us_per_iter": round(min(closed) / iters * 1e6, 2),
    }


def bench_watchdog_overhead(paddle, jax, np, on_tpu):
    """Watchdog off-path tax on the LeNet eager step (ISSUE-8 acceptance:
    <=1% with FLAGS_collective_timeout_s=0): the live code path — a
    publish() attr probe per step plus a guard flag compare per host sync —
    against the same loop with both patched to no-ops. Interleaved
    alternating-order min-of-N segments, same discipline as
    bench_profiler_overhead (fixed-order A/B reads CPU drift as fake
    overhead)."""
    import contextlib

    from paddle_tpu.distributed import watchdog
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    lossf = paddle.nn.CrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(64, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (64,)))
    pairs = 40 if on_tpu else 24

    def one_step():
        loss = lossf(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        watchdog.publish(step=0, phase="bench")
        return loss

    one_step(); one_step()  # warm the flush executable cache

    def timed_step():
        t0 = time.perf_counter()
        float(one_step().item())  # item() syncs: the step's guard fires
        return time.perf_counter() - t0

    @contextlib.contextmanager
    def _stubbed():
        orig_guard, orig_publish = watchdog.guard, watchdog.publish
        watchdog.guard = lambda what: contextlib.nullcontext()
        watchdog.publish = lambda *a, **k: None
        try:
            yield
        finally:
            watchdog.guard, watchdog.publish = orig_guard, orig_publish

    # the watchdog tax (~5us/step: one publish + a guard flag probe per
    # host sync) is far below the wall-clock drift of multi-second
    # segments, so the arms alternate at STEP granularity in alternating
    # order — adjacent ~100ms steps see the same CPU budget — and the
    # verdict is the median of per-pair ratios (robust to the occasional
    # descheduled step)
    ratios = []
    for i in range(pairs):
        if i % 2 == 0:
            t_live = timed_step()
            with _stubbed():
                t_stub = timed_step()
        else:
            with _stubbed():
                t_stub = timed_step()
            t_live = timed_step()
        ratios.append(t_live / t_stub)
    ratios.sort()
    overhead = ratios[len(ratios) // 2] - 1.0
    return {
        "name": f"watchdog disabled-path overhead (LeNet eager, {pairs} interleaved step pairs)",
        "overhead_pct": round(overhead * 100.0, 2),
    }


def bench_verify_overhead(paddle, jax, np, on_tpu):
    """Lazy-graph verifier tax on the LeNet train loop (ISSUE-9 acceptance:
    <2% with FLAGS_lazy_verify=1; ~0 when off). Two measurements, one
    verdict: (a) an interleaved per-step-pair A/B (median of ratios, the
    bench_watchdog_overhead discipline) — honest but carries this shared
    box's +-8% scheduler noise; (b) a same-run DIRECT attribution: the
    verifier entry point is wrapped with a timer while the flag-on loop
    runs, so verify time / step time is immune to drift between arms. The
    pinned number is (b); (a) corroborates on quiet boxes (TPU hosts)."""
    from paddle_tpu.framework import flags
    from paddle_tpu.analysis import verify_graph as _vg
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    lossf = paddle.nn.CrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(64, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (64,)))
    pairs = 40 if on_tpu else 24

    def one_step():
        loss = lossf(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    prev = bool(flags.flag("FLAGS_lazy_verify", False))

    def timed_step(verify):
        flags.set_flags({"FLAGS_lazy_verify": verify})
        t0 = time.perf_counter()
        float(one_step().item())
        return time.perf_counter() - t0

    orig_verify = _vg.verify_before_dispatch
    acc = [0.0, 0]  # verify seconds, calls

    def timed_verify(*a, **k):
        t0 = time.perf_counter()
        try:
            return orig_verify(*a, **k)
        finally:
            acc[0] += time.perf_counter() - t0
            acc[1] += 1

    try:
        # warm the flush executable cache under BOTH flag values (inside the
        # try: a timeout/compile failure here must not leak the verifier flag
        # into every later benchmark); the verifier changes no signatures
        # (pinned by test_graph_verify parity), so both arms replay the same
        # executables
        flags.set_flags({"FLAGS_lazy_verify": False})
        one_step(); one_step()
        flags.set_flags({"FLAGS_lazy_verify": True})
        one_step(); one_step()

        # (a) interleaved per-step-pair A/B
        ratios = []
        for i in range(pairs):
            if i % 2 == 0:
                t_on = timed_step(True)
                t_off = timed_step(False)
            else:
                t_off = timed_step(False)
                t_on = timed_step(True)
            ratios.append(t_on / t_off)
        ratios.sort()
        ab_overhead = ratios[len(ratios) // 2] - 1.0

        # (b) direct attribution: verify time as a share of flag-on step time
        _vg.verify_before_dispatch = timed_verify
        flags.set_flags({"FLAGS_lazy_verify": True})
        t0 = time.perf_counter()
        n_steps = 16
        for _ in range(n_steps):
            float(one_step().item())
        total = time.perf_counter() - t0
    finally:
        _vg.verify_before_dispatch = orig_verify
        flags.set_flags({"FLAGS_lazy_verify": prev})
    direct = acc[0] / max(total - acc[0], 1e-9)
    return {
        "name": f"lazy-graph verifier overhead (LeNet eager, {pairs} step pairs + direct attribution)",
        "overhead_pct": round(direct * 100.0, 2),
        "ab_overhead_pct": round(ab_overhead * 100.0, 2),
        "verify_us_per_flush": round(acc[0] / max(acc[1], 1) * 1e6, 1),
        "verified_flushes": acc[1],
        "budget_pct": 2.0,
    }


def bench_stability_overhead(paddle, jax, np, on_tpu):
    """Stability-sentinel tax on the LeNet train loop (ISSUE-13 acceptance:
    enabled-path budget <2%, like bench_verify_overhead; the DISABLED path
    is one attribute probe per flush and one flag probe per fit, pinned ~0
    by the tier-1 inert tripwire). Enabled arm: a sentinel observes every
    step's fused signal pack (loss + grad norm + non-finite rate + update
    ratio, one 4-float readback per step riding the deferred drain) with
    thresholds set so nothing trips. Two measurements, one verdict — the
    bench_verify_overhead discipline: (a) interleaved per-step-pair A/B
    (median of ratios; honest but carries this shared box's scheduler
    noise), and (b) same-run DIRECT attribution — observe() wall time as a
    share of enabled-loop step time, immune to drift between arms. The
    pinned number is (b). Also populates the grad_global_norm / loss_ema
    fields of the main BENCH line."""
    from paddle_tpu.fault.sentinel import StabilitySentinel
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    lossf = paddle.nn.CrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(64, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (64,)))
    params = [p for p in model.parameters() if not p.stop_gradient]
    pairs = 40 if on_tpu else 24
    sent = StabilitySentinel(
        window=256, warmup=10_000, zmax=1e9, max_skips=0, max_rollbacks=0
    )
    step_no = [0]
    acc = [0.0, 0]  # observe seconds, calls

    def one_step(observe):
        loss = lossf(model(x), y)
        loss.backward()
        if observe:
            step_no[0] += 1
            t0 = time.perf_counter()
            sent.observe(
                step_no[0], loss=loss,
                grads=[p.grad for p in params if p.grad is not None],
                params=params, lr=opt.get_lr(),
            )
            acc[0] += time.perf_counter() - t0
            acc[1] += 1
        opt.step()
        opt.clear_grad()
        return loss

    def timed_step(observe):
        t0 = time.perf_counter()
        float(one_step(observe).item())
        return time.perf_counter() - t0

    try:
        # warm both arms' flush executables (the signal pack is an extra
        # fused node, so the enabled arm has its own cache signature)
        one_step(False); one_step(False)
        one_step(True); one_step(True)

        # (a) interleaved per-step-pair A/B
        ratios = []
        for i in range(pairs):
            if i % 2 == 0:
                t_on = timed_step(True)
                t_off = timed_step(False)
            else:
                t_off = timed_step(False)
                t_on = timed_step(True)
            ratios.append(t_on / t_off)
        ratios.sort()
        ab_overhead = ratios[len(ratios) // 2] - 1.0

        # (b) direct attribution: observe() time / enabled-loop step time
        acc[0] = 0.0
        acc[1] = 0
        n_steps = 16
        t0 = time.perf_counter()
        for _ in range(n_steps):
            float(one_step(True).item())
        total = time.perf_counter() - t0
        sent.poll()
    finally:
        sent.close()
    direct = acc[0] / max(total - acc[0], 1e-9)
    return {
        "name": (
            f"stability-sentinel overhead (LeNet eager, {pairs} step pairs "
            "+ direct attribution)"
        ),
        "overhead_pct": round(direct * 100.0, 2),
        "ab_overhead_pct": round(ab_overhead * 100.0, 2),
        "observe_us_per_step": round(acc[0] / max(acc[1], 1) * 1e6, 1),
        "budget_pct": 2.0,
    }


def bench_observe_overhead(paddle, jax, np, on_tpu):
    """Serving-observability tax (ISSUE-20 acceptance: <2% per step): the
    same prompt wave through two warm engines — request tracing + SLO
    histograms armed vs flag-off — as interleaved alternating-order wave
    pairs, median of per-pair ratios (the bench_watchdog_overhead
    discipline; fixed-order A/B reads CPU drift as fake overhead). Ends
    with the structural-zero tripwire: every ``serving.observe`` hook is
    monkeypatched to raise and a flag-off engine must still serve a wave —
    the inert path is one ``is not None`` probe per hook site, never a
    call."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining
    from paddle_tpu.serving import Engine, observe

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                    num_heads=2, max_position_embeddings=256,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size,
                           (int(rng.randint(4, 24)),)).tolist()
               for _ in range(16)]
    max_new = 8
    ekw = dict(block_size=16, num_blocks=256, max_batch=16, max_seq_len=128)
    observe.reset()

    def wave(eng, n=None):
        t0 = time.monotonic()
        hs = [eng.submit(p, max_new_tokens=max_new)
              for p in prompts[:n or len(prompts)]]
        [h.result(timeout=600) for h in hs]
        return time.monotonic() - t0

    pairs = 10 if on_tpu else 6
    with Engine(model, trace=False, metrics_port=0, **ekw) as off, \
            Engine(model, trace=True, metrics_port=0, **ekw) as on:
        wave(off)
        wave(on)  # warm both arms' bucket executables
        ratios = []
        for i in range(pairs):
            if i % 2 == 0:
                t_on, t_off = wave(on), wave(off)
            else:
                t_off, t_on = wave(off), wave(on)
            ratios.append(t_on / t_off)
    ratios.sort()
    overhead = ratios[len(ratios) // 2] - 1.0

    # structural-zero tripwire: a flag-off engine with every hook exploded
    # must still serve (a hook call would fail the wave, not just slow it)
    hooks = [n for n in dir(observe) if n.startswith("on_")]
    saved = {n: getattr(observe, n) for n in hooks}

    def _explode(*a, **k):
        raise AssertionError("observe hook reached from a flag-off engine")

    try:
        for n in hooks:
            setattr(observe, n, _explode)
        with Engine(model, trace=False, metrics_port=0, **ekw) as eng:
            wave(eng, n=4)
        inert_ok = True
    finally:
        for n, f in saved.items():
            setattr(observe, n, f)
    observe.reset()
    return {
        "name": (
            f"serving observability overhead ({len(prompts)} streams x "
            f"{pairs} interleaved wave pairs)"
        ),
        "overhead_pct": round(overhead * 100.0, 2),
        "inert_flag_off": inert_ok,
        "budget_pct": 2.0,
    }


def bench_memory_pressure(paddle, jax, np, on_tpu):
    """HBM-admission enforce-path tax on the LeNet eager loop (ISSUE-14
    acceptance: <2% enabled; the DISABLED path is one flag probe per flush,
    pinned by the tier-1 inert tripwire) plus a pressure drive that reports
    recovery-ladder engagements. Overhead protocol = bench_stability_overhead:
    (a) interleaved per-step-pair A/B (median of ratios), (b) same-run DIRECT
    attribution — preflight() wall time as a share of enabled-loop step time;
    (b) is the pinned number. The enabled arm runs FLAGS_hbm_admission=
    enforce against an effectively-unlimited budget, so every flush pays the
    real admission cost (census walk + compare) and nothing rejects."""
    from paddle_tpu.fault import inject, memory
    from paddle_tpu.framework import flags
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    lossf = paddle.nn.CrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(64, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (64,)))
    pairs = 40 if on_tpu else 24

    def one_step():
        loss = lossf(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    prev = flags.get_flags(["FLAGS_hbm_admission", "FLAGS_hbm_budget_bytes"])

    def timed_step(enforce):
        flags.set_flags({"FLAGS_hbm_admission": "enforce" if enforce else "off"})
        t0 = time.perf_counter()
        float(one_step().item())
        return time.perf_counter() - t0

    orig_preflight = memory.preflight
    acc = [0.0, 0]  # preflight seconds, calls

    def timed_preflight(*a, **k):
        t0 = time.perf_counter()
        try:
            return orig_preflight(*a, **k)
        finally:
            acc[0] += time.perf_counter() - t0
            acc[1] += 1

    try:
        flags.set_flags({"FLAGS_hbm_budget_bytes": 1 << 60})
        # warm both arms (the enforce arm AOT-upgrades the cached entries)
        flags.set_flags({"FLAGS_hbm_admission": "off"})
        one_step(); one_step()
        flags.set_flags({"FLAGS_hbm_admission": "enforce"})
        one_step(); one_step()

        # (a) interleaved per-step-pair A/B
        ratios = []
        for i in range(pairs):
            if i % 2 == 0:
                t_on = timed_step(True)
                t_off = timed_step(False)
            else:
                t_off = timed_step(False)
                t_on = timed_step(True)
            ratios.append(t_on / t_off)
        ratios.sort()
        ab_overhead = ratios[len(ratios) // 2] - 1.0

        # (b) direct attribution: preflight time / enforce-loop step time
        memory.preflight = timed_preflight
        flags.set_flags({"FLAGS_hbm_admission": "enforce"})
        n_steps = 16
        t0 = time.perf_counter()
        for _ in range(n_steps):
            float(one_step().item())
        total = time.perf_counter() - t0

        # pressure drive: a transient injected RESOURCE_EXHAUSTED at the
        # flush dispatch engages the ladder (free pressure → retry)
        from paddle_tpu import profiler as _prof

        flags.set_flags({"FLAGS_hbm_admission": "off"})
        c0 = _prof.counters()
        rec0 = (c0.get("hbm_oom_trips", 0), c0.get("hbm_oom_recoveries", 0))
        inject.arm("hbm.oom:op=lazy_flush,at=2,times=1")
        w = paddle.to_tensor(np.ones((4, 4), np.float32))
        w.stop_gradient = False
        for i in range(3):
            drive_x = paddle.to_tensor(
                np.random.RandomState(i).randn(8, 4).astype(np.float32))
            dl = (paddle.matmul(drive_x, w) ** 2).mean()
            dl.backward()
            w._set_data((w - 0.1 * w.grad)._data)
            w.clear_grad()
            float(dl.item())
        inject.disarm()
        c = _prof.counters()
        trips = c.get("hbm_oom_trips", 0) - rec0[0]
        recov = c.get("hbm_oom_recoveries", 0) - rec0[1]
    finally:
        memory.preflight = orig_preflight
        inject.disarm()
        flags.set_flags(prev)
    direct = acc[0] / max(total - acc[0], 1e-9)
    pred = memory.last_prediction()
    return {
        "name": (
            f"hbm admission enforce overhead (LeNet eager, {pairs} step "
            "pairs + direct attribution) + pressure drive"
        ),
        "overhead_pct": round(direct * 100.0, 2),
        "ab_overhead_pct": round(ab_overhead * 100.0, 2),
        "preflight_us_per_flush": round(acc[0] / max(acc[1], 1) * 1e6, 1),
        "budget_pct": 2.0,
        "ladder_trips": trips,
        "ladder_recoveries": recov,
        "hbm_predicted_peak_bytes": pred.get("hbm_predicted_peak_bytes"),
    }


HOSTEMB_WORKER = """
import os, json, time
os.environ["JAX_PLATFORMS"] = os.environ.get("HE_PLATFORM", "cpu")
import numpy as np
from paddle_tpu.framework import flags
from paddle_tpu.incubate.host_embedding import sharded_host_embedding, ShardedHostEmbeddingTable

rank = int(os.environ["PADDLE_TRAINER_ID"])
V, D = int(os.environ["HE_V"]), int(os.environ["HE_D"])
per, steps = int(os.environ["HE_PER"]), int(os.environ["HE_STEPS"])
emb = sharded_host_embedding(V, D, seed=1)
table = emb.table
assert isinstance(table, ShardedHostEmbeddingTable)
rng = np.random.RandomState(7)  # same stream on every rank (sync PS)
batches = [np.unique((rng.zipf(1.2, per) % V).astype(np.int64)) for _ in range(steps + 1)]
# warmup exchange (row init + store/socket setup)
rows = table.gather(batches[-1])
table.apply_update(batches[-1], np.full((batches[-1].size, D), 0.01, np.float32), 0.1)
t0 = time.perf_counter()
n = 0
for ids in batches[:steps]:
    rows = table.gather(ids)
    table.apply_update(ids, rows * np.float32(0.001), lr=0.1)
    n += ids.size * 2  # one pull + one push per id
dt = time.perf_counter() - t0
from paddle_tpu import profiler
print(json.dumps({"rank": rank, "lookups_per_sec": n / dt,
                  "push_bytes": profiler.counters().get("host_emb_push_bytes", 0)}),
      flush=True)
"""


def _hostemb_sharded_lps(np, world, V, D, per, steps):
    """Spawn a world of sharded-table workers doing table-level pull/push
    rounds; returns rank-0's steady-state lookups/sec (None on any
    failure — the sharded bench is best-effort on CPU CI boxes)."""
    import socket
    import subprocess
    import sys

    try:
        from paddle_tpu.core.native import lib

        if lib() is None:
            return None
    except Exception:
        return None
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    repo = os.path.dirname(os.path.abspath(__file__))
    procs = []
    for rank in range(world):
        env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS",)}
        env.update({
            "PYTHONPATH": repo, "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_EMB_STORE_PORT": str(port),
            "HE_V": str(V), "HE_D": str(D), "HE_PER": str(per),
            "HE_STEPS": str(steps),
        })
        procs.append(subprocess.Popen([sys.executable, "-c", HOSTEMB_WORKER],
                                      env=env, stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            if p.returncode != 0:
                # kill the rest: surviving ranks are blocked forever in the
                # store collective and would outlive the bench
                for q in procs:
                    q.kill()
                return None
            outs.append(json.loads(out.decode().strip().splitlines()[-1]))
    except Exception:
        for p in procs:
            p.kill()
        return None
    r0 = next(o for o in outs if o["rank"] == 0)
    return {"lookups_per_sec": round(r0["lookups_per_sec"], 1),
            "push_bytes": r0["push_bytes"]}


def bench_host_embedding(paddle, jax, np, on_tpu):
    """Host-embedding PS hot path (ROADMAP item 4): interleaved A/B of the
    pre-PR path (pure-numpy fallback, synchronous per-microbatch pull +
    inline push — the kill-switched code IS the old code) against the
    rebuilt path (native gather/scatter, HBM hot-row cache, prefetched pull
    + async push). Metric: embedding lookups/sec through the PS hot path —
    lookups divided by the HOST-BLOCKING time the training loop pays for
    the embedding layer (`host_emb_block_ns`), which is what the LazyTensor
    overlap discipline (arXiv:2102.13267) says should approach zero: host
    table work belongs behind device execution. Wall-clock per step is
    reported alongside so the overlap claim is checkable (a path that
    merely shifted work off the counter would inflate wall time). Both
    sides run identical id streams on identically-seeded tables and must
    land BIT-IDENTICAL tables — the A/B is also a parity pin. Ends with
    2- and 4-process sharded pull/push rounds over the coalesced
    chunk-parallel store transport, and prints ONE `HOSTEMB_PERF` JSON
    line."""
    from paddle_tpu import profiler as _prof
    from paddle_tpu.framework import flags as _fl
    from paddle_tpu.incubate.host_embedding import HostEmbedding
    import paddle_tpu.nn as nn

    if on_tpu:
        rows, dim, mbs, per = 80_000_000, 64, 8, 8192
        rounds, steps = 3, 3
    else:
        rows, dim, mbs, per = 500_000, 32, 8, 8192
        rounds, steps = 3, 3
    lookups_per_step = mbs * per
    rng = np.random.RandomState(0)
    stream = [[(rng.zipf(1.2, per) % rows).astype(np.int64).reshape(64, -1)
               for _ in range(mbs)]
              for _ in range(rounds * (steps + 1) + 4)]

    OLD = {"FLAGS_host_emb_native": False, "FLAGS_host_emb_async_push": False}
    NEW = {"FLAGS_host_emb_native": True, "FLAGS_host_emb_async_push": True}
    prev = _fl.get_flags(list(OLD) + ["FLAGS_host_emb_cache_rows",
                                      "FLAGS_host_emb_cache_min_count"])
    d = tempfile.mkdtemp()
    sides = {}
    try:
        _fl.set_flags({"FLAGS_host_emb_cache_min_count": 2})
        for side in ("old", "new"):
            emb = HostEmbedding(
                rows, dim, path=os.path.join(d, f"{side}.npy"), seed=1,
                cache_rows=(4096 if side == "new" else 0))
            paddle.seed(0)
            head = nn.Linear(dim, 64)
            head2 = nn.Linear(64, 1)
            opt = paddle.optimizer.SGD(
                learning_rate=0.1,
                parameters=head.parameters() + head2.parameters())
            sides[side] = {"emb": emb, "head": head, "head2": head2,
                           "opt": opt, "block_ns": 0, "wall_ns": 0,
                           "flags": OLD if side == "old" else NEW}

        def one_step(side, step_idx):
            st = sides[side]
            emb, head, head2, opt = st["emb"], st["head"], st["head2"], st["opt"]
            new = side == "new"
            loss = None
            for m, ids in enumerate(stream[step_idx]):
                if new and m == 0:
                    # pipelined pull: the whole NEXT step's microbatches are
                    # known now — one union prefetch job staged in advance
                    emb.prefetch(stream[step_idx + 1])
                out = emb(paddle.to_tensor(ids))
                pooled = paddle.mean(out, axis=1)
                loss = paddle.mean(head2(paddle.tanh(head(pooled))) ** 2)
                loss.backward()
            # device work resolved BEFORE the push on BOTH sides, so the PS
            # accounting holds pure host table time, never device waits:
            # old applies inline after, new enqueues pure-host work that
            # overlaps the next step's tracing + device execution
            opt.step()
            opt.clear_grad()
            float(loss.item())
            emb.apply_gradients(lr=0.05)

        # warmup: compile the dense step, touch first rows, warm the cache
        for side in ("old", "new"):
            _fl.set_flags(sides[side]["flags"])
            one_step(side, 0)
            one_step(side, 1)
            sides[side]["emb"].sync()
        # parity probe: after the SAME two steps, both sides' tables must
        # match (native + pipeline are bit-exact vs pure numpy; the
        # dense-leaf hot cache adds summation-order rounding only — over
        # many steps a trained head amplifies those ulps chaotically, so
        # the pin is taken here, not at the end of the timed rounds)
        probe = np.unique(stream[0][0].ravel())[:2048]
        t_old = sides["old"]["emb"].table.gather(probe)
        t_new = sides["new"]["emb"].table.gather(probe)
        rel = float((np.abs(t_new - t_old) /
                     np.maximum(np.abs(t_old), 1e-6)).max())
        parity = rel < 1e-4
        step_idx = 2
        for _ in range(rounds):
            for side in ("old", "new"):
                st = sides[side]
                _fl.set_flags(st["flags"])
                # one untimed re-warm step after the side switch: the other
                # side's round trashed CPU caches (old recompiles every
                # step), which would otherwise bill its first timed step
                one_step(side, step_idx)
                b0 = _prof.counters().get("host_emb_block_ns", 0)
                t0 = time.perf_counter_ns()
                for s in range(1, steps + 1):
                    one_step(side, step_idx + s)
                st["emb"].sync()  # drain: trailing async work charged here
                st["wall_ns"] += time.perf_counter_ns() - t0
                st["block_ns"] += _prof.counters().get("host_emb_block_ns", 0) - b0
            step_idx += steps + 1
        cache_stats = sides["new"]["emb"].cache.stats()
    finally:
        _fl.set_flags(prev)
        shutil.rmtree(d, ignore_errors=True)

    # ---- r04-faithful A/B: the PRE-PR bench shape (ONE b256x64 uniform
    # batch per step over a memmap table). The old path pays its true
    # production pathologies here: the unique-count varies every step, so
    # the traced step graph RECOMPILES per step (the dominant term in the
    # recorded 1.9k lookups/sec), and the whole pull/push is synchronous
    # host work. The new path's HWM-padded shapes compile once and the
    # pull/push pipelines away.
    r04 = {}
    try:
        d2 = tempfile.mkdtemp()
        v2, dim2, b2, ids2 = ((80_000_000, 64, 256, 64) if on_tpu
                              else (8_000_000, 64, 256, 64))
        r04_steps, r04_warm = 4, 2
        rng2 = np.random.RandomState(1)
        batches2 = [rng2.randint(0, v2, (b2, ids2)).astype(np.int64)
                    for _ in range(r04_steps + r04_warm)]
        _fl.set_flags({"FLAGS_host_emb_cache_min_count": 2})
        for side in ("old", "new"):
            _fl.set_flags(OLD if side == "old" else NEW)
            emb = HostEmbedding(v2, dim2, path=os.path.join(d2, f"{side}.npy"),
                                seed=1, cache_rows=(4096 if side == "new" else 0))
            paddle.seed(0)
            head = nn.Linear(dim2, 256)
            head2 = nn.Linear(256, 1)
            new = side == "new"
            def step2(i):
                if new and i + 1 < len(batches2):
                    emb.prefetch(batches2[i + 1])
                out = emb(paddle.to_tensor(batches2[i]))
                loss = paddle.mean(
                    head2(paddle.tanh(head(paddle.mean(out, axis=1)))) ** 2)
                loss.backward()
                float(loss.item())
                emb.apply_gradients(lr=0.05)
            for i in range(r04_warm):
                step2(i)
            emb.sync()
            t0 = time.perf_counter_ns()
            for i in range(r04_warm, r04_warm + r04_steps):
                step2(i)
            emb.sync()
            dt = (time.perf_counter_ns() - t0) / 1e9
            r04[side] = b2 * ids2 * r04_steps / dt
            del emb
    except Exception as e:
        r04 = {"error": str(e)[:200]}
    finally:
        shutil.rmtree(d2, ignore_errors=True)
        _fl.set_flags(prev)

    total_steps = rounds * steps
    total_lookups = total_steps * lookups_per_step

    def lps(ns):
        return total_lookups / (ns / 1e9) if ns > 0 else None

    old_lps, new_lps = lps(sides["old"]["block_ns"]), lps(sides["new"]["block_ns"])
    from paddle_tpu.core import native as _native

    line = {
        "name": (f"Host-embedding PS hot path ({rows/1e6:.1f}M x {dim} table, "
                 f"{mbs}x{per} lookups/step, zipf ids)"),
        "lookups_per_sec": round(new_lps, 1) if new_lps else None,
        "lookups_per_sec_old": round(old_lps, 1) if old_lps else None,
        "ps_speedup_x": (round(new_lps / old_lps, 1)
                         if old_lps and new_lps else None),
        "ps_block_ms_per_step_old": round(
            sides["old"]["block_ns"] / total_steps / 1e6, 3),
        "ps_block_ms_per_step_new": round(
            sides["new"]["block_ns"] / total_steps / 1e6, 3),
        "wall_ms_per_step_old": round(
            sides["old"]["wall_ns"] / total_steps / 1e6, 1),
        "wall_ms_per_step_new": round(
            sides["new"]["wall_ns"] / total_steps / 1e6, 1),
        "wall_speedup_x": round(
            sides["old"]["wall_ns"] / max(sides["new"]["wall_ns"], 1), 2),
        "ab_parity_ok": parity,
        "ab_parity_max_rel_err": rel,
        # r04-faithful shape: lookups/sec through the FULL step, old vs new
        "r04_lookups_per_sec": (round(r04["new"], 1)
                                if "new" in r04 else None),
        "r04_lookups_per_sec_old": (round(r04["old"], 1)
                                    if "old" in r04 else None),
        "r04_speedup_x": (round(r04["new"] / r04["old"], 1)
                          if "new" in r04 and "old" in r04 else None),
        "hot_hit_rate": round(cache_stats["hit_rate"], 4),
        "native": bool(_native.lib() is not None and _native.HAS_EMBED),
        "push_bytes": _prof.counters().get("host_emb_push_bytes", 0),
        "procs": {},
    }
    # sharded pull/push rounds (table-level, coalesced chunk-parallel
    # transport) at 2 and 4 processes
    for world in (2, 4):
        r = _hostemb_sharded_lps(np, world, V=200_000, D=32, per=4096, steps=3)
        if r is not None:
            line["procs"][str(world)] = r
    print("HOSTEMB_PERF " + json.dumps(line))
    return line


def bench_serving(paddle, jax, np, on_tpu):
    """Serving-engine load generator (ROADMAP item 1): >= 64 concurrent
    autoregressive streams through the continuous-batching + paged-KV engine
    on a tiny GPT, submitted from client threads, then a SECOND timed window
    at 4x the measured sustainable load with deadlines + fast-fail shedding
    armed (round 12 resilience layer) — the engine must shed instead of
    stalling, keeping admitted-request p99 bounded. Ends with the
    high-prefix-overlap A/B (`_bench_serving_prefix_spec`) and the
    crash-recovery A/B (`_bench_serving_recovery`: re-prefill vs snapshot
    re-attach MTTR). Prints ONE `SERVE_PERF` JSON line (p50/p99 request
    latency, generated tokens/sec, mean decode batch occupancy, compile
    count, the overload window's shed-rate / deadline-miss-rate /
    p99-under-overload, the prefix/speculative hit- and acceptance-rates
    with speedup-vs-baseline, the recovery round's per-arm MTTR +
    re-prefilled-tokens vs re-attached-blocks, and the observability
    round's TTFT p50/p99, inter-token p99 and cost-model drift gauges)
    and returns the same dict for extra_metrics."""
    import threading

    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining
    from paddle_tpu.serving import Engine

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                        num_heads=4, max_position_embeddings=2048,
                        hidden_dropout=0.0, attention_dropout=0.0)
        streams, max_new, lo, hi = 256, 64, 16, 256
        ekw = dict(block_size=16, num_blocks=8192, max_batch=128,
                   max_seq_len=1024)
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=2, max_position_embeddings=256,
                        hidden_dropout=0.0, attention_dropout=0.0)
        streams, max_new, lo, hi = 64, 8, 4, 32
        ekw = dict(block_size=16, num_blocks=512, max_batch=64,
                   max_seq_len=128)
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (int(rng.randint(lo, hi)),)).tolist()
               for _ in range(streams)]

    with Engine(model, **ekw) as eng:
        # warm EVERY bucket executable the timed wave will touch (all prefill
        # length buckets + every decode width the drain passes through) with
        # an untimed wave of the same prompts, so the timed window measures
        # serving, not compilation — the "warm cache" the compile-count
        # promise is about
        warm = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        [h.result(timeout=600) for h in warm]
        handles = [None] * streams
        clients = 8
        client_errs = []

        def client(cid):
            try:
                for i in range(cid, streams, clients):
                    handles[i] = eng.submit(prompts[i], max_new_tokens=max_new)
            except Exception as e:  # surface the REAL failure, not a None handle
                client_errs.append(e)

        from paddle_tpu import profiler as _prof

        c0 = _prof.counters()
        t0 = time.monotonic()
        threads = [threading.Thread(target=client, args=(c,)) for c in range(clients)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        if client_errs:
            raise client_errs[0]
        outs = [h.result(timeout=600) for h in handles]
        wall = time.monotonic() - t0
        c1 = _prof.counters()
        lat = sorted(h.latency_s for h in handles)
        st = eng.stats()

    gen_tokens = sum(len(o) - len(p) for o, p in zip(outs, prompts))
    assert all(len(o) == len(p) + max_new for o, p in zip(outs, prompts))
    # occupancy over the TIMED window only (counter deltas) — the engine's
    # lifetime mean would dilute it with the warm wave's ramp/drain
    d_live = c1.get("serve_occupancy_live", 0) - c0.get("serve_occupancy_live", 0)
    d_slots = c1.get("serve_occupancy_slots", 0) - c0.get("serve_occupancy_slots", 0)
    p99_unloaded = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
    line = {
        "name": f"serving load-gen (GPT h{cfg.hidden_size}xL{cfg.num_layers}, "
                f"{streams} streams, max_new {max_new})",
        "streams": streams,
        "tokens_per_sec": round(gen_tokens / wall, 1),
        "p50_latency_s": round(lat[len(lat) // 2], 3),
        "p99_latency_s": round(p99_unloaded, 3),
        "batch_occupancy_mean": round(d_live / max(d_slots, 1), 4),
        "compiles": st["compiles"],
        "wall_s": round(wall, 2),
    }
    line["overload"] = _bench_serving_overload(
        np, model, ekw, prompts, max_new, streams / wall, p99_unloaded)
    line["prefix_spec"] = _bench_serving_prefix_spec(
        np, model, cfg.vocab_size, ekw, on_tpu)
    line["recovery"] = _bench_serving_recovery(np, model, ekw, prompts,
                                               max_new)
    line["paged_kernel"] = _bench_serving_paged_kernel(
        np, model, ekw, prompts, max_new)
    line["mesh"] = _bench_serving_mesh(
        np, model, ekw, prompts, max_new, on_tpu)
    line["chunked_prefill"] = _bench_serving_chunked_prefill(
        np, model, cfg.vocab_size, ekw, max_new, on_tpu)
    line["observe"] = _bench_serving_observe(
        np, paddle, model, ekw, prompts, max_new)
    print("SERVE_PERF " + json.dumps(line))
    return line


def _bench_serving_observe(np, paddle, model, ekw, prompts, max_new):
    """SLO observability round (ISSUE-20): re-drive a slice of the stream
    set on a TRACED engine and fold the token-latency SLO quantiles (TTFT
    p50/p99, inter-token p99 — the TPOT line) plus the three cost-model
    drift gauges into SERVE_PERF. ``step_eta`` (shed-ETA decode EMA +
    collective floor vs measured step time) accrues on the traced engine
    itself; ``hbm_admission`` needs the preflight armed while the engine
    steps, so one admission-checked lazy dispatch seeds the predictor
    first; ``kernel_estimate`` (cost-model candidate ordering vs measured
    timings) comes from a small measured search over a stubbed fused_ce
    runner with known per-config timings."""
    from paddle_tpu.framework import flags
    from paddle_tpu.ops.kernels import autotune, registry
    from paddle_tpu.serving import Engine, observe

    observe.reset()
    sub = prompts[: min(32, len(prompts))]
    old_adm = flags._FLAGS.get("FLAGS_hbm_admission")
    flags._FLAGS["FLAGS_hbm_admission"] = "warn"
    try:
        # seed the admission predictor — drift (b) compares it against the
        # post-step census inside the traced engine's scheduler loop
        t = paddle.to_tensor(np.ones((64, 64), np.float32))
        (t @ t).numpy()
        with Engine(model, trace=True, metrics_port=0, **ekw) as eng:
            hs = [eng.submit(p, max_new_tokens=max_new) for p in sub]
            [h.result(timeout=600) for h in hs]
    finally:
        if old_adm is None:
            flags._FLAGS.pop("FLAGS_hbm_admission", None)
        else:
            flags._FLAGS["FLAGS_hbm_admission"] = old_adm

    # drift (c): measured search on a stub runner registered under a name
    # the cost model knows (fused_ce), so candidate estimates differ and
    # the discordant-pair fraction is defined
    saved = registry._REGISTRY.get("fused_ce")
    old_samples = flags._FLAGS.get("FLAGS_kernel_tune_samples")
    flags._FLAGS["FLAGS_kernel_tune_samples"] = 1
    try:
        sleeps = {32: 0.004, 64: 0.0, 128: 0.008}

        def runner(key):
            def make(cfg):
                br = int(cfg["block_rows"])

                def step():
                    time.sleep(sleeps[br])
                    return np.zeros(4, np.float32)

                return step

            return make

        spec = registry.register_kernel(
            "fused_ce", defaults={"block_rows": 32},
            space={"block_rows": (32, 64, 128)}, runner=runner)
        autotune.search(spec, (256, 64, 512, "float32"))
    finally:
        if old_samples is None:
            flags._FLAGS.pop("FLAGS_kernel_tune_samples", None)
        else:
            flags._FLAGS["FLAGS_kernel_tune_samples"] = old_samples
        if saved is not None:
            registry._REGISTRY["fused_ce"] = saved
        else:
            registry._REGISTRY.pop("fused_ce", None)

    book = observe.trace_book()
    out = {
        "streams": len(sub),
        "ttft_p50_s": round(observe.percentile("serve_ttft_seconds", 0.5), 4),
        "ttft_p99_s": round(observe.percentile("serve_ttft_seconds", 0.99), 4),
        "inter_token_p99_s": round(
            observe.percentile("serve_inter_token_seconds", 0.99), 5),
        "timelines": len(book.completed()),
        "drift": {k: round(float(v.get("rel_err", 0.0)), 4)
                  for k, v in observe.drift_gauges().items()},
    }
    observe.reset()
    return out


def _bench_serving_mesh(np, model, ekw, prompts, max_new, on_tpu):
    """Tensor-parallel serving round (ISSUE-19): the same stream set at
    tp=1 vs tp=2 (and tp=4 when the box has the devices and the model the
    heads), reporting per-arm generated tokens/sec, the per-decode-step
    tensor-parallel collective bytes at fp32 vs blockwise-int8
    (EQuARX-style wire shrink), and whether the sharded arms stayed
    bit-identical (the concat-partitioned contract). On a real multi-chip
    backend the tp arms must hold >= 0.8x linear scaling; CPU "devices"
    are virtual slices of one socket, so there the scaling ratio is
    reported but not asserted."""
    import jax

    import paddle_tpu.models.generation as G
    from paddle_tpu.serving import Engine

    ndev = jax.device_count()
    if ndev < 2:
        return {"skipped": f"{ndev} visible device(s), tp needs >= 2"}
    arch_key, _, params, _ = G.gpt_decode_state(model)
    heads = arch_key[1]
    tps = [1, 2] + [4] * (ndev >= 4 and heads % 4 == 0)
    sub = prompts[: min(16, len(prompts))]
    arms, outs = {}, {}
    for tp in tps:
        kw = dict(ekw, tp=tp) if tp > 1 else dict(ekw)
        with Engine(model, **kw) as eng:
            warm = [eng.submit(p, max_new_tokens=max_new) for p in sub]
            [h.result(timeout=600) for h in warm]
            t0 = time.monotonic()
            hs = [eng.submit(p, max_new_tokens=max_new) for p in sub]
            res = [h.result(timeout=600) for h in hs]
            wall = time.monotonic() - t0
        gen = sum(len(o) - len(p) for o, p in zip(res, sub))
        arms[tp] = round(gen / max(wall, 1e-9), 1)
        outs[tp] = res
    fp32_b, int8_b = G.tp_collective_bytes(arch_key, params, ekw["max_batch"], 2)
    scaling = {str(tp): round(arms[tp] / max(tp * arms[1], 1e-9), 3)
               for tp in tps if tp > 1}
    if on_tpu:
        for tp, ratio in scaling.items():
            assert ratio >= 0.8, \
                f"tp={tp} scaling {ratio} below the 0.8x-linear floor"
    return {
        "devices": ndev,
        "tokens_per_sec": {str(tp): arms[tp] for tp in tps},
        "linear_scaling": scaling,
        "scaling_asserted": bool(on_tpu),
        "identical_tokens": all(outs[tp] == outs[1] for tp in tps[1:]),
        "collective_bytes_per_step_fp32": fp32_b,
        "collective_bytes_per_step_int8": int8_b,
        "int8_wire_shrink": round(fp32_b / max(int8_b, 1), 3),
    }


def _bench_serving_chunked_prefill(np, model, vocab, ekw, max_new, on_tpu):
    """Chunked-prefill A/B (ISSUE-19): short streams decode while long
    prompts are admitted mid-flight; the victims' decode-stall p99 (the
    worst inter-token gap — a monolithic prefill freezes every live stream
    for the whole pass) must come down when the same admits run one
    FLAGS_serve_prefill_chunk-sized chunk per scheduler step."""
    import threading

    from paddle_tpu.serving import Engine

    rng = np.random.RandomState(5)
    n_vic, long_len = (8, 768) if on_tpu else (4, 96)
    chunk = ekw["block_size"] * 2
    victims = [rng.randint(0, vocab, (6,)).tolist() for _ in range(n_vic)]
    longs = [rng.randint(0, vocab, (long_len,)).tolist() for _ in range(2)]

    # victims need enough decode steps to still be live while the longs
    # prefill (the whole point of the stall probe) even when the outer
    # bench runs a tiny max_new on the CPU tier
    vic_new = max(max_new, 12)

    def arm(chunked):
        kw = dict(ekw, prefill_chunk=chunk) if chunked else dict(ekw)
        gaps = []
        with Engine(model, **kw) as eng:
            warm = [eng.submit(p, max_new_tokens=max_new)
                    for p in victims + longs]
            [h.result(timeout=600) for h in warm]
            hs = [eng.submit(v, max_new_tokens=vic_new, temperature=0.0,
                             stream=True)
                  for v in victims]
            rows = [[] for _ in hs]

            def consume(h, out):
                last = time.monotonic()
                for _tok in h:
                    now = time.monotonic()
                    out.append(now - last)
                    last = now

            threads = [threading.Thread(target=consume, args=(h, rows[i]))
                       for i, h in enumerate(hs)]
            [t.start() for t in threads]
            deadline = time.monotonic() + 60
            while eng.stats()["decode_steps"] < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.002)
            lh = [eng.submit(p, max_new_tokens=2) for p in longs]
            [t.join() for t in threads]
            [h.result(timeout=600) for h in lh]
        # drop each victim's first gap (TTFT, includes its own prefill) —
        # the stall metric is the DECODE inter-token gap
        for r in rows:
            gaps.extend(r[1:])
        gaps.sort()
        return {
            "decode_stall_p99_s": round(
                gaps[min(len(gaps) - 1, int(len(gaps) * 0.99))], 4),
            "decode_stall_max_s": round(gaps[-1], 4),
        }

    mono = arm(False)
    chunked = arm(True)
    return {
        "victims": n_vic,
        "long_prompt_tokens": long_len,
        "chunk_tokens": chunk,
        "monolithic": mono,
        "chunked": chunked,
        "stall_p99_reduced": chunked["decode_stall_p99_s"]
        < mono["decode_stall_p99_s"],
    }


def _bench_serving_paged_kernel(np, model, ekw, prompts, max_new):
    """Decode A/B (ISSUE-18): the gather-then-dense paged read vs the
    block-table-aware Pallas paged-attention kernel behind
    ``FLAGS_serve_paged_kernel``, same prompts both arms. Reports per-arm
    generated tokens/sec, the speedup, and whether the outputs stayed
    bit-identical (the kernel's correctness contract — a False here is a
    bug, not a perf note)."""
    from paddle_tpu.framework import flags
    from paddle_tpu.serving import Engine

    sub = prompts[: min(16, len(prompts))]
    arms, outs = {}, {}
    for arm, on in (("gather", False), ("kernel", True)):
        old = flags._FLAGS.get("FLAGS_serve_paged_kernel")
        flags._FLAGS["FLAGS_serve_paged_kernel"] = on
        try:
            with Engine(model, **ekw) as eng:
                warm = [eng.submit(p, max_new_tokens=max_new) for p in sub]
                [h.result(timeout=600) for h in warm]
                t0 = time.monotonic()
                hs = [eng.submit(p, max_new_tokens=max_new) for p in sub]
                res = [h.result(timeout=600) for h in hs]
                wall = time.monotonic() - t0
        finally:
            if old is None:
                flags._FLAGS.pop("FLAGS_serve_paged_kernel", None)
            else:
                flags._FLAGS["FLAGS_serve_paged_kernel"] = old
        gen = sum(len(o) - len(p) for o, p in zip(res, sub))
        arms[arm] = round(gen / max(wall, 1e-9), 1)
        outs[arm] = res
    return {
        "streams": len(sub),
        "gather_tokens_per_sec": arms["gather"],
        "kernel_tokens_per_sec": arms["kernel"],
        "speedup": round(arms["kernel"] / max(arms["gather"], 1e-9), 3),
        "identical_tokens": outs["gather"] == outs["kernel"],
    }


def bench_kernel_autotune(paddle, jax, np, on_tpu):
    """Kernel-registry autotune A/B (ISSUE-18): a real measured-timing
    search over the flash-attention config space against a throwaway tuning
    DB, then steady-state timing of the tuned config vs the pinned default,
    a gather-vs-kernel paged-decode step A/B, and the DB hit/miss/search
    accounting. Prints ONE `KERNEL_PERF` JSON line and returns the same
    dict for extra_metrics. The run's tune dir is a temp dir — the
    benchmark never pollutes (or benefits from) the user's cache."""
    import shutil
    import tempfile

    import jax.numpy as jnp

    import paddle_tpu.models.generation as G
    from paddle_tpu import profiler as _prof
    from paddle_tpu.framework import flags
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining
    from paddle_tpu.ops import kernels as K
    from paddle_tpu.ops.kernels import autotune as _autotune
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_array

    if on_tpu:
        b, h, t, d, dtype = 1, 8, 8192, 128, jnp.bfloat16
        samples, budget_s = 5, 120.0
    else:
        # interpret-mode Pallas is slow: small shape, few samples
        b, h, t, d, dtype = 1, 2, 256, 32, jnp.float32
        samples, budget_s = 2, 10.0

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, t, d), dtype)
    kk = jnp.asarray(rng.randn(b, h, t, d), dtype)
    v = jnp.asarray(rng.randn(b, h, t, d), dtype)

    def time_fn(fn, *args):
        jax.block_until_ready(fn(*args))
        ts = []
        for _ in range(samples):
            t0 = time.monotonic()
            jax.block_until_ready(fn(*args))
            ts.append(time.monotonic() - t0)
        ts.sort()
        return ts[len(ts) // 2] * 1e3

    def flash_with(cfg):
        return jax.jit(lambda a, b_, c: flash_attention_array(
            a, b_, c, causal=True, block_q=int(cfg["block_q"]),
            block_k=int(cfg["block_k"])))

    key = K.flash_attention_key(b, h, t, t, d, q.dtype, True)
    default_cfg = dict(K.get_kernel("flash_attention").defaults)

    tune_td = tempfile.mkdtemp(prefix="bench_tune_")
    knobs = {"FLAGS_kernel_autotune": "search",
             "FLAGS_kernel_tune_dir": tune_td,
             "FLAGS_kernel_tune_samples": samples,
             "FLAGS_kernel_tune_budget_s": budget_s}
    old = {k_: flags._FLAGS.get(k_) for k_ in knobs}
    try:
        flags._FLAGS.update(knobs)
        _autotune.clear_cache()
        c0 = _prof.counters()
        t0 = time.monotonic()
        tuned_cfg = K.resolve_config("flash_attention", key)
        search_s = time.monotonic() - t0
        # rerun with a cold memo: must be a pure disk hit, zero re-search
        _autotune.clear_cache()
        K.resolve_config("flash_attention", key)
        c1 = _prof.counters()
    finally:
        for k_, v_ in old.items():
            if v_ is None:
                flags._FLAGS.pop(k_, None)
            else:
                flags._FLAGS[k_] = v_
        shutil.rmtree(tune_td, ignore_errors=True)
        _autotune.clear_cache()

    default_ms = time_fn(flash_with(default_cfg), q, kk, v)
    tuned_ms = time_fn(flash_with(tuned_cfg), q, kk, v)

    # paged decode: gather builder vs Pallas-kernel builder, one step
    paddle.seed(0)
    if on_tpu:
        gcfg = GPTConfig(vocab_size=8192, hidden_size=512, num_layers=4,
                         num_heads=8, max_position_embeddings=2048,
                         hidden_dropout=0.0, attention_dropout=0.0)
        B, BS, MB, NB = 64, 16, 16, 2048
    else:
        gcfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                         num_heads=2, max_position_embeddings=256,
                         hidden_dropout=0.0, attention_dropout=0.0)
        B, BS, MB, NB = 8, 8, 4, 64
    model = GPTForPretraining(gcfg)
    model.eval()
    _, arch, params, _ = G.gpt_decode_state(model)
    L, KV, D = len(params["layers"]), arch["kv_heads"], arch["head_dim"]
    kpool = jnp.zeros((L, NB, BS, KV, D), jnp.float32)
    vpool = jnp.zeros((L, NB, BS, KV, D), jnp.float32)
    perm = rng.permutation(np.arange(1, NB))[: B * MB]
    tables = jnp.asarray(perm.reshape(B, MB).astype(np.int32))
    pos = jnp.asarray(rng.randint(0, BS * MB, (B,)).astype(np.int32))
    toks = jnp.asarray(rng.randint(0, gcfg.vocab_size, (B,)).astype(np.int32))
    temps = jnp.zeros((B,), jnp.float32)
    pkey = jax.random.PRNGKey(0)
    gather_fn = jax.jit(G.build_paged_decode(arch, B, BS, MB))
    kernel_fn = jax.jit(G.build_paged_decode_kernel(arch, B, BS, MB))
    args = (params, kpool, vpool, tables, pos, toks, temps, pkey)
    gather_ms = time_fn(gather_fn, *args)
    kernel_ms = time_fn(kernel_fn, *args)

    def delta(name):
        return c1.get(name, 0) - c0.get(name, 0)

    line = {
        "name": "kernel autotune A/B",
        "flash": {
            "shape": f"b{b} h{h} t{t} d{d} {np.dtype(dtype).name} causal",
            "default_config": default_cfg, "tuned_config": tuned_cfg,
            "default_ms": round(default_ms, 3),
            "tuned_ms": round(tuned_ms, 3),
            "speedup": round(default_ms / max(tuned_ms, 1e-9), 3),
        },
        "paged_decode": {
            "shape": f"B{B} L{L} h{gcfg.hidden_size} blocks{MB}x{BS}",
            "gather_ms": round(gather_ms, 3),
            "kernel_ms": round(kernel_ms, 3),
            "speedup": round(gather_ms / max(kernel_ms, 1e-9), 3),
        },
        "db": {"search_s": round(search_s, 2),
               "searches": delta("kernel_tune_searches"),
               "candidates": delta("kernel_tune_candidates"),
               "hits": delta("kernel_tune_hits"),
               "misses": delta("kernel_tune_misses"),
               "rejects": delta("kernel_tune_db_rejects"),
               "budget_stops": delta("kernel_tune_budget_stops")},
    }
    print("KERNEL_PERF " + json.dumps(line))
    return line


def _bench_serving_recovery(np, model, ekw, prompts, max_new):
    """Crash-recovery A/B (ISSUE-17): the same injected mid-decode crash
    recovered two ways — the PR 12 re-prefill/requeue path vs snapshot
    re-attach (``snapshot=True``). Reports, per arm, the supervisor's
    detect→recover MTTR, the crash→fully-drained wall (the serving-level
    MTTR: when the service has actually caught up), and how many tokens
    were re-prefilled vs how many KV blocks re-attached. The acceptance
    bar: re-attach re-prefills ZERO tokens and drains faster than
    re-prefill (``mttr_speedup_x`` > 1)."""
    from paddle_tpu import profiler as _prof
    from paddle_tpu.fault import inject
    from paddle_tpu.serving import ServingSupervisor

    n = min(16, len(prompts))
    ps = prompts[:n]
    out = {"streams": n, "max_new": max_new}
    try:
        for name, snap in (("reprefill", False), ("reattach", True)):
            c0 = _prof.counters()
            inject.arm("serve.crash:at=6")
            with ServingSupervisor(model, watchdog_s=5.0, snapshot=snap,
                                   **ekw) as sup:
                hs = [sup.submit(p, max_new_tokens=max_new) for p in ps]
                deadline = time.monotonic() + 120
                while not inject.fired_counts().get("serve.crash") \
                        and time.monotonic() < deadline:
                    time.sleep(0.002)
                t0 = time.monotonic()
                [h.result(timeout=600) for h in hs]
                drain = time.monotonic() - t0
                assert sup.restarts == 1
                mode = sup.health()["last_recovery"]["mode"]
            inject.disarm()
            c1 = _prof.counters()

            def d(k):
                return c1.get(k, 0) - c0.get(k, 0)

            out[name] = {
                "mode": mode,
                "supervisor_mttr_ms": d("serve_restart_mttr_ms"),
                "crash_to_drained_s": round(drain, 3),
                "reprefill_tokens": d("serve_reprefill_tokens"),
                "reattached_blocks": d("serve_reattached_blocks"),
                "reprefill_tokens_saved": d("serve_reprefill_tokens_saved"),
            }
    finally:
        inject.disarm()
    out["mttr_speedup_x"] = round(
        out["reprefill"]["crash_to_drained_s"]
        / max(out["reattach"]["crash_to_drained_s"], 1e-9), 3)
    return out


def _bench_serving_prefix_spec(np, model, vocab, ekw, on_tpu):
    """High-prefix-overlap workload mode (ROADMAP item 2): every stream
    shares one long system prompt and differs only in a short user tail —
    the agent/chat serving shape. Three arms over identical prompt sets on
    warm executables: OFF (the PR 11 path), prefix cache ON (tail-only
    prefill against shared KV blocks), and prefix+speculative ON. Reports
    `prefix_hit_rate`, `draft_acceptance_rate`, and `speedup_vs_baseline`
    (cache-on tokens/sec over cache-off) — the ISSUE-16 acceptance bar is
    >= 2x on this workload."""
    from paddle_tpu import profiler as _prof
    from paddle_tpu.serving import Engine

    if on_tpu:
        streams, shared_len, tail_lo, tail_hi, max_new = 128, 768, 8, 48, 32
        spec_k = 4
    else:
        # the shared prefix is most of max_seq_len (the agent-loop shape:
        # a big system prompt + a short user turn). Concurrency and pool
        # are kept SMALL: CPU XLA pays one whole-pool copy-on-write per
        # paged-decode/tail-prefill call (the gather forces the scatter
        # chain off the in-place path — a harness artifact, not a TPU
        # cost), so the pool is sized to just hold max_batch full prompts
        # plus the cache, keeping that artifact out of the A/B's signal
        streams, shared_len, tail_lo, tail_hi, max_new = 64, 224, 4, 12, 2
        spec_k = 2
        ekw = dict(ekw, max_seq_len=256, num_blocks=160, max_batch=8)
    rng = np.random.RandomState(1)
    shared = rng.randint(0, vocab, (shared_len,)).tolist()

    def wave():
        return [shared + rng.randint(0, vocab,
                                     (int(rng.randint(tail_lo, tail_hi)),)).tolist()
                for _ in range(streams)]

    warm_prompts, warm2_prompts, timed_prompts = wave(), wave(), wave()
    arms = {
        "off": {},
        "cache": {"prefix_cache": True},
        "cache+spec": {"prefix_cache": True, "spec_k": spec_k},
    }
    out = {"streams": streams, "shared_prefix_len": shared_len,
           "max_new": max_new, "spec_k": spec_k}
    tps = {}
    for name, extra in arms.items():
        with Engine(model, **dict(ekw, **extra)) as eng:
            # two untimed warm waves: the first compiles the full-length
            # buckets and (when armed) populates the prefix index with the
            # shared system prompt — its streams all MISS an empty cache —
            # and the second exercises the hit path so every tail-prefill
            # bucket the timed wave will touch is already compiled
            for wp in (warm_prompts, warm2_prompts):
                [h.result(timeout=600) for h in
                 [eng.submit(p, max_new_tokens=max_new) for p in wp]]
            c0 = _prof.counters()
            t0 = time.monotonic()
            hs = [eng.submit(p, max_new_tokens=max_new) for p in timed_prompts]
            outs = [h.result(timeout=600) for h in hs]
            wall = time.monotonic() - t0
            c1 = _prof.counters()
            eng._pool.check()
        assert all(len(o) == len(p) + max_new
                   for o, p in zip(outs, timed_prompts))
        gen = sum(max_new for _ in outs)
        tps[name] = gen / wall
        d = {k: c1.get(k, 0) - c0.get(k, 0) for k in (
            "serve_prefix_hits", "serve_prefix_misses",
            "serve_draft_proposed", "serve_draft_accepted")}
        if name == "cache":
            hits, misses = d["serve_prefix_hits"], d["serve_prefix_misses"]
            out["prefix_hit_rate"] = round(hits / max(hits + misses, 1), 4)
        if name == "cache+spec":
            out["draft_acceptance_rate"] = round(
                d["serve_draft_accepted"] / max(d["serve_draft_proposed"], 1), 4)
    # acceptance probe: the timed wave's short generations barely decode, so
    # the steady-state acceptance rate comes from a longer greedy pass (the
    # n-gram drafter feeds on the stream's own repetition, which needs tokens)
    with Engine(model, **dict(ekw, prefix_cache=True, spec_k=spec_k)) as eng:
        c0 = _prof.counters()
        [h.result(timeout=600) for h in
         [eng.submit(p, max_new_tokens=8 * max_new)
          for p in timed_prompts[:streams // 4]]]
        c1 = _prof.counters()
    prop = c1.get("serve_draft_proposed", 0) - c0.get("serve_draft_proposed", 0)
    acc = c1.get("serve_draft_accepted", 0) - c0.get("serve_draft_accepted", 0)
    out["draft_acceptance_rate_long"] = round(acc / max(prop, 1), 4)
    out["tokens_per_sec_off"] = round(tps["off"], 1)
    out["tokens_per_sec_cached"] = round(tps["cache"], 1)
    out["tokens_per_sec_cached_spec"] = round(tps["cache+spec"], 1)
    out["speedup_vs_baseline"] = round(tps["cache"] / tps["off"], 3)
    out["speedup_spec_vs_baseline"] = round(tps["cache+spec"] / tps["off"], 3)
    return out


def _bench_serving_overload(np, model, ekw, prompts, max_new,
                            sustainable_rps, p99_unloaded):
    """Overload window: offer requests open-loop at 4x the closed-loop
    sustainable rate into an engine with load shedding + per-request
    deadlines armed. The acceptance bar: the engine sheds (`Overloaded` at
    submit) and early-fails doomed work (`DeadlineExceeded`) instead of
    letting queue latency grow without bound — p99 of ADMITTED requests
    stays within ~2x the unloaded p99, and the page pool conserves."""
    from paddle_tpu.serving import DeadlineExceeded, Engine, Overloaded

    offered_rps = 4.0 * sustainable_rps
    deadline_s = max(0.25, 2.0 * p99_unloaded)
    window_s = 8.0
    ekw = dict(ekw, shed=True, max_queue=max(8, ekw["max_batch"] // 2))
    shed = missed = failed = 0
    lats = []
    with Engine(model, **ekw) as eng:
        # warm every bucket untimed so the window measures scheduling; the
        # warm wave honors the engine's own shed policy by backing off on
        # the retry_after_s hint (the polite-client contract)
        warm = []
        for p in prompts[:ekw["max_batch"]]:
            while True:
                try:
                    warm.append(eng.submit(p, max_new_tokens=max_new))
                    break
                except Overloaded as e:
                    time.sleep(max(e.retry_after_s, 0.01))
        [h.result(timeout=600) for h in warm]
        handles = []
        t0 = time.monotonic()
        i = 0
        while True:
            due = t0 + i / offered_rps
            now = time.monotonic()
            if due > t0 + window_s:
                break
            if due > now:
                time.sleep(due - now)
            try:
                handles.append(eng.submit(prompts[i % len(prompts)],
                                          max_new_tokens=max_new,
                                          deadline_s=deadline_s))
            except Overloaded:
                shed += 1
            i += 1
        for h in handles:
            try:
                h.result(timeout=600)
                lats.append(h.latency_s)
            except DeadlineExceeded:
                missed += 1
            except Exception:
                failed += 1
        eng._pool.check()  # conservation held through the whole storm
    offered = i
    lats.sort()
    p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))] if lats else None
    return {
        "offered_rps": round(offered_rps, 2),
        "offered": offered,
        "window_s": window_s,
        "deadline_s": round(deadline_s, 3),
        "shed_rate": round(shed / max(offered, 1), 4),
        "deadline_miss_rate": round(missed / max(offered - shed, 1), 4),
        "failed": failed,
        "completed": len(lats),
        "p99_latency_s": None if p99 is None else round(p99, 3),
        "p99_vs_unloaded": None if p99 is None
        else round(p99 / max(p99_unloaded, 1e-9), 3),
    }


def main():
    t_start = time.time()
    import numpy as np
    import jax

    import paddle_tpu as paddle

    on_tpu = any(d.platform != "cpu" for d in jax.devices())

    def remaining():
        return _BUDGET_S - (time.time() - t_start)

    try:
        # the primary metric gets the lion's share, but must leave enough
        # slack for the JSON line to print before the driver's hard kill —
        # and never arm past the remaining budget even with slow startup
        with _alarm(min(remaining(), max(30.0, remaining() - 30.0))):
            gpt = bench_gpt(paddle, jax, np, on_tpu)
    except (_BenchTimeout, Exception) as e:
        gpt = {
            "name": "GPT bf16 train", "tokens_per_sec": None,
            "loss": None, "mfu": None, "error": str(e)[:200] or type(e).__name__,
        }
    extras = []
    for fn in (bench_resnet50_aot, bench_resnet50_int8, bench_lenet_eager,
               bench_profiler_overhead, bench_watchdog_overhead,
               bench_verify_overhead, bench_stability_overhead,
               bench_observe_overhead, bench_memory_pressure,
               bench_gpt_1p3b, bench_gpt_8k_flash,
               bench_vit_l_aot, bench_yolov3_aot, bench_llama_1b,
               bench_dp8_gpt, bench_serving, bench_host_embedding,
               bench_kernel_autotune):
        if remaining() < 30.0:
            extras.append({"name": fn.__name__, "skipped": "budget"})
            continue
        try:
            with _alarm(remaining() - 15.0):
                extras.append(fn(paddle, jax, np, on_tpu))
        except (_BenchTimeout, Exception) as e:  # a broken extra must not kill the primary line
            extras.append({"name": fn.__name__, "error": str(e)[:200] or type(e).__name__})

    tokens_per_sec = gpt["tokens_per_sec"]
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")
    # None (not 1.0) when the primary metric died: a driver gating on
    # vs_baseline must not read a dead run as at-parity with the best
    vs_baseline = 1.0 if tokens_per_sec is not None else None
    try:
        platform = jax.devices()[0].platform
        best = None
        if os.path.exists(baseline_path):
            base = json.load(open(baseline_path))
            if base.get("value") and base.get("platform") == platform:
                best = float(base["value"])
                if tokens_per_sec is not None:
                    vs_baseline = tokens_per_sec / best
        if on_tpu and tokens_per_sec is not None and (best is None or tokens_per_sec > best):
            # ratchet: the recorded best only ever goes up, so a future
            # regression is always visible as vs_baseline < 1.0
            json.dump(
                {"value": tokens_per_sec, "unit": "tokens/sec/chip", "platform": platform},
                open(baseline_path, "w"),
            )
    except Exception:
        pass

    # telemetry snapshot: the run's engine counters + a fresh live-buffer
    # census, so every BENCH_*.json is self-describing about cache hits,
    # donation, sync bytes and memory high-water mark
    from paddle_tpu import profiler

    try:
        profiler.memory_census()
        counters = profiler.counters()
        memory = profiler.memory_stats()
    except Exception:
        counters, memory = {}, {}

    # dispatch-gap (ROADMAP item 2): host idle per device step — the primary
    # fused-step loop's measured block time, falling back to the lazy
    # (LeNet) loop's when the primary died
    gap = gpt.get("dispatch_gap_ms_per_step")
    if gap is None:
        gap = next(
            (e.get("dispatch_gap_ms_per_step") for e in extras
             if e.get("dispatch_gap_ms_per_step") is not None),
            None,
        )

    # training-stability telemetry (ISSUE-13): the last judged sentinel
    # signals (populated by bench_stability_overhead's observed loop; None
    # when no sentinel ran) plus the skip/rollback counters — every BENCH
    # line reports whether the run quarantined or rolled back anything
    try:
        from paddle_tpu.fault import sentinel as _sentinel

        _stab = _sentinel.last_signals()
    except Exception:
        _stab = {}

    # HBM resilience telemetry (ISSUE-14): the most recent preflight
    # prediction (populated by bench_memory_pressure's enforce loop; None
    # when admission never ran) plus the ladder/admission counters — every
    # BENCH line reports whether the run predicted, rejected, or recovered
    try:
        from paddle_tpu.fault import memory as _hbm_mem

        _hbm = _hbm_mem.last_prediction()
    except Exception:
        _hbm = {}

    print(
        json.dumps(
            {
                "metric": gpt["name"] + " throughput",
                "value": tokens_per_sec,
                "unit": "tokens/sec/chip",
                "vs_baseline": round(vs_baseline, 3) if vs_baseline is not None else None,
                "loss": gpt["loss"],
                "mfu": gpt["mfu"],
                "dispatch_gap_ms_per_step": gap,
                "grad_global_norm": _stab.get("grad_norm"),
                "loss_ema": _stab.get("loss_ema"),
                "stability_skips": counters.get("stability_skips", 0),
                "stability_rollbacks": counters.get("stability_rollbacks", 0),
                "hbm_predicted_peak_bytes": _hbm.get("hbm_predicted_peak_bytes"),
                "hbm_oom_recoveries": counters.get("hbm_oom_recoveries", 0),
                "hbm_admission_rejects": counters.get("hbm_admission_rejects", 0),
                # host-embedding PS telemetry (ISSUE-15): hot-cache hit rate
                # + cross-rank push bytes from the run's counters
                "host_emb_hot_hit_rate": round(
                    counters.get("host_emb_hot_hits", 0)
                    / max(counters.get("host_emb_hot_hits", 0)
                          + counters.get("host_emb_hot_misses", 0), 1), 4),
                "host_emb_push_bytes": counters.get("host_emb_push_bytes", 0),
                # kernel-autotune telemetry (ISSUE-18): DB hit/miss counts
                # for the run — nonzero only when FLAGS_kernel_autotune ran
                "kernel_tune_hits": counters.get("kernel_tune_hits", 0),
                "kernel_tune_misses": counters.get("kernel_tune_misses", 0),
                "platform": jax.devices()[0].platform,
                "wall_s": round(time.time() - t_start, 1),
                **({"error": gpt["error"]} if gpt.get("error") else {}),
                "counters": counters,
                "memory": memory,
                "extra_metrics": extras,
            }
        )
    )


if __name__ == "__main__":
    main()
