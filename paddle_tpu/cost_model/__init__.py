"""paddle.cost_model (reference python/paddle/cost_model/cost_model.py):
per-op and whole-program cost estimation.

The reference ships a measured static table (static_op_benchmark.json) plus
a profiler-measured mode. TPU-first: costs come from XLA itself —
``jit(...).lower().compile().cost_analysis()`` gives flops/bytes per
compiled program, and per-op timings are measured on the live backend, so
the numbers track the REAL compiler and chip instead of a frozen table.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

__all__ = ["CostModel", "executable_memory"]


def executable_memory(compiled) -> Optional[Dict[str, int]]:
    """Per-executable memory footprint from XLA's ``memory_analysis()``
    (the memory-side sibling of the ``cost_analysis()`` wrap above):
    argument/output/temp/alias bytes plus the derived ``peak_bytes``
    (argument + output + temp − alias — the aliased share reuses donated
    input buffers, so it must not count twice). None when the backend
    doesn't expose the analysis. fault/memory.py keys these dicts like the
    lazy executable cache and feeds the preflight HBM admission check."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None

    def g(name):
        return int(getattr(ma, name, 0) or 0)

    arg = g("argument_size_in_bytes")
    out = g("output_size_in_bytes")
    tmp = g("temp_size_in_bytes")
    alias = g("alias_size_in_bytes")
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "alias_bytes": alias,
        "peak_bytes": max(arg + out + tmp - alias, 0),
    }


class CostModel:
    def __init__(self):
        self._static_cache: Dict[tuple, dict] = {}

    # -- whole-program analysis (reference profile_measure) ------------------
    def profile_measure(self, program=None, startup_program=None,
                        device="tpu", fetch_cost_list=("time",), fn=None,
                        args=None, iters=10):
        """Measure a compiled program. Either pass a ``static.Program``-backed
        callable via ``fn``/``args`` or a traced Program with a runner.
        Returns {"time": ms_per_iter, "flops": ..., "bytes": ...}."""
        import jax

        if fn is None and program is not None and hasattr(program, "_fn"):
            fn, args = program._fn, program._example_args
        if fn is None:
            raise ValueError("pass fn=<jittable callable>, args=<inputs>")
        from ..core import lazy as lazy_mod

        jitted = jax.jit(fn)
        out = jitted(*args)
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
        # monotonic clock (wall time jumps under NTP/VM migration — the
        # analysis monotonic-deadline class) and an ATTRIBUTED device wait:
        # the readback rides lazy.timed_block so it lands as a `block` span
        # (+ lazy_block_ns) instead of hiding inside a host fetch, with an
        # unconditional barrier behind it (timed_block is a no-op for
        # already-ready arrays and when FLAGS_lazy_async is off).
        t0 = time.monotonic()
        for _ in range(iters):
            out = jitted(*args)
        leaves = jax.tree_util.tree_leaves(out)
        lazy_mod.timed_block(leaves, "cost_model.profile_measure")
        jax.block_until_ready(leaves)
        dt = (time.monotonic() - t0) / iters
        cost = {}
        try:
            analysis = jitted.lower(*args).compile().cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0]
            cost["flops"] = float(analysis.get("flops", 0.0))
            cost["bytes"] = float(analysis.get("bytes accessed", 0.0))
        except Exception:
            pass
        cost["time"] = dt * 1e3  # ms, reference units
        return cost

    # -- kernel-config cost estimates (ops/kernels autotune ordering) --------
    def kernel_estimate(self, name, key, config):
        """Analytic cost estimate (ms-scale score) for one tunable-kernel
        config at one shape bucket — the ordering heuristic that decides
        which candidates the measured-timing search visits FIRST under its
        budget (``ops/kernels/autotune.candidates``). The model is the
        standard roofline sum the XLA ``cost_analysis`` numbers decompose
        into — flops/peak + bytes/bandwidth — plus the two terms XLA's
        per-program numbers miss but block-size tuning lives on: a
        per-grid-program launch overhead and the padding waste when a block
        doesn't tile its axis. Relative order is all that matters; an
        unknown kernel scores 0.0 (neutral — stub kernels keep declared
        order)."""
        import jax

        try:
            platform = jax.devices()[0].platform
        except Exception:
            platform = "cpu"
        # coarse per-platform peaks; only RATIOS matter for ordering
        peak_flops = 180e12 if platform == "tpu" else 1e11
        peak_bw = 7e11 if platform == "tpu" else 5e10
        overhead_ms = 2e-3 if platform == "tpu" else 2e-2

        def pad(n, b):
            b = max(int(b), 1)
            return (-(-int(n) // b)) * b

        if name == "flash_attention":
            bh, h, t, t_kv, d, dtype, causal = key
            bq, bk = int(config["block_q"]), int(config["block_k"])
            tq, tk = pad(t, bq), pad(t_kv, bk)
            flops = 4.0 * bh * tq * tk * d * (0.5 if causal else 1.0)
            bytes_ = 2.0 * bh * (tq + 2 * tk) * d * 4
            progs = bh * (tq // min(bq, tq))
            # VMEM pressure: both tiles plus accumulators must fit
            vmem = (bq * d + 2 * bk * d + bq * bk) * 4
            spill = 4.0 if vmem > 8 * 1024 * 1024 else 1.0
        elif name == "fused_ce":
            n, d, v, dtype = key
            br = int(config["block_rows"])
            nr = pad(n, br)
            # fwd + remat-bwd: 3 block-logits gemms over the padded rows
            flops = 3.0 * 2.0 * nr * d * v
            bytes_ = (nr * d + 2 * v * d + br * v) * 4.0
            progs = nr // br
            vmem = br * v * 4
            spill = 4.0 if vmem > 16 * 1024 * 1024 else 1.0
        elif name == "paged_attention":
            b, mb, bs, kv, rep, d, dtype = key
            r = int(config["rows_per_program"])
            t_pad = mb * bs
            # "live" scores ~half the padded context on average; "full" all
            frac = 0.5 if config.get("score_mode") == "live" else 1.0
            flops = 4.0 * b * kv * rep * t_pad * d * frac
            bytes_ = 2.0 * b * t_pad * kv * d * 2.0 + b * kv * rep * d * 4
            progs = b // max(r, 1)
            vmem = 2 * t_pad * kv * d * 4 * r
            spill = 4.0 if vmem > 8 * 1024 * 1024 else 1.0
        elif name == "int8_matmul":
            m, k_dim, n, transpose_w, dtype = key
            bn = int(config["block_n"])
            nn = pad(n, min(bn, n))
            flops = 2.0 * m * k_dim * nn
            bytes_ = k_dim * nn * 1.0 + m * k_dim * 4 + m * nn * 4
            progs = nn // min(bn, nn)
            vmem = (min(bn, nn) * k_dim + m * k_dim) * 4
            spill = 4.0 if vmem > 8 * 1024 * 1024 else 1.0
        elif name == "tp_collective":
            # per-decode-step tensor-parallel all_gather term (serving
            # PR 19): key = (wire_bytes, tp). A ring gather moves
            # (tp-1)/tp of the payload per hop over the slowest link;
            # count the whole payload once (upper bound, ordering-safe)
            # plus one launch overhead per collective boundary — the
            # engine uses this as the shed-ETA floor while its measured
            # decode EMA is still cold.
            wire_bytes, tp = key
            ici_bw = 1e11 if platform == "tpu" else 5e9
            boundaries = max(int(tp) - 1, 1)
            return (float(wire_bytes) / ici_bw) * 1e3 \
                + boundaries * overhead_ms
        else:
            return 0.0
        ms = (flops / peak_flops + bytes_ / peak_bw) * 1e3 * spill
        return ms + progs * overhead_ms

    # -- per-op costs (reference static_cost_data/get_static_op_time) --------
    def static_cost_data(self):
        """The measured per-op table built so far (op → cost dict)."""
        return {f"{k[0]}/{k[1]}/{k[2]}/{k[3]}": v
                for k, v in self._static_cache.items()}

    def get_static_op_time(self, op_name, forward=True, dtype="float32",
                           shape=(1024, 1024)):
        """Measure (and cache) one op's time on the live backend — the role
        of the reference's frozen static_op_benchmark.json, but tracking the
        real compiler/chip. Returns {"op_time": ms, "flops": ...}."""
        import jax
        import jax.numpy as jnp

        from ..ops.registry import all_ops

        key = (op_name, bool(forward), str(dtype), tuple(shape))
        if key in self._static_cache:
            return self._static_cache[key]
        ops = all_ops()
        op = ops.get(op_name) or ops.get(f"functional.{op_name}")
        if op is None:
            raise KeyError(f"unknown op {op_name!r} (registry has {len(ops)})")
        rng = np.random.RandomState(0)
        x = rng.rand(*shape).astype(dtype) + 0.5

        import paddle_tpu as paddle

        xt = paddle.to_tensor(x)
        if forward:
            def run():
                return op(xt)
        else:
            xt.stop_gradient = False

            def run():
                out = op(xt)
                out = out[0] if isinstance(out, (tuple, list)) else out
                out.sum().backward()
                g = xt.grad
                xt.clear_grad()
                return g
        out = run()
        t0 = time.monotonic()
        for _ in range(5):
            out = run()
        o = out[0] if isinstance(out, (tuple, list)) else out
        # Tensor.numpy() routes through the lazy.timed_block funnel, so the
        # sync that closes the timed region is already an attributed block
        float(np.asarray(o.numpy()).ravel()[0])
        cost = {"op_time": (time.monotonic() - t0) / 5 * 1e3, "dtype": str(dtype)}
        self._static_cache[key] = cost
        return cost
