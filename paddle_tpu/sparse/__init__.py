"""paddle.sparse — COO/CSR sparse tensors and ops.

Parity: reference ``paddle/phi/core/sparse_coo_tensor.h`` /
``sparse_csr_tensor.h``, kernels in ``paddle/phi/kernels/sparse/``, Python
surface ``python/paddle/incubate/sparse`` (v2.3 namespace; also exposed here
as ``paddle.sparse``). TPU-native substrate: ``jax.experimental.sparse``
BCOO/BCSR — XLA-native batched sparse formats whose matmuls lower to
gather/scatter+MXU programs, differentiable end to end.

SelectedRows (``paddle/phi/core/selected_rows.h:27``) is also here: the
rows+values embedding-gradient format with lazy merge.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.lazy import concrete as _concrete
from jax.experimental import sparse as jsparse

from ..core.dispatch import as_tensor
from ..core.tensor import Tensor


class SparseTensor(Tensor):
    """Base for sparse tensors: wraps a jax.experimental.sparse matrix in the
    Tensor protocol WITHOUT densifying — ``_data`` holds only the stored
    values; shape metadata reflects the logical dense shape. Dense kernels
    require an explicit ``.to_dense()`` (same contract as the reference:
    phi dense kernels reject sparse inputs)."""

    __slots__ = ("_sp",)

    def __init__(self, sp, stop_gradient=True):
        self._sp = sp
        super().__init__(sp.data, stop_gradient=stop_gradient)

    @property
    def shape(self):
        return list(self._sp.shape)

    @property
    def ndim(self):
        return len(self._sp.shape)

    @property
    def size(self):
        return int(np.prod(self._sp.shape))

    @property
    def is_sparse(self):
        return True

    def numpy(self):
        return np.asarray(self._sp.todense())

    def to_dense(self):
        return Tensor(self._sp.todense(), stop_gradient=self.stop_gradient)

    def nnz(self):
        return int(self._sp.nse)

    # dense Tensor methods would silently operate on the 1-D values buffer —
    # block the common ones with a clear error (reference: phi dense kernels
    # raise on sparse inputs)
    def _no_dense(self, *a, **k):
        raise TypeError(
            "dense op on a sparse tensor: use paddle.sparse.* ops or call "
            ".to_dense() first"
        )

    __add__ = __radd__ = __sub__ = __mul__ = __rmul__ = __truediv__ = _no_dense
    __matmul__ = __neg__ = _no_dense
    sum = mean = max = min = reshape = transpose = matmul = _no_dense


class SparseCooTensor(SparseTensor):
    def indices(self):
        return Tensor(jnp.swapaxes(self._sp.indices, 0, 1), stop_gradient=True)

    def values(self):
        return Tensor(self._sp.data, stop_gradient=self.stop_gradient)

    def coalesce(self):
        return SparseCooTensor(self._sp.sum_duplicates(), self.stop_gradient)

    def is_sparse_coo(self):
        return True

    def to_sparse_csr(self):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._sp.sum_duplicates()), self.stop_gradient)


class SparseCsrTensor(SparseTensor):
    def crows(self):
        return Tensor(self._sp.indptr, stop_gradient=True)

    def cols(self):
        return Tensor(self._sp.indices, stop_gradient=True)

    def values(self):
        return Tensor(self._sp.data, stop_gradient=self.stop_gradient)

    def is_sparse_csr(self):
        return True

    def to_sparse_coo(self, sparse_dim=2):
        return SparseCooTensor(self._sp.to_bcoo(), self.stop_gradient)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    """Build a COO tensor (reference sparse_coo_tensor API: indices (ndim, nnz))."""
    idx = np.asarray(as_tensor(indices)._data, np.int32)
    vals = as_tensor(values)._data
    if dtype is not None:
        from ..core import dtype as dtypes

        vals = vals.astype(dtypes.convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    sp = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(sp, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    vals = as_tensor(values)._data
    if dtype is not None:
        from ..core import dtype as dtypes

        vals = vals.astype(dtypes.convert_dtype(dtype))
    sp = jsparse.BCSR(
        (vals, jnp.asarray(as_tensor(cols)._data, jnp.int32),
         jnp.asarray(as_tensor(crows)._data, jnp.int32)),
        shape=tuple(int(s) for s in shape),
    )
    return SparseCsrTensor(sp, stop_gradient=stop_gradient)


def to_sparse_coo(x, sparse_dim=None):
    t = as_tensor(x)
    return SparseCooTensor(jsparse.BCOO.fromdense(t._data), stop_gradient=t.stop_gradient)


def to_sparse_csr(x):
    t = as_tensor(x)
    return SparseCsrTensor(jsparse.BCSR.fromdense(t._data), stop_gradient=t.stop_gradient)


def _sp(x):
    if isinstance(x, SparseTensor):
        return x._sp
    raise TypeError(f"expected a sparse tensor, got {type(x).__name__}")


def _rewrap(sp, like):
    cls = SparseCsrTensor if isinstance(sp, jsparse.BCSR) else SparseCooTensor
    return cls(sp, stop_gradient=like.stop_gradient)


# -- sparse ops (reference phi/kernels/sparse/) ------------------------------

def add(x, y, name=None):
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        a = _sp(x)
        b = _sp(y)
        if tuple(a.shape) != tuple(b.shape):
            raise ValueError(f"sparse.add shape mismatch: {a.shape} vs {b.shape}")
        if isinstance(a, jsparse.BCSR):
            a = a.to_bcoo()
        if isinstance(b, jsparse.BCSR):
            b = b.to_bcoo()
        if a.data.dtype != b.data.dtype:
            b = jsparse.BCOO((b.data.astype(a.data.dtype), b.indices), shape=b.shape)
        out = jsparse.BCOO(
            (jnp.concatenate([a.data, b.data]), jnp.concatenate([a.indices, b.indices])),
            shape=a.shape,
        ).sum_duplicates()
        return _rewrap(out, x)
    # mixed sparse/dense: densify the sparse side
    xd = x.to_dense() if isinstance(x, SparseTensor) else as_tensor(x)
    yd = y.to_dense() if isinstance(y, SparseTensor) else as_tensor(y)
    return Tensor(xd._data + yd._data, stop_gradient=xd.stop_gradient and yd.stop_gradient)


def multiply(x, y, name=None):
    """Elementwise multiply; the result keeps x's sparsity pattern (zero
    entries stay zero, so gathering y at x's coordinates is exact even when
    y is itself sparse)."""
    if not isinstance(x, SparseTensor):
        raise TypeError("sparse.multiply expects a sparse first operand")
    sp = _sp(x)
    coo = sp.to_bcoo() if isinstance(sp, jsparse.BCSR) else sp
    if isinstance(y, SparseTensor):
        yv = y._sp.todense()
    else:
        yv = as_tensor(y)._data
    if hasattr(yv, "ndim") and yv.ndim:
        gathered = yv[tuple(coo.indices[:, i] for i in range(coo.indices.shape[1]))]
    else:
        gathered = yv
    return _rewrap(jsparse.BCOO((coo.data * gathered, coo.indices), shape=coo.shape), x)


def matmul(x, y, name=None):
    """Sparse @ dense -> dense (reference sparse matmul kernel). Lowers to an
    XLA gather/scatter program; differentiable wrt the dense operand and the
    sparse values."""
    sp = _sp(x)
    yt = as_tensor(y)
    out = sp @ yt._data
    res = Tensor(out, stop_gradient=x.stop_gradient and yt.stop_gradient)
    return res


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) * sparse_mask -> sparse (reference masked_matmul):
    only mask's nonzero positions are computed/kept."""
    xt, yt = as_tensor(x), as_tensor(y)
    m = _sp(mask)
    coo = m.to_bcoo() if isinstance(m, jsparse.BCSR) else m
    rows = coo.indices[:, 0]
    cols = coo.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", _concrete(xt._data)[rows], jnp.swapaxes(_concrete(yt._data), 0, 1)[cols])
    return _rewrap(jsparse.BCOO((vals, coo.indices), shape=coo.shape), mask)


def _unary(fn_name, jfn):
    def op(x, name=None):
        sp = _sp(x)
        coo = sp.to_bcoo() if isinstance(sp, jsparse.BCSR) else sp
        return _rewrap(jsparse.BCOO((jfn(coo.data), coo.indices), shape=coo.shape), x)

    op.__name__ = fn_name
    op.__doc__ = f"Sparse elementwise {fn_name} on stored values (phi/kernels/sparse)."
    return op


relu = _unary("relu", jax.nn.relu)
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
abs = _unary("abs", jnp.abs)
pow = lambda x, factor, name=None: _unary("pow", lambda v: jnp.power(v, factor))(x)  # noqa: E731
neg = _unary("neg", jnp.negative)
cast = lambda x, index_dtype=None, value_dtype=None, name=None: _unary(  # noqa: E731
    "cast", lambda v: v.astype(value_dtype or v.dtype)
)(x)


def softmax(x, axis=-1, name=None):
    """Sparse softmax over the LAST axis (reference sparse/softmax_kernel
    supports the same): entries sharing all other coordinates form one
    normalization group; missing entries are -inf so normalization is over
    nonzeros only."""
    sp = _sp(x)
    coo = sp.to_bcoo() if isinstance(sp, jsparse.BCSR) else sp
    ndim = len(coo.shape)
    if axis not in (-1, ndim - 1):
        raise NotImplementedError("sparse.softmax supports the last axis only")
    # group id = joint index over all dims except the softmax axis
    if ndim == 2:
        rows = coo.indices[:, 0]
        n_rows = coo.shape[0]
    else:
        lead = tuple(coo.indices[:, i] for i in range(ndim - 1))
        rows = jnp.ravel_multi_index(lead, coo.shape[:-1], mode="clip")
        n_rows = int(np.prod(coo.shape[:-1]))
    row_max = jnp.full((n_rows,), -jnp.inf, coo.data.dtype).at[rows].max(coo.data)
    ex = jnp.exp(coo.data - row_max[rows])
    row_sum = jnp.zeros((n_rows,), coo.data.dtype).at[rows].add(ex)
    return _rewrap(jsparse.BCOO((ex / row_sum[rows], coo.indices), shape=coo.shape), x)


class SelectedRows:
    """Embedding-gradient format (reference phi/core/selected_rows.h:27):
    ``rows[i]`` is the embedding row id of ``value[i]``; duplicates allowed
    until ``merge()`` (reference merge_selected_rows op)."""

    def __init__(self, rows, value, height):
        self.rows = jnp.asarray(as_tensor(rows)._data, jnp.int32)
        self.value = as_tensor(value)._data
        self.height = int(height)

    def merge(self):
        """Sum duplicate rows (merge_selected_rows)."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True, size=self.rows.shape[0], fill_value=-1)
        merged = jnp.zeros((uniq.shape[0],) + self.value.shape[1:], self.value.dtype)
        merged = merged.at[inv].add(self.value)
        keep = uniq >= 0
        return SelectedRows(uniq[keep], merged[keep], self.height)

    def to_dense(self):
        out = jnp.zeros((self.height,) + self.value.shape[1:], self.value.dtype)
        return Tensor(out.at[self.rows].add(self.value), stop_gradient=True)


__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "SelectedRows",
    "sparse_coo_tensor", "sparse_csr_tensor", "to_sparse_coo", "to_sparse_csr",
    "add", "multiply", "matmul", "masked_matmul", "softmax",
    "relu", "sin", "tanh", "sqrt", "abs", "pow", "neg", "cast",
]
