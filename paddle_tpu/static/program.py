"""Program metadata — jaxpr-backed program introspection.

Parity: reference Program IR (``framework.proto`` ProgramDesc/BlockDesc/
OpDesc, ``python/paddle/fluid/framework.py`` Program/Block/Operator). The
TPU-native program IS the traced jaxpr (then XLA HLO); this module exposes
that trace through the reference's introspection surface: ``program.blocks``,
``block.ops``, ``op.type``/``input_names``/``output_names``, ``block.vars``
— so tooling that walks a Program (op counting, pass auditing, debugging)
has the same handles.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np
import jax

from ..core.engine import no_grad
from ..core.tensor import Tensor


class OpDesc:
    """One primitive application (reference framework.proto OpDesc)."""

    def __init__(self, eqn):
        self.type = str(eqn.primitive.name)
        self.input_names = [str(v) for v in eqn.invars]
        self.output_names = [str(v) for v in eqn.outvars]
        self.attrs = {k: v for k, v in eqn.params.items()
                      if isinstance(v, (int, float, str, bool, tuple))}

    def __repr__(self):
        return f"{{Op: {self.type}({', '.join(self.input_names)}) -> ({', '.join(self.output_names)})}}"


class VarDesc:
    def __init__(self, name, aval):
        self.name = name
        self.shape = list(getattr(aval, "shape", ()))
        self.dtype = np.dtype(getattr(aval, "dtype", np.float32))

    def __repr__(self):
        return f"{{Var {self.name}: {self.dtype} {self.shape}}}"


def _flat_eqns(jaxpr):
    """Inline pjit/closed_call wrappers (the eager dispatch jits every op, so
    without inlining the trace reads as a wall of 'pjit' eqns)."""
    out = []
    for e in jaxpr.eqns:
        if e.primitive.name in ("pjit", "jit", "closed_call", "custom_jvp_call", "custom_vjp_call"):
            inner = e.params.get("jaxpr") or e.params.get("call_jaxpr")
            if inner is not None:
                out.extend(_flat_eqns(getattr(inner, "jaxpr", inner)))
                continue
        out.append(e)
    return out


class Block:
    """Reference BlockDesc: the op list + var table of one (sub)jaxpr."""

    def __init__(self, jaxpr, idx=0):
        self.idx = idx
        eqns = _flat_eqns(jaxpr)
        self.ops: List[OpDesc] = [OpDesc(e) for e in eqns]
        self.vars: Dict[str, VarDesc] = {}
        for v in list(jaxpr.invars) + [ov for e in eqns for ov in e.outvars]:
            self.vars[str(v)] = VarDesc(str(v), v.aval)

    def all_op_types(self):
        return [op.type for op in self.ops]


class Program:
    """Reference Program over a traced computation."""

    def __init__(self, closed_jaxpr):
        self._jaxpr = closed_jaxpr
        main = closed_jaxpr.jaxpr
        self.blocks = [Block(main, 0)]
        # sub-blocks: control-flow bodies (cond branches, scan/while bodies)
        # mirror the reference's sub-BlockDescs
        idx = 1
        for eqn in _flat_eqns(main):
            for key in ("jaxpr", "branches", "cond_jaxpr", "body_jaxpr", "call_jaxpr"):
                sub = eqn.params.get(key)
                if sub is None:
                    continue
                subs = sub if isinstance(sub, (tuple, list)) else [sub]
                for sj in subs:
                    inner = getattr(sj, "jaxpr", sj)
                    if hasattr(inner, "eqns"):
                        self.blocks.append(Block(inner, idx))
                        idx += 1

    def global_block(self) -> Block:
        return self.blocks[0]

    def num_ops(self):
        return sum(len(b.ops) for b in self.blocks)

    def __repr__(self):
        return (
            f"{{Program: {len(self.blocks)} block(s), {self.num_ops()} ops; "
            f"main: {', '.join(self.global_block().all_op_types()[:12])}"
            + ("…" if len(self.global_block().ops) > 12 else "") + "}"
        )

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_callable(fn, input_specs: Sequence[Any], layer=None) -> "Program":
        """Trace ``fn(*tensors)`` (a Layer or python fn over Tensors) at the
        given InputSpecs/example tensors and return its Program view."""
        from .input import InputSpec

        layer = layer if layer is not None else (fn if hasattr(fn, "parameters") else None)
        params = [p for _, p in layer.named_parameters()] if layer is not None else []
        buffers = [b for _, b in layer.named_buffers()] if layer is not None else []

        shapes = []
        for s in input_specs:
            if isinstance(s, InputSpec):
                shape = tuple(1 if (d is None or d == -1) else int(d) for d in s.shape)
                shapes.append(jax.ShapeDtypeStruct(shape, np.dtype(s.dtype)))
            elif isinstance(s, Tensor):
                shapes.append(jax.ShapeDtypeStruct(tuple(s.shape), s.dtype))
            else:
                a = np.asarray(s)
                shapes.append(jax.ShapeDtypeStruct(a.shape, a.dtype))

        def pure(*arrays):
            feed = arrays[: len(shapes)]
            param_arrays = arrays[len(shapes):]
            saved = [(t, t._data) for t in params + buffers]
            try:
                for t, a in zip(params, param_arrays):
                    t._data = a
                with no_grad():
                    out = fn(*[Tensor(a, stop_gradient=True) for a in feed])
                outs = out if isinstance(out, (list, tuple)) else [out]
                return tuple(o._data if isinstance(o, Tensor) else o for o in outs)
            finally:
                for t, a in saved:
                    t._data = a

        closed = jax.make_jaxpr(pure)(*shapes, *[p._data for p in params])
        return Program(closed)
