"""Program metadata — jaxpr-backed program introspection.

Parity: reference Program IR (``framework.proto`` ProgramDesc/BlockDesc/
OpDesc, ``python/paddle/fluid/framework.py`` Program/Block/Operator). The
TPU-native program IS the traced jaxpr (then XLA HLO); this module exposes
that trace through the reference's introspection surface: ``program.blocks``,
``block.ops``, ``op.type``/``input_names``/``output_names``, ``block.vars``
— so tooling that walks a Program (op counting, pass auditing, debugging)
has the same handles.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np
import jax

from ..core.engine import no_grad
from ..core.tensor import Tensor


class OpDesc:
    """One primitive application (reference framework.proto OpDesc)."""

    def __init__(self, eqn):
        self.type = str(eqn.primitive.name)
        self.input_names = [str(v) for v in eqn.invars]
        self.output_names = [str(v) for v in eqn.outvars]
        self.attrs = {k: v for k, v in eqn.params.items()
                      if isinstance(v, (int, float, str, bool, tuple))}

    def __repr__(self):
        return f"{{Op: {self.type}({', '.join(self.input_names)}) -> ({', '.join(self.output_names)})}}"


class VarDesc:
    def __init__(self, name, aval):
        self.name = name
        self.shape = list(getattr(aval, "shape", ()))
        self.dtype = np.dtype(getattr(aval, "dtype", np.float32))

    def __repr__(self):
        return f"{{Var {self.name}: {self.dtype} {self.shape}}}"


def _flat_eqns(jaxpr):
    """Inline pjit/closed_call wrappers (the eager dispatch jits every op, so
    without inlining the trace reads as a wall of 'pjit' eqns)."""
    out = []
    for e in jaxpr.eqns:
        if e.primitive.name in ("pjit", "jit", "closed_call", "custom_jvp_call", "custom_vjp_call"):
            inner = e.params.get("jaxpr") or e.params.get("call_jaxpr")
            if inner is not None:
                out.extend(_flat_eqns(getattr(inner, "jaxpr", inner)))
                continue
        out.append(e)
    return out


class Block:
    """Reference BlockDesc: the op list + var table of one (sub)jaxpr."""

    def __init__(self, jaxpr, idx=0):
        self.idx = idx
        eqns = _flat_eqns(jaxpr)
        self.ops: List[OpDesc] = [OpDesc(e) for e in eqns]
        self.vars: Dict[str, VarDesc] = {}
        for v in list(jaxpr.invars) + [ov for e in eqns for ov in e.outvars]:
            self.vars[str(v)] = VarDesc(str(v), v.aval)

    def all_op_types(self):
        return [op.type for op in self.ops]


class Program:
    """Reference Program over a traced computation.

    Beyond the read-only jaxpr view, a Program produced by
    ``from_callable`` keeps its CAPTURE (the pure function + input shapes +
    parameter values), so it supports the reference's program-as-data
    transforms (``python/paddle/fluid/framework.py`` Program.clone/prune,
    ``backward.py:1413`` append_backward, ``:2010`` gradients) by re-tracing
    the capture — the TPU-native equivalent of editing a ProgramDesc.
    """

    def __init__(self, closed_jaxpr, capture=None):
        self._jaxpr = closed_jaxpr
        # capture = (pure, feed_shapes, param_arrays); pure(*feeds, *params)
        self._capture = capture
        main = closed_jaxpr.jaxpr
        self.blocks = [Block(main, 0)]
        # sub-blocks: control-flow bodies (cond branches, scan/while bodies)
        # mirror the reference's sub-BlockDescs
        idx = 1
        for eqn in _flat_eqns(main):
            for key in ("jaxpr", "branches", "cond_jaxpr", "body_jaxpr", "call_jaxpr"):
                sub = eqn.params.get(key)
                if sub is None:
                    continue
                subs = sub if isinstance(sub, (tuple, list)) else [sub]
                for sj in subs:
                    inner = getattr(sj, "jaxpr", sj)
                    if hasattr(inner, "eqns"):
                        self.blocks.append(Block(inner, idx))
                        idx += 1

    def global_block(self) -> Block:
        return self.blocks[0]

    def num_ops(self):
        return sum(len(b.ops) for b in self.blocks)

    def __repr__(self):
        return (
            f"{{Program: {len(self.blocks)} block(s), {self.num_ops()} ops; "
            f"main: {', '.join(self.global_block().all_op_types()[:12])}"
            + ("…" if len(self.global_block().ops) > 12 else "") + "}"
        )


    # -- transforms (capture-level re-traces) ------------------------------
    def _require_capture(self):
        if self._capture is None:
            raise ValueError(
                "this Program is a bare jaxpr view; transforms need a "
                "capture-level Program (build it with Program.from_callable)"
            )
        return self._capture

    @property
    def num_outputs(self):
        return len(self._jaxpr.jaxpr.outvars)

    def clone(self, for_test: bool = True) -> "Program":
        """Re-trace the capture into an independent Program (reference
        Program.clone; for_test has no effect — the capture was traced in
        eval/no-grad mode already)."""
        pure, shapes, param_arrays = self._require_capture()
        return Program(
            jax.make_jaxpr(pure)(*shapes, *param_arrays),
            capture=(pure, shapes, list(param_arrays)),
        )

    def prune(self, targets) -> "Program":
        """Keep only the outputs in ``targets`` (indices); dead ops are
        eliminated (reference Program._prune). The re-trace is followed by an
        explicit DCE pass — tracing alone records every executed op."""
        from jax.interpreters.partial_eval import dce_jaxpr

        pure, shapes, param_arrays = self._require_capture()
        idx = [targets] if isinstance(targets, int) else list(targets)

        def pruned(*arrays):
            outs = pure(*arrays)
            outs = outs if isinstance(outs, (tuple, list)) else [outs]
            return tuple(outs[i] for i in idx)

        closed = jax.make_jaxpr(pruned)(*shapes, *param_arrays)
        try:
            # instantiate=True keeps all invars so the closed-jaxpr binding
            # (consts ↔ constvars, args ↔ invars) stays aligned
            dced, _ = dce_jaxpr(
                closed.jaxpr, [True] * len(closed.jaxpr.outvars), instantiate=True
            )
            closed = closed.replace(jaxpr=dced)
        except Exception:
            pass  # DCE is an optimization of the view; the capture is correct
        return Program(closed, capture=(pruned, shapes, list(param_arrays)))

    def rebind_feeds(self, input_specs) -> "Program":
        """Re-trace at new feed shapes/dtypes (reference feed-var rebinding:
        same ops, new feed/fetch binding)."""
        from .input import InputSpec

        pure, _, param_arrays = self._require_capture()
        shapes = []
        for s in input_specs:
            if isinstance(s, InputSpec):
                shape = tuple(1 if (d is None or d == -1) else int(d) for d in s.shape)
                shapes.append(jax.ShapeDtypeStruct(shape, np.dtype(s.dtype)))
            elif isinstance(s, Tensor):
                shapes.append(jax.ShapeDtypeStruct(tuple(s.shape), s.dtype))
            else:
                a = np.asarray(s)
                shapes.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
        return Program(
            jax.make_jaxpr(pure)(*shapes, *param_arrays),
            capture=(pure, shapes, list(param_arrays)),
        )

    def append_backward(self, loss_index: int = 0) -> "Program":
        """New Program computing (loss, *param_grads) — the reference's
        ``append_backward(loss)`` (backward.py:1413) as a grad re-trace."""
        pure, shapes, param_arrays = self._require_capture()
        n_feed = len(shapes)

        def with_grads(*arrays):
            feeds, ps = arrays[:n_feed], list(arrays[n_feed:])

            def loss_of(ps_):
                outs = pure(*feeds, *ps_)
                loss = outs[loss_index] if isinstance(outs, (tuple, list)) else outs
                return loss

            loss, grads = jax.value_and_grad(loss_of)(ps)
            return (loss, *grads)

        return Program(
            jax.make_jaxpr(with_grads)(*shapes, *param_arrays),
            capture=(with_grads, shapes, list(param_arrays)),
        )

    def gradients(self, target_index: int = 0, input_indices=None) -> "Program":
        """Grads of output[target_index] wrt the given FEED indices (all feeds
        by default) — reference ``gradients(targets, inputs)``
        (backward.py:2010)."""
        pure, shapes, param_arrays = self._require_capture()
        n_feed = len(shapes)
        wrt = list(range(n_feed)) if input_indices is None else (
            [input_indices] if isinstance(input_indices, int) else list(input_indices)
        )

        def grad_fn(*arrays):
            feeds, ps = list(arrays[:n_feed]), list(arrays[n_feed:])

            def target_of(wrt_feeds):
                f2 = list(feeds)
                for j, i in enumerate(wrt):
                    f2[i] = wrt_feeds[j]
                outs = pure(*f2, *ps)
                out = outs[target_index] if isinstance(outs, (tuple, list)) else outs
                return out

            return tuple(jax.grad(target_of)([feeds[i] for i in wrt]))

        return Program(
            jax.make_jaxpr(grad_fn)(*shapes, *param_arrays),
            capture=(grad_fn, shapes, list(param_arrays)),
        )

    def run(self, *feeds):
        """Execute the captured program (params closed in) on feed arrays.
        The jitted callable is cached on the Program — repeat runs dispatch,
        they don't retrace."""
        pure, shapes, param_arrays = self._require_capture()
        jitted = getattr(self, "_jitted", None)
        if jitted is None:
            jitted = self._jitted = jax.jit(pure)
        arrays = [
            f._data if isinstance(f, Tensor) else jax.numpy.asarray(f) for f in feeds
        ]
        outs = jitted(*arrays, *param_arrays)
        return [Tensor(o, stop_gradient=True) for o in (
            outs if isinstance(outs, (tuple, list)) else [outs]
        )]

    # -- construction ------------------------------------------------------
    @staticmethod
    def load(path_prefix: str) -> "TrainableProgram":
        """Load a saved inference artifact as a trainable program (the
        reference load→append_backward→train workflow on a ProgramDesc)."""
        return TrainableProgram.load(path_prefix)

    @staticmethod
    def from_callable(fn, input_specs: Sequence[Any], layer=None) -> "Program":
        """Trace ``fn(*tensors)`` (a Layer or python fn over Tensors) at the
        given InputSpecs/example tensors and return its Program view."""
        from .input import InputSpec

        layer = layer if layer is not None else (fn if hasattr(fn, "parameters") else None)
        params = [p for _, p in layer.named_parameters()] if layer is not None else []
        buffers = [b for _, b in layer.named_buffers()] if layer is not None else []

        shapes = []
        for s in input_specs:
            if isinstance(s, InputSpec):
                shape = tuple(1 if (d is None or d == -1) else int(d) for d in s.shape)
                shapes.append(jax.ShapeDtypeStruct(shape, np.dtype(s.dtype)))
            elif isinstance(s, Tensor):
                shapes.append(jax.ShapeDtypeStruct(tuple(s.shape), s.dtype))
            else:
                a = np.asarray(s)
                shapes.append(jax.ShapeDtypeStruct(a.shape, a.dtype))

        def pure(*arrays):
            feed = arrays[: len(shapes)]
            param_arrays = arrays[len(shapes):]
            saved = [(t, t._data) for t in params + buffers]
            try:
                for t, a in zip(params, param_arrays):
                    t._data = a
                with no_grad():
                    out = fn(*[Tensor(a, stop_gradient=True) for a in feed])
                outs = out if isinstance(out, (list, tuple)) else [out]
                return tuple(o._data if isinstance(o, Tensor) else o for o in outs)
            finally:
                for t, a in saved:
                    t._data = a

        param_arrays = [p._data for p in params]
        closed = jax.make_jaxpr(pure)(*shapes, *param_arrays)
        return Program(closed, capture=(pure, shapes, param_arrays))


class TrainableProgram:
    """A ``jit.save``d artifact reloaded WITH parameters as program inputs
    and a serialized VJP (the ``.pdtrain`` companion written by jit.save), so
    the reference's load → append loss+grads → train workflow
    (``backward.py:1413`` on a loaded ProgramDesc) works without the original
    python model. Gradients flow through the deserialized StableHLO via
    ``jax.export`` vjp; buffers (BN stats) are baked eval-mode constants."""

    def __init__(self, exported, param_names, params, state):
        self._exported = exported
        self.param_names = param_names
        self._params = params  # list of jnp arrays, aligned with param_names
        self._state = state  # full named state dict (numpy), incl. buffers
        self._step = None
        self._loss_fn = None

    @staticmethod
    def load(path_prefix: str) -> "TrainableProgram":
        import json as _json

        with open(path_prefix + ".pdtrain", "rb") as f:
            from ..core.compat import jax_export
            exported = jax_export().deserialize(f.read())
        with open(path_prefix + ".pdtrain.json") as f:
            param_names = _json.load(f)["param_names"]
        from ..framework.io import load as fload

        meta = fload(path_prefix + ".pdiparams")
        state = {k: np.asarray(v._data) for k, v in meta["state"].items()}
        params = [jax.numpy.asarray(state[n]) for n in param_names]
        return TrainableProgram(exported, param_names, params, state)

    def __call__(self, *feeds):
        arrays = [
            f._data if isinstance(f, Tensor) else jax.numpy.asarray(f) for f in feeds
        ]
        outs = self._exported.call(self._params, *arrays)
        outs = outs if isinstance(outs, (tuple, list)) else [outs]
        return [Tensor(o, stop_gradient=True) for o in outs]

    def append_backward(self, loss_fn):
        """Attach ``loss_fn(outputs, *labels) -> scalar`` and build the fused
        train step (fwd through the loaded program + vjp + SGD update)."""
        self._loss_fn = loss_fn
        call = self._exported.call

        @jax.jit
        def step(params, lr, feeds, labels):
            def loss_of(ps):
                outs = call(ps, *feeds)
                outs = outs if isinstance(outs, (tuple, list)) else [outs]
                # loss_fn sees Tensors (paddle losses); grads flow at the
                # array level through jax.value_and_grad, not the eager tape
                outs_t = [Tensor(o, stop_gradient=True) for o in outs]
                labels_t = [Tensor(l, stop_gradient=True) for l in labels]
                loss = loss_fn(outs_t, *labels_t)
                return loss._data if isinstance(loss, Tensor) else loss

            loss, grads = jax.value_and_grad(loss_of)(params)
            new_params = [p - lr * g for p, g in zip(params, grads)]
            return loss, new_params

        self._step = step
        return self

    def gradients(self, feeds, labels):
        """(loss, {param_name: grad}) at the current parameters."""
        if self._loss_fn is None:
            raise ValueError("call append_backward(loss_fn) first")
        call, loss_fn = self._exported.call, self._loss_fn

        def loss_of(ps):
            outs = call(ps, *[_as_array(f) for f in feeds])
            outs = outs if isinstance(outs, (tuple, list)) else [outs]
            outs_t = [Tensor(o, stop_gradient=True) for o in outs]
            labels_t = [Tensor(_as_array(l), stop_gradient=True) for l in labels]
            loss = loss_fn(outs_t, *labels_t)
            return loss._data if isinstance(loss, Tensor) else loss

        loss, grads = jax.value_and_grad(loss_of)(self._params)
        return Tensor(loss), dict(zip(self.param_names, (Tensor(g) for g in grads)))

    def train_step(self, feeds, labels, lr=0.01):
        """One SGD step on the loaded program; updates held params in place."""
        if self._step is None:
            raise ValueError("call append_backward(loss_fn) first")
        feeds_a = tuple(_as_array(f) for f in feeds)
        labels_a = tuple(_as_array(l) for l in labels)
        loss, new_params = self._step(
            self._params, jax.numpy.float32(lr), feeds_a, labels_a
        )
        self._params = list(new_params)
        return Tensor(loss)

    def state_dict(self):
        """Full state with the trained parameter values folded back in."""
        out = {k: Tensor(v) for k, v in self._state.items()}
        for n, p in zip(self.param_names, self._params):
            out[n] = Tensor(p)
        return out


def _as_array(x):
    return x._data if isinstance(x, Tensor) else jax.numpy.asarray(x)
