"""paddle.static facade.

Parity: reference ``python/paddle/static/__init__.py`` — the curated
static-graph API. TPU-native reinterpretation: a "Program" is a captured,
compiled XLA computation (see paddle_tpu/jit); Executor.run compiles+runs it.
The reference's Program/Scope/feed-fetch machinery
(``python/paddle/fluid/framework.py``, ``executor.py:1093``) collapses into
jit tracing, so these entry points adapt the same user workflow onto it.
"""
from __future__ import annotations

from .input import InputSpec  # noqa: F401
from .. import jit as _jit
from ..core.tensor import Tensor


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, program=None, **kwargs):
    """Maps to jit.save of the traced function (reference static/io.py)."""
    raise NotImplementedError(
        "static.save_inference_model: trace with paddle_tpu.jit.to_static and "
        "use paddle_tpu.jit.save (static program capture IS jit capture here)"
    )


def load_inference_model(path_prefix, executor=None, **kwargs):
    layer = _jit.load(path_prefix)
    return layer


class Executor:
    """Compile-and-run adapter (reference Executor.run executor.py:1093)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program):
            args = [Tensor(v) for v in (feed or {}).values()]
            out = program(*args)
            return [o.numpy() for o in (out if isinstance(out, (list, tuple)) else [out])]
        raise NotImplementedError("pass a traced callable as `program`")


def default_main_program():
    return None


def default_startup_program():
    return None


class program_guard:
    def __init__(self, *a, **k):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


# gradient clip re-exports for parity
from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401,E402
