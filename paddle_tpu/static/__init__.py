"""paddle.static facade.

Parity: reference ``python/paddle/static/__init__.py`` — the curated
static-graph API. TPU-native reinterpretation: a "Program" is a captured,
compiled XLA computation (see paddle_tpu/jit); Executor.run compiles+runs it.
The reference's Program/Scope/feed-fetch machinery
(``python/paddle/fluid/framework.py``, ``executor.py:1093``) collapses into
jit tracing, so these entry points adapt the same user workflow onto it.
"""
from __future__ import annotations

from .input import InputSpec  # noqa: F401
from .. import jit as _jit
from ..core.tensor import Tensor


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, program=None, **kwargs):
    """Serialize an inference program + params (reference static/io.py
    ``save_inference_model``; artifact is loadable by ``load_inference_model``
    and ``paddle_tpu.inference.Predictor``).

    TPU-native adaptation: a "program" is a traced callable. ``feed_vars``
    are InputSpecs (``static.data`` returns these) or example Tensors;
    ``fetch_vars`` is the model — a Layer or callable mapping the feeds to
    outputs. (The reference threads Variables of a global Program through
    these arguments; with trace-capture the callable IS the program.)
    """
    fn = program if callable(program) else fetch_vars
    if not callable(fn):
        raise TypeError(
            "save_inference_model: pass the model (Layer or callable) as "
            "fetch_vars (or program=); static Programs are trace-captured here"
        )
    specs = [
        s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
        for s in (feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars])
    ]
    _jit.save(fn, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    layer = _jit.load(path_prefix)
    return layer


class Executor:
    """Compile-and-run adapter (reference Executor.run executor.py:1093)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program):
            args = [Tensor(v) for v in (feed or {}).values()]
            out = program(*args)
            return [o.numpy() for o in (out if isinstance(out, (list, tuple)) else [out])]
        raise NotImplementedError("pass a traced callable as `program`")


def default_main_program():
    return None


def default_startup_program():
    return None


class program_guard:
    def __init__(self, *a, **k):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


# gradient clip re-exports for parity
from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401,E402

from .program import Program, Block, OpDesc, VarDesc, TrainableProgram  # noqa: F401,E402


def load_program(path_prefix):
    """Load a saved inference artifact as a TrainableProgram (reference
    load_inference_model → append_backward workflow; see program.py)."""
    return TrainableProgram.load(path_prefix)


def append_backward(loss=None, program=None, loss_index=0, **kwargs):
    """Reference ``paddle.static.append_backward`` (backward.py:1413) over a
    capture-level Program: returns a new Program computing (loss, *grads)."""
    prog = program if program is not None else loss
    if not isinstance(prog, Program):
        raise TypeError("append_backward needs a static.Program")
    return prog.append_backward(loss_index)


def gradients(targets=None, inputs=None, program=None, target_index=0, **kwargs):
    """Reference ``paddle.static.gradients`` (backward.py:2010) — grads of an
    output wrt feeds, as a re-traced Program."""
    prog = program if program is not None else targets
    if not isinstance(prog, Program):
        raise TypeError("gradients needs a static.Program")
    return prog.gradients(target_index, inputs)

# control-flow ops under static.nn (reference paddle.static.nn.cond/while_loop)
from ..ops import control_flow as nn  # noqa: E402  (module alias: static.nn)

import sys as _sys  # noqa: E402

# register the alias so `import paddle_tpu.static.nn` works (reference idiom)
_sys.modules[__name__ + ".nn"] = nn
