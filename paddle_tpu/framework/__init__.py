"""Framework internals: persistence, flags, program-level helpers."""
from .io import save, load  # noqa: F401
from .flags import set_flags, get_flags  # noqa: F401
from ..core.tensor import Parameter  # noqa: F401


def in_dygraph_mode():
    return True
