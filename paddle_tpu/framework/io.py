"""paddle.save / paddle.load.

Reference: ``python/paddle/framework/io.py:553,769`` — pickle-based state
persistence with a tensor protocol. We serialize Tensors as numpy arrays
inside a pickle stream; nested dicts/lists (state_dicts, opt states) are
supported, matching reference semantics. bfloat16 is serialized via a
dtype-tagged raw-bytes wrapper since numpy lacks native bf16.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Tensor, Parameter


class _TensorPayload:
    """Pickle-stable tensor container (handles bfloat16 via raw bytes)."""

    def __init__(self, array: np.ndarray, dtype_name: str, is_param: bool, name: str, stop_gradient: bool = True):
        self.dtype_name = dtype_name
        self.is_param = is_param
        self.name = name
        self.stop_gradient = stop_gradient
        if dtype_name == "bfloat16":
            self.shape = array.shape
            self.buf = array.tobytes()
        else:
            self.array = array

    def to_tensor(self):
        from ..core import dtype as dtypes

        if self.dtype_name == "bfloat16":
            arr = np.frombuffer(self.buf, dtype=dtypes.bfloat16).reshape(self.shape)
        else:
            arr = self.array
        if self.is_param:
            t = Parameter(arr, trainable=not self.stop_gradient)
            t.name = self.name
            return t
        t = Tensor(arr, stop_gradient=self.stop_gradient)
        t.name = self.name
        return t


def _pack(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        arr = np.asarray(obj._data)
        from ..core import dtype as dtypes

        return _TensorPayload(
            arr, dtypes.dtype_name(obj.dtype), isinstance(obj, Parameter), obj.name, obj.stop_gradient
        )
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return type(obj)(packed) if not isinstance(obj, tuple) else tuple(packed)
    return obj


def _unpack(obj: Any, return_numpy=False) -> Any:
    if isinstance(obj, _TensorPayload):
        t = obj.to_tensor()
        return t.numpy() if return_numpy else t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_unpack(v, return_numpy) for v in obj]
        return tuple(out) if isinstance(obj, tuple) else out
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
