"""paddle.save / paddle.load.

Reference: ``python/paddle/framework/io.py:553,769`` — pickle-based state
persistence with a tensor protocol. Tensors are serialized as plain,
self-describing dicts holding numpy arrays (bfloat16 as dtype-tagged raw
bytes, since numpy lacks native bf16), so checkpoints are readable with
nothing but pickle+numpy — no framework import required — matching the
reference's plain numpy-pickle state-dict format.
"""
from __future__ import annotations

import contextlib
import itertools
import os
import pickle
import threading
from typing import Any

import numpy as np

from ..core.tensor import Tensor, Parameter

_TENSOR_KEY = "__paddle_tpu_tensor__"

_tmp_seq = itertools.count(1)  # same-process same-path writers get unique tmps


@contextlib.contextmanager
def atomic_open(path: str, mode: str = "w"):
    """Open a tmp file beside ``path`` and ``os.replace`` it over ``path``
    on clean exit (removed on error). Readers concurrently — or after a
    mid-write SIGKILL — see the old content or the complete new write,
    never a torn file. The repo-wide idiom for every artifact another
    process may read (lint rule ``atomic-write``; two torn-cache segfault
    incidents, PR 3 / PR 4). The tmp name carries pid, thread id and a
    sequence number: two THREADS of one process writing the same path must
    not truncate each other's in-flight tmp — last replace wins with a
    complete file either way."""
    tmp = (
        f"{path}.tmp{os.getpid()}-{threading.get_ident()}-{next(_tmp_seq)}"
    )
    try:
        with open(tmp, mode) as f:
            yield f
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


class _TensorPayload:
    """Legacy pickle container — kept so pre-existing checkpoints load."""

    def to_tensor(self):
        from ..core import dtype as dtypes

        if self.dtype_name == "bfloat16":
            arr = np.frombuffer(self.buf, dtype=dtypes.bfloat16).reshape(self.shape)
        else:
            arr = self.array
        return _make_tensor(arr, self.is_param, self.name, self.stop_gradient)


def _make_tensor(arr, is_param, name, stop_gradient):
    if is_param:
        t = Parameter(arr, trainable=not stop_gradient)
        t.name = name
        return t
    t = Tensor(arr, stop_gradient=stop_gradient)
    t.name = name
    return t


def _pack(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        from ..core import dtype as dtypes

        arr = np.asarray(obj._data)
        dtype_name = dtypes.dtype_name(obj.dtype)
        rec = {
            _TENSOR_KEY: 1,
            "dtype": dtype_name,
            "is_param": isinstance(obj, Parameter),
            "name": obj.name,
            "stop_gradient": obj.stop_gradient,
        }
        if dtype_name == "bfloat16":
            rec["shape"] = tuple(arr.shape)
            rec["data"] = arr.tobytes()
        else:
            rec["data"] = arr
        return rec
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return type(obj)(packed) if not isinstance(obj, tuple) else tuple(packed)
    return obj


def _unpack(obj: Any, return_numpy=False) -> Any:
    if isinstance(obj, _TensorPayload):
        t = obj.to_tensor()
        return t.numpy() if return_numpy else t
    if isinstance(obj, dict):
        if obj.get(_TENSOR_KEY):
            if obj["dtype"] == "bfloat16":
                from ..core import dtype as dtypes

                arr = np.frombuffer(obj["data"], dtype=dtypes.bfloat16).reshape(obj["shape"])
            else:
                arr = obj["data"]
            t = _make_tensor(arr, obj["is_param"], obj["name"], obj["stop_gradient"])
            return t.numpy() if return_numpy else t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_unpack(v, return_numpy) for v in obj]
        return tuple(out) if isinstance(obj, tuple) else out
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # atomic: a kill mid-pickle must not leave a truncated state file where
    # a resumable checkpoint used to be
    with atomic_open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
