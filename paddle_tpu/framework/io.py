"""paddle.save / paddle.load.

Reference: ``python/paddle/framework/io.py:553,769`` — pickle-based state
persistence with a tensor protocol. Tensors are serialized as plain,
self-describing dicts holding numpy arrays (bfloat16 as dtype-tagged raw
bytes, since numpy lacks native bf16), so checkpoints are readable with
nothing but pickle+numpy — no framework import required — matching the
reference's plain numpy-pickle state-dict format.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Tensor, Parameter

_TENSOR_KEY = "__paddle_tpu_tensor__"


class _TensorPayload:
    """Legacy pickle container — kept so pre-existing checkpoints load."""

    def to_tensor(self):
        from ..core import dtype as dtypes

        if self.dtype_name == "bfloat16":
            arr = np.frombuffer(self.buf, dtype=dtypes.bfloat16).reshape(self.shape)
        else:
            arr = self.array
        return _make_tensor(arr, self.is_param, self.name, self.stop_gradient)


def _make_tensor(arr, is_param, name, stop_gradient):
    if is_param:
        t = Parameter(arr, trainable=not stop_gradient)
        t.name = name
        return t
    t = Tensor(arr, stop_gradient=stop_gradient)
    t.name = name
    return t


def _pack(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        from ..core import dtype as dtypes

        arr = np.asarray(obj._data)
        dtype_name = dtypes.dtype_name(obj.dtype)
        rec = {
            _TENSOR_KEY: 1,
            "dtype": dtype_name,
            "is_param": isinstance(obj, Parameter),
            "name": obj.name,
            "stop_gradient": obj.stop_gradient,
        }
        if dtype_name == "bfloat16":
            rec["shape"] = tuple(arr.shape)
            rec["data"] = arr.tobytes()
        else:
            rec["data"] = arr
        return rec
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return type(obj)(packed) if not isinstance(obj, tuple) else tuple(packed)
    return obj


def _unpack(obj: Any, return_numpy=False) -> Any:
    if isinstance(obj, _TensorPayload):
        t = obj.to_tensor()
        return t.numpy() if return_numpy else t
    if isinstance(obj, dict):
        if obj.get(_TENSOR_KEY):
            if obj["dtype"] == "bfloat16":
                from ..core import dtype as dtypes

                arr = np.frombuffer(obj["data"], dtype=dtypes.bfloat16).reshape(obj["shape"])
            else:
                arr = obj["data"]
            t = _make_tensor(arr, obj["is_param"], obj["name"], obj["stop_gradient"])
            return t.numpy() if return_numpy else t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_unpack(v, return_numpy) for v in obj]
        return tuple(out) if isinstance(obj, tuple) else out
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
