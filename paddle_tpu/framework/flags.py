"""Global flag registry.

Reference: gflags exported via ``paddle/fluid/platform/flags.cc`` (53 flags) +
``pybind/global_value_getter_setter.cc`` → ``paddle.set_flags/get_flags`` and
``FLAGS_*`` env pickup. Here flags mostly steer debug behavior (nan/inf
checking, deterministic ops) and XLA options.
"""
from __future__ import annotations

import os
from typing import Dict

_FLAGS: Dict[str, object] = {
    "FLAGS_check_nan_inf": False,          # reference operator.cc:1171 nan/inf scan
    # Lazy-mode per-op nan/inf attribution (checkify-style): every flush is
    # re-run unfused with every node output checked, so NaNs in fused-away
    # dead intermediates are caught too and the first non-finite value is
    # attributed to the op that produced it. ~2x compute — the reference's
    # documented debug-mode cost. Only consulted when FLAGS_check_nan_inf
    # is set.
    "FLAGS_check_nan_inf_per_op": False,
    # Verify checkpoint shard checksums against the manifest on load (skipped
    # automatically for legacy checkpoints without a manifest).
    "FLAGS_ckpt_verify_on_load": True,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_use_bf16_matmul": True,         # TPU-native: allow bf16 matmul precision
    "FLAGS_jit_cache_size": 4096,
    "FLAGS_log_level": 0,
    # Lazy-graph IR verifier (analysis/verify_graph.py): re-derive and
    # cross-check the pending graph's wiring, leaf table, donation mask and
    # cache signature immediately before every dispatch, raising a
    # structured GraphInvariantError naming the offending node. Default on
    # in the test suite (conftest); off in production, where the disabled
    # path costs one flag probe per flush (bench_verify_overhead pins the
    # enabled cost <2% on the CPU LeNet loop).
    "FLAGS_lazy_verify": False,
    # Runtime ownership assertions (analysis/thread_checks.py): wrap
    # `# guarded_by:`-annotated shared structures in proxies that make an
    # unguarded/foreign-thread mutation raise at the mutation site, so races
    # fail deterministically in the chaos/async suites instead of corrupting
    # a table. Opt-in; consulted at structure WRAP time, not per mutation.
    "FLAGS_thread_checks": False,
    # Lazy-flush buffer donation: dead-after-flush inputs (rebound params,
    # optimizer moments, accumulated grads) are passed as donate_argnums so
    # XLA updates weights in place instead of copying ~3x model size per
    # step. FLAGS_lazy_donate=0 is the kill-switch.
    "FLAGS_lazy_donate": True,
    # Async lazy runtime (arXiv:2102.13267 overlap): the flush returns at
    # executable DISPATCH (results are unblocked jax.Array futures), the
    # NaN/Inf guard scan and the telemetry memory census run off the critical
    # path (deferred to the next flush/materialization/lazy.sync(), trip
    # surfaces ≤1 step late), and host readback waits are attributed via
    # `block` spans + lazy_block_ns. FLAGS_lazy_async=0 is the kill-switch
    # restoring the fully synchronous behavior.
    "FLAGS_lazy_async": True,
    # Background compilation of flush-cache misses: the miss step (and any
    # same-signature step until the compile lands) executes via the un-jitted
    # replay while a worker thread compiles the fused executable. OPT-IN:
    # the unfused replay can differ from the fused executable by ~1 ulp and
    # the pickup step depends on compile latency, so loops that pin bitwise
    # reproducibility across runs must leave it off. Needs FLAGS_lazy_async.
    "FLAGS_lazy_bg_compile": False,
    # ZeRO-1 sharded weight update for pure-DP meshes (arXiv:2004.13336):
    # reduce_scatter(grads) -> each replica updates its 1/dp shard of params
    # + optimizer moments -> all_gather(params), with grads coalesced into
    # reverse-backward-order buckets (fleet/grad_buckets.py). On by default;
    # the engine falls back to the replicated GSPMD update for hybrid
    # meshes, non-elementwise rules (LAMB/LARS) and grad accumulation.
    "FLAGS_shard_weight_update": True,
    # EQuARX-style blockwise int8 compression of the DP gradient collectives
    # (collective.py quantized_* prims). Off by default — lossy; enable with
    # FLAGS_quantized_allreduce_error_feedback to carry the compression
    # residual into the next step.
    "FLAGS_quantized_allreduce": False,
    "FLAGS_quantized_allreduce_block": 128,
    "FLAGS_quantized_allreduce_error_feedback": False,
    # Gradient-bucket byte cap (reference DataParallel comm_buffer_size=25MB).
    "FLAGS_dp_bucket_bytes": 25 * 1024 * 1024,
    # Per-flush live-buffer memory census (jax.live_arrays() walk feeding the
    # profiler's live_bytes/peak gauges and lazy_flush span attrs) without a
    # running Profiler; Profiler(profile_memory=True) turns it on per session.
    "FLAGS_profile_memory": False,
    # Serving engine defaults (paddle_tpu/serving/ — continuous batching +
    # paged KV cache): KV block size in tokens, total preallocated blocks in
    # the pool (block 0 is the reserved trash block), the decode batch-width
    # ceiling (bucketed in powers of two up to this), the fixed prefill
    # batch width, the per-sequence length cap (clamped to the model's
    # max_position_embeddings), and the weight-only int8 serving path.
    # EngineConfig fields override per engine.
    "FLAGS_serve_block_size": 16,
    "FLAGS_serve_num_blocks": 512,
    "FLAGS_serve_max_batch": 64,
    "FLAGS_serve_prefill_batch": 4,
    "FLAGS_serve_max_seq_len": 2048,
    "FLAGS_serve_int8": False,
    # Serving throughput multipliers (PR 16). FLAGS_serve_prefix_cache keeps
    # retired prompts' KV blocks in a refcounted prefix index so admission
    # can match the longest cached prefix (chained block-granularity hashes
    # over prompt token chunks) and prefill only the tail.
    # FLAGS_serve_spec_k > 0 arms speculative decoding: a drafter proposes k
    # tokens per step and the target model verifies all k in ONE batched
    # paged-decode step, accepting the longest agreeing prefix (greedy
    # output stays bit-identical to non-speculative decode).
    # FLAGS_serve_drafter picks the proposer: "ngram" (host-side prompt
    # lookup, no extra model) — a small same-family model can be passed to
    # Engine(drafter=...) directly. FLAGS_serve_draft_window bounds the
    # model drafter's dense attention window in tokens. Both features
    # default OFF and their code paths are never reached unconfigured
    # (pinned by the inert tripwire in tests/test_serving_prefix.py).
    "FLAGS_serve_prefix_cache": False,
    "FLAGS_serve_spec_k": 0,
    "FLAGS_serve_drafter": "ngram",
    "FLAGS_serve_draft_window": 64,
    # Serving resilience (serving/engine.py + serving/supervisor.py).
    # FLAGS_serve_max_queue sets the queue depth at which the shed policy
    # engages (0 = never); it is only enforced when FLAGS_serve_shed is ALSO
    # set, in which case submit() past the cap fast-fails with a structured
    # Overloaded (Retry-After-style retry_after_s hint) instead of letting
    # queue latency grow without bound — with shed off, the queue stays
    # unbounded (PR 11 semantics). FLAGS_serve_watchdog_s is the
    # ServingSupervisor's liveness
    # deadline: a crashed or wedged engine scheduler thread is detected
    # within this many seconds (heartbeat staleness), in-flight work is
    # failed or requeued, and the engine restarts over the same model/pool
    # config. All three are EngineConfig/supervisor overridable per engine;
    # none adds threads or host syncs when left at the defaults.
    "FLAGS_serve_max_queue": 0,
    "FLAGS_serve_shed": False,
    "FLAGS_serve_watchdog_s": 10.0,
    # Serving state durability (PR 17). With FLAGS_serve_snapshot on, the
    # ServingSupervisor's crash recovery captures the dead engine's frozen
    # serving state (PagePool bookkeeping + KV pool arrays + block tables +
    # prefix-cache chain, validated end-to-end) and the replacement engine
    # RE-ATTACHES the surviving blocks — streams resume mid-decode with
    # zero re-prefilled tokens, bit-identical to an uninterrupted run. A
    # capture that fails validation falls back to the PR 12 re-prefill
    # path, so recovery is never worse than before. Off (default): the
    # snapshot/adopt code paths are never reached (inert tripwire in
    # tests/test_serving_snapshot.py); Engine.handoff() is an explicit API
    # and needs no flag. Supervisor snapshot= overrides per instance.
    "FLAGS_serve_snapshot": False,
    # Multi-chip serving (PR 19). FLAGS_serve_tp shards attention heads,
    # FFN columns, the LM head, and the KV PagePool over a tp-sized mesh
    # axis (0/1 = single-chip, the exact prior code path). Every tensor-
    # parallel boundary is a concat-style all_gather of column-partitioned
    # outputs (never a psum of partials), so greedy decode stays
    # bit-identical to the single-chip engine. FLAGS_serve_prefill_chunk
    # splits prompt prefill into chunks of that many tokens (must be a
    # multiple of the KV block size; 0 = monolithic prefill) interleaved
    # one chunk per scheduler step with the live decode batch, so a long
    # prompt no longer stalls every in-flight stream for a full prefill.
    # FLAGS_serve_tp_int8 quantizes the per-step tensor-parallel
    # all_gather payloads to blockwise int8 (EQuARX-style, lossy — greedy
    # tokens may differ; off by default). All three default OFF and their
    # code paths are never reached unconfigured (inert tripwire in
    # tests/test_serving_tp.py).
    "FLAGS_serve_tp": 0,
    "FLAGS_serve_prefill_chunk": 0,
    "FLAGS_serve_tp_int8": False,
    # Serving SLO observability (PR 20, serving/observe.py).
    # FLAGS_serve_trace arms request-scoped tracing + the SLO metric layer:
    # every submitted request carries a trace id attached to each span it
    # touches (queue wait, shed, prefix match, prefill chunks, decode steps,
    # CoW, eviction, relay), completed per-request timelines land in a
    # bounded ring (FLAGS_serve_trace_ring capacity, chrome-trace/JSONL
    # exportable), and TTFT / inter-token gap / end-to-end / queue-wait
    # histograms per priority class flow into export_metrics(). Off
    # (default): the observe module is never touched — one attribute probe
    # per step, engine behavior byte-identical (inert tripwire in
    # tests/test_serving_observe.py). FLAGS_serve_metrics_port > 0 starts
    # the opt-in stdlib http.server telemetry thread (/metrics, /healthz,
    # /readyz, /debug/requests); 0 (default) = zero threads.
    "FLAGS_serve_trace": False,
    "FLAGS_serve_trace_ring": 256,
    "FLAGS_serve_metrics_port": 0,
    # Training stability sentinel (fault/sentinel.py): statistical anomaly
    # detection over per-step signals (loss, global grad norm, update/param
    # ratio, non-finite rate) with a skip -> rollback -> halt policy ladder,
    # batch quarantine and sample-exact auto-rollback. FLAGS_stability_enable
    # turns the hapi.Model.fit wiring on (one flag probe per fit call when
    # off); loops can also pass a configured StabilitySentinel explicitly.
    # window/warmup/zmax parameterize the robust (median/MAD) statistics;
    # max_skips/max_rollbacks/cooldown shape the escalation ladder;
    # anchor_interval + ckpt_dir configure the rollback anchor checkpoint;
    # quarantine_dir (when set) persists the quarantine log as JSONL.
    "FLAGS_stability_enable": False,
    "FLAGS_stability_window": 64,
    "FLAGS_stability_warmup": 8,
    "FLAGS_stability_zmax": 8.0,
    "FLAGS_stability_max_skips": 2,
    "FLAGS_stability_max_rollbacks": 2,
    "FLAGS_stability_cooldown": 16,
    "FLAGS_stability_anchor_interval": 25,
    "FLAGS_stability_ckpt_dir": "",
    "FLAGS_stability_quarantine_dir": "",
    # HBM exhaustion resilience (fault/memory.py). FLAGS_hbm_admission gates
    # the preflight memory-admission check on the lazy flush: "off" (default;
    # the whole disabled path is one flag probe per flush), "warn" (predict
    # and attach the estimate to the compile/flush spans, warn once per
    # executable when over budget, dispatch anyway), "enforce" (raise a
    # structured HbmBudgetExceeded BEFORE the dispatch touches the device).
    # FLAGS_hbm_budget_bytes overrides the device budget (0 = resolve from
    # the backend's reported capacity minus FLAGS_hbm_reserve_bytes; on
    # backends that report no capacity — CPU — 0 means no budget, so
    # admission only predicts/attributes and never rejects).
    "FLAGS_hbm_admission": "off",
    "FLAGS_hbm_budget_bytes": 0,
    "FLAGS_hbm_reserve_bytes": 256 * 1024 * 1024,
    # Host-embedding parameter server (incubate/host_embedding.py).
    # FLAGS_host_emb_native routes the table's batched unique/gather and the
    # SelectedRows-style sparse update through runtime_cpp/embed.cc
    # (multi-threaded, bit-exact with the numpy fallback); it silently falls
    # back when the .so is unbuilt/stale or the table dtype isn't float32.
    # FLAGS_host_emb_threads caps the kernel thread count (0 = hardware).
    # FLAGS_host_emb_cache_rows sizes the HBM hot-row cache (rows; 0 = off);
    # admission needs FLAGS_host_emb_cache_min_count sightings, and when the
    # PR 14 HBM budget is resolvable the cache is clamped to
    # FLAGS_host_emb_cache_frac of it (and registers a free_pressure handler
    # that halves it under memory pressure). FLAGS_host_emb_async_push makes
    # apply_gradients enqueue the sparse update to the PS worker thread
    # (host table work hides behind device execution; ordering vs later
    # gathers/prefetches is preserved by the worker's FIFO). Sharded-table
    # transport: FLAGS_host_emb_chunk_bytes per store message (the pre-PR
    # path used 512 KiB), FLAGS_host_emb_transport_threads parallel store
    # clients per peer exchange (0 = serial pre-PR behavior), and
    # FLAGS_host_emb_push_fp16 opts into float16 cross-rank grad payloads
    # (EQuARX-style byte shrink; lossy, off by default).
    "FLAGS_host_emb_native": True,
    "FLAGS_host_emb_threads": 16,
    "FLAGS_host_emb_cache_rows": 0,
    "FLAGS_host_emb_cache_min_count": 3,
    "FLAGS_host_emb_cache_frac": 0.25,
    "FLAGS_host_emb_async_push": False,
    "FLAGS_host_emb_chunk_bytes": 4 * 1024 * 1024,
    "FLAGS_host_emb_transport_threads": 4,
    "FLAGS_host_emb_push_fp16": False,
    # JAX persistent compilation cache (warm executable starts across
    # processes). Dir defaults to ~/.cache/paddle_tpu/xla when unset.
    "FLAGS_xla_persistent_cache": True,
    "FLAGS_xla_persistent_cache_dir": "",
    "FLAGS_xla_persistent_cache_min_compile_secs": 0.5,
    # Kernel autotuning (ops/kernels/). FLAGS_kernel_autotune: "off" makes
    # resolve_config a pure dict probe returning each kernel's pinned
    # defaults (byte-identical traces to the pre-registry call sites);
    # "ondemand" reads persisted winners from the tuning DB but never
    # searches; "search" runs a measured-timing search on a DB miss and
    # persists the verified winner. FLAGS_kernel_tune_dir overrides the DB
    # location (default ~/.cache/paddle_tpu/tune). Per-kernel search budget
    # and timing samples: FLAGS_kernel_tune_budget_s (monotonic deadline),
    # FLAGS_kernel_tune_samples (median-of-k, compile excluded).
    "FLAGS_kernel_autotune": "off",
    "FLAGS_kernel_tune_dir": "",
    "FLAGS_kernel_tune_budget_s": 20.0,
    "FLAGS_kernel_tune_samples": 5,
    # Serving kernel kill-switches. FLAGS_serve_paged_kernel routes engine
    # decode through the paged-attention Pallas kernel (reads K/V straight
    # from PagePool blocks — bit-identical to the gather path; spec-decode
    # keeps the gather). FLAGS_serve_int8_kernel keeps the int8 LM-head
    # weight quantized end-to-end via the fused int8 matmul kernel instead
    # of dequantizing it densely each step.
    "FLAGS_serve_paged_kernel": False,
    "FLAGS_serve_int8_kernel": False,
}

# Env pickup at import (reference: gflags env integration)
for _k in list(_FLAGS):
    if _k in os.environ:
        v = os.environ[_k]
        cur = _FLAGS[_k]
        if isinstance(cur, bool):
            _FLAGS[_k] = v.lower() in ("1", "true", "yes")
        elif isinstance(cur, float):
            _FLAGS[_k] = float(v)
        elif isinstance(cur, int):
            _FLAGS[_k] = int(v)
        else:
            _FLAGS[_k] = v


def register_flag(name: str, default):
    """Register a new flag (plugins/tests). Registration is explicit so that
    ``set_flags`` can reject typos instead of creating dead flags."""
    _FLAGS.setdefault(name, default)


def set_flags(flags: dict):
    for k, v in flags.items():
        if k not in _FLAGS:
            # A typo like FLAGS_chek_nan_inf would otherwise create a dead
            # flag and silently disable the debug mode the user asked for.
            import difflib

            hint = difflib.get_close_matches(k, _FLAGS, n=1)
            raise KeyError(
                f"unknown flag {k!r}"
                + (f"; did you mean {hint[0]!r}?" if hint else "")
                + " (use framework.flags.register_flag to add new flags)"
            )
        _FLAGS[k] = v


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}


def flag(name, default=None):
    return _FLAGS.get(name, default)
