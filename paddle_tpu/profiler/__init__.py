"""Profiler.

Parity: reference new profiler (``paddle/fluid/platform/profiler/`` —
Profiler composes HostTracer + CudaTracer(CUPTI), chrome-trace export) and
python API (``python/paddle/profiler/``). TPU-native: host events recorded in
Python/C++ ring buffer; device timeline delegated to jax.profiler (XProf /
tensorboard trace), the TPU equivalent of CUPTI.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Dict, List, Optional

import jax


class ProfilerTarget:
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class _Event:
    __slots__ = ("name", "start", "end", "tid")

    def __init__(self, name, start, end, tid=0):
        self.name = name
        self.start = start
        self.end = end
        self.tid = tid


_events: List[_Event] = []
_enabled = False

# Engine counters (always on — integer bumps at flush/step granularity, not
# per-op): lazy-flush executable cache behavior and buffer donation. The
# donation counter counts argument positions PASSED as donate_argnums; on
# backends that ignore the aliasing hint the count still reflects what the
# liveness pass proved dead.
_counters: Dict[str, int] = {}


def counter_inc(name: str, n: int = 1):
    _counters[name] = _counters.get(name, 0) + n


def counters() -> Dict[str, int]:
    """Snapshot of engine counters: ``lazy_flushes``, ``lazy_cache_hits``,
    ``lazy_donated_buffers``, ``lazy_donation_fallbacks`` (always on),
    ``dispatch_fastkey_hits`` (per-op — only counted while the profiler is
    running, to keep the dispatch hot path free of bookkeeping), and the
    fault-tolerance set: ``ckpt_saves`` / ``ckpt_save_failures`` /
    ``ckpt_resume_fallbacks`` (crash-safe checkpointing),
    ``preemption_drains`` (PreemptionGuard SIGTERM drains),
    ``retry_attempts`` (fault/retry.py backoff retries), ``naninf_trips``
    (lazy-mode FLAGS_check_nan_inf post-flush trips) and
    ``naninf_donation_suppressed`` (flushes that skipped buffer donation to
    keep pre-step state inspectable under the nan guard).

    DP gradient-sync set (per train step, analytic wire accounting from the
    bucket plan): ``dp_sync_bytes`` (per-replica payload bytes entering the
    DP GRADIENT collectives — reduce-scatter for the ZeRO-1 path, both ring
    phases for bucketed all-reduce; int8+scale bytes when
    FLAGS_quantized_allreduce is on), ``dp_gather_bytes`` (ZeRO-1
    updated-param all-gather, full precision), ``dp_buckets`` /
    ``dp_reduce_scatters`` / ``dp_all_reduces`` (collective launches), and
    ``wus_enabled`` (1 when the engine runs the sharded weight update)."""
    return dict(_counters)


def reset_counters():
    _counters.clear()

# Native host recorder (runtime_cpp/trace.cc) when built — GIL-cheap record.
_native = None
_native_rec = None


def _native_recorder():
    global _native, _native_rec
    if _native_rec is not None:
        return _native_rec
    try:
        from ..core.native import lib

        _native = lib()
        if _native is not None:
            _native_rec = _native.ptt_create(1 << 16)
    except Exception:
        _native = None
    return _native_rec


def _record(name: str, t0: int, tid: int = 0):
    """Hot-path event sink: dispatch/lazy/jit call this with a start stamp
    taken only when ``_enabled`` was already true (reference records every
    traced op the same way, imperative/tracer.cc:177)."""
    t1 = time.perf_counter_ns()
    if not _enabled:
        return
    rec = _native_recorder()
    if rec is not None:
        nid = _native.ptt_intern(rec, name.encode())
        _native.ptt_record(rec, nid, tid, t0, t1)
    _events.append(_Event(name, t0, t1, tid))


class RecordEvent:
    """Reference: platform/profiler.h RecordEvent push/pop. Events land in
    the C++ ring buffer when the native runtime is built."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()

    def end(self):
        if _enabled and self._t0 is not None:
            t1 = time.perf_counter_ns()
            rec = _native_recorder()
            if rec is not None:
                nid = _native.ptt_intern(rec, self.name.encode())
                _native.ptt_record(rec, nid, 0, self._t0, t1)
            _events.append(_Event(self.name, self._t0, t1))

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None, timer_only=False, record_shapes=False, profile_memory=False, with_flops=False):
        self.timer_only = timer_only
        self._jax_tracing = False
        self._trace_dir = None

    def start(self):
        global _enabled
        _enabled = True
        _events.clear()
        if not self.timer_only:
            self._trace_dir = os.environ.get("PADDLE_TPU_TRACE_DIR", "/tmp/paddle_tpu_trace")
            try:
                jax.profiler.start_trace(self._trace_dir)
                self._jax_tracing = True
            except Exception:
                self._jax_tracing = False

    def stop(self):
        global _enabled
        _enabled = False
        if self._jax_tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_tracing = False

    def step(self):
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def export(self, path, format="json"):
        """Chrome-trace export (reference chrometracing_logger.cc)."""
        trace = {
            "traceEvents": [
                {
                    "name": e.name,
                    "ph": "X",
                    "ts": e.start / 1000.0,
                    "dur": (e.end - e.start) / 1000.0,
                    "pid": 0,
                    "tid": e.tid,
                }
                for e in _events
            ]
        }
        with open(path, "w") as f:
            json.dump(trace, f)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        from collections import defaultdict

        agg = defaultdict(lambda: [0, 0.0])
        for e in _events:
            agg[e.name][0] += 1
            agg[e.name][1] += (e.end - e.start) / 1e6
        lines = [f"{'name':40s} {'calls':>8s} {'total_ms':>12s}"]
        for name, (calls, total) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:40s} {calls:8d} {total:12.3f}")
        return "\n".join(lines)


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    return None


@contextlib.contextmanager
def profiler_guard(**kwargs):
    p = Profiler(**kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()
