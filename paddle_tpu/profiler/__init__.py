"""Profiler.

Parity: reference new profiler (``paddle/fluid/platform/profiler/`` —
Profiler composes HostTracer + CudaTracer(CUPTI), chrome-trace export, stat
aggregation) and python API (``python/paddle/profiler/``). TPU-native: host
events + structured spans recorded in a Python/C++ ring buffer; device
timeline delegated to jax.profiler (XProf / tensorboard trace), the TPU
equivalent of CUPTI.

Layers (each usable alone):

* **engine counters** — always-on integer bumps at flush/step granularity
  (:func:`counters`), exported as JSON or Prometheus text
  (:mod:`.export`), folded into every ``bench.py`` JSON line;
* **span tracer** (:mod:`.spans`) — nested, attributed spans
  (``train_step`` → ``lazy_flush`` → ``trace``/``donate``/``compile``/
  ``execute``; ``dp_sync`` → per-bucket; ``ckpt_save`` →
  ``serialize``/``commit``) recorded while a :class:`Profiler` runs;
* **flight recorder** (:mod:`.flight`) — always-on bounded ring of the last
  N spans + a JSON post-mortem dump on NaN trips, preemption drains,
  checkpoint-save failure, or an uncaught training-loop exception;
* **memory accounting** — per-flush live-buffer census over
  ``jax.live_arrays()`` with a high-water-mark gauge (:func:`memory_census`),
  on under ``Profiler(profile_memory=True)`` or ``FLAGS_profile_memory``.
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Dict, List, Optional

import jax


class ProfilerTarget:
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class _Event:
    __slots__ = ("name", "start", "end", "tid")

    def __init__(self, name, start, end, tid=0):
        self.name = name
        self.start = start
        self.end = end
        self.tid = tid


_events: List[_Event] = []
_enabled = False
_memory_on = False  # set while a Profiler(profile_memory=True) session runs

# Engine counters (always on — integer bumps at flush/step granularity, not
# per-op): lazy-flush executable cache behavior and buffer donation. The
# donation counter counts argument positions PASSED as donate_argnums; on
# backends that ignore the aliasing hint the count still reflects what the
# liveness pass proved dead.
_counters: Dict[str, int] = {}


def counter_inc(name: str, n: int = 1):
    _counters[name] = _counters.get(name, 0) + n


def counters() -> Dict[str, int]:
    """Snapshot of engine counters.

    Lazy engine (always on): ``lazy_flushes``, ``lazy_cache_hits``,
    ``lazy_donated_buffers``, ``lazy_donation_fallbacks``;
    ``dispatch_fastkey_hits`` is per-op and only counted while the profiler
    is running, to keep the dispatch hot path free of bookkeeping.

    Async runtime (FLAGS_lazy_async): ``lazy_blocks`` / ``lazy_block_ns``
    (attributed host waits on the device — the dispatch-gap metric bench.py
    reports per step), ``lazy_deferred_checks`` (NaN/Inf scans moved off the
    critical path), ``lazy_bg_compiles`` / ``lazy_bg_replays`` /
    ``lazy_bg_pickups`` / ``lazy_bg_compile_failures`` /
    ``lazy_bg_aot_fallbacks`` (FLAGS_lazy_bg_compile background compilation:
    misses compiling off-thread, steps served by the un-jitted replay
    meanwhile, compiled executables picked up, and fallbacks), and
    ``io_device_prefetched`` (batches staged on device by the
    DevicePrefetcher input stage).

    Fault tolerance: ``ckpt_saves`` / ``ckpt_save_failures`` /
    ``ckpt_resume_fallbacks`` (crash-safe checkpointing),
    ``preemption_drains`` (PreemptionGuard SIGTERM drains),
    ``retry_attempts`` (fault/retry.py backoff retries), ``naninf_trips``
    (FLAGS_check_nan_inf trips, eager and lazy), and
    ``naninf_donation_suppressed`` (flushes that skipped buffer donation to
    keep pre-step state inspectable under the nan guard).

    DP gradient-sync set (per train step, analytic wire accounting from the
    bucket plan): ``dp_sync_bytes`` (per-replica payload bytes entering the
    DP GRADIENT collectives — reduce-scatter for the ZeRO-1 path, both ring
    phases for bucketed all-reduce; int8+scale bytes when
    FLAGS_quantized_allreduce is on), ``dp_gather_bytes`` (ZeRO-1
    updated-param all-gather, full precision), ``dp_buckets`` /
    ``dp_reduce_scatters`` / ``dp_all_reduces`` (collective launches), and
    ``wus_enabled`` (1 when the engine runs the sharded weight update).

    Serving engine (paddle_tpu/serving/): ``serve_requests`` /
    ``serve_admitted`` / ``serve_retired`` / ``serve_cancelled`` /
    ``serve_failed`` (request lifecycle), ``serve_prefills`` /
    ``serve_decode_steps`` / ``serve_tokens`` (work done),
    ``serve_compiles`` (bucket programs built — bounded by the bucket
    count), ``serve_pages_allocated`` / ``serve_pages_freed`` (KV block
    pool churn), ``serve_backpressure`` (admissions stalled on pool
    exhaustion), ``serve_preempted`` (sequences evicted for re-prefill),
    ``serve_occupancy_live`` / ``serve_occupancy_slots`` (live rows vs
    padded batch slots per decode step — their ratio is mean batch
    occupancy), and ``serve_engine_errors``. Live gauges (queue depth,
    page-pool utilization, in-flight request table) come from
    ``Engine.stats()`` and ride every flight-recorder dump via the
    engine's context provider.

    Serving resilience (round 12): ``serve_shed`` (submissions fast-failed
    ``Overloaded`` at the queue cap), ``serve_deadline_shed`` (queued
    requests shed expired/doomed at admission) and
    ``serve_deadline_expired`` (running/preempted requests expired at a
    step boundary), ``serve_wedged_close`` (close() joins that timed out on
    a wedged scheduler thread), ``serve_crash_detected`` /
    ``serve_wedge_detected`` / ``serve_restarts`` / ``serve_requeued`` /
    ``serve_relayed`` (ServingSupervisor recovery: failures detected,
    engines restarted, requests resubmitted onto the fresh engine, and
    originals completed through the recovery relay — a requeued request's
    CONTINUATION counts once in serve_requests/serve_retired on the new
    engine, while the original's relay completion counts only in
    serve_relayed, so lifecycle counters stay per-logical-outcome), and
    ``serve_pool_damaged`` (serve.pool_corrupt chaos firings).

    HBM exhaustion resilience (fault/memory.py): ``hbm_admission_checks`` /
    ``hbm_admission_rejects`` (preflight admission decisions under
    ``FLAGS_hbm_admission``), ``hbm_oom_trips`` (classified
    RESOURCE_EXHAUSTED events, wherever they fired), ``hbm_oom_recoveries``
    (ladder rungs that brought the step/stream back — flush retry, engine
    microbatch degrade), ``hbm_degraded_steps`` (engine steps re-run
    through the grad-accumulate scan path), ``hbm_cache_evicted`` (cold
    lazy executables dropped by free_pressure), ``serve_pool_shrunk`` /
    ``serve_pages_parked`` / ``serve_pages_unparked`` (serving KV-block
    admission-headroom shrink under pressure), and
    ``stability_coordinated_trips`` / ``stability_barrier_timeouts`` (the
    sentinel's cross-rank VerdictBarrier adoptions and degraded rounds).

    Kernel autotuning (ops/kernels/, FLAGS_kernel_autotune):
    ``kernel_tune_hits`` / ``kernel_tune_misses`` (registry config
    resolutions served by the tuning DB vs falling back / searching),
    ``kernel_tune_searches`` (measured-timing searches run),
    ``kernel_tune_candidates`` (candidate configs timed),
    ``kernel_tune_verify_fails`` (candidates rejected by the
    against-default output check), ``kernel_tune_candidate_errors``
    (candidates that failed to compile/run), ``kernel_tune_budget_stops``
    (searches cut short by FLAGS_kernel_tune_budget_s), and
    ``kernel_tune_db_rejects`` (torn/corrupt DB entries rejected and
    deleted). All zero while autotuning is off — resolution is then a
    dict probe that touches none of this machinery.

    Prefix cache + CoW KV sharing (serving/prefix.py): ``serve_prefix_hits``
    / ``serve_prefix_misses`` (admissions that found / missed a cached
    prompt prefix), ``serve_prefix_blocks_shared`` (KV blocks adopted from
    the cache instead of re-prefilled), ``serve_prefix_evicted`` (cached
    prefixes dropped by the LRU bound), ``serve_pages_shared`` (blocks
    holding refcount > 1 at share time), and ``serve_cow_copies``
    (copy-on-write block duplications when a shared block is written).

    Chunked prefill (FLAGS_serve_prefill_chunk): ``serve_prefill_chunks``
    (prompt chunks executed through the chunk bucket) and
    ``serve_tail_prefills`` (final partial chunks landed through the
    ordinary prefill path).

    Speculative decoding (FLAGS_serve_spec_k): ``serve_draft_proposed``
    / ``serve_draft_accepted`` (draft tokens proposed vs accepted by the
    target-model verify — their ratio is the acceptance rate).

    Serving state durability (rounds 17-18): ``serve_snapshots`` /
    ``serve_snapshot_failed`` / ``serve_snapshot_rejected`` (KV-pool
    snapshot writes, failures, and stale/corrupt restores rejected),
    ``serve_pool_restores`` (pools rebuilt from a snapshot),
    ``serve_adoptions`` (engines adopting a restored pool),
    ``serve_reattached`` / ``serve_reattached_blocks`` (crash re-attach:
    requests resumed onto snapshot KV state and the blocks they kept),
    ``serve_reprefill_tokens`` / ``serve_reprefill_tokens_saved`` (tokens
    re-prefilled after recovery vs spared by re-attach),
    ``serve_handoffs`` (zero-downtime engine→engine handoffs), and
    ``serve_restart_mttr_ms`` (cumulative supervisor detect→ready repair
    time).

    Serving observability (this round): ``serve_trace_evicted`` (completed
    request timelines dropped from the bounded trace ring),
    ``serve_http_requests`` (telemetry endpoint GETs served), and
    ``serve_http_bind_failed`` (endpoint start-ups that lost the port —
    telemetry never takes serving down).

    Host embedding offload (incubate/host_embedding.py): ``host_emb_lookups`` /
    ``host_emb_block_ns`` (gather round-trips and attributed host-wait
    time), ``host_emb_hot_hits`` / ``host_emb_hot_misses`` (device-resident
    hot-shard membership), ``host_emb_cache_admitted`` /
    ``host_emb_cache_evicted`` / ``host_emb_cache_shrinks`` (hot-cache
    churn), ``host_emb_prefetch_hits`` / ``host_emb_prefetch_drops`` /
    ``host_emb_prefetch_patched`` (lookahead pipeline), and
    ``host_emb_push_bytes`` (host-side gradient write-back volume).

    Numeric stability sentinel (stability/): ``stability_observed`` /
    ``stability_trips`` / ``stability_skips`` / ``stability_halts`` /
    ``stability_rollbacks`` / ``stability_readbacks`` (steps watched,
    verdicts tripped, and the skip/halt/rollback reactions plus device
    readbacks the policy paid for).

    Cluster plumbing: ``ckpt_coordinated_commits`` (multi-host checkpoint
    barrier commits), ``heartbeat_failures`` (elastic heartbeat misses),
    ``watchdog_trips`` (collective-watchdog stall detections),
    ``io_quarantine_skips`` (poisoned input batches skipped), and
    ``lazy_verify_passes`` (FLAGS_lazy_verify replay cross-checks).

    Telemetry: ``flight_dumps`` (flight-recorder post-mortems written by
    this process).

    Export: :func:`export_metrics` (JSON or Prometheus text) embeds this
    snapshot plus the memory gauges; ``Profiler.export`` embeds it as
    chrome-trace metadata; ``bench.py`` folds it into every BENCH JSON line.
    """
    return dict(_counters)


# The counter registry: every counter the package bumps, by name. The
# ``counter-registry`` lint rule (analysis/lint.py) enforces the three-way
# contract — every ``counter_inc`` literal in the package appears here,
# every name here is bumped somewhere, and every name here is documented
# (double-backticked) in the :func:`counters` docstring above. Adding a
# counter means adding it in all three places; the lint failure names the
# one you forgot.
KNOWN_COUNTERS = frozenset({
    "ckpt_coordinated_commits", "ckpt_resume_fallbacks",
    "ckpt_save_failures", "ckpt_saves",
    "dispatch_fastkey_hits",
    "dp_all_reduces", "dp_buckets", "dp_gather_bytes",
    "dp_reduce_scatters", "dp_sync_bytes",
    "flight_dumps",
    "hbm_admission_checks", "hbm_admission_rejects", "hbm_cache_evicted",
    "hbm_degraded_steps", "hbm_oom_recoveries", "hbm_oom_trips",
    "heartbeat_failures",
    "host_emb_block_ns", "host_emb_cache_admitted",
    "host_emb_cache_evicted", "host_emb_cache_shrinks",
    "host_emb_hot_hits", "host_emb_hot_misses", "host_emb_lookups",
    "host_emb_prefetch_drops", "host_emb_prefetch_hits",
    "host_emb_prefetch_patched", "host_emb_push_bytes",
    "io_device_prefetched", "io_quarantine_skips",
    "kernel_tune_budget_stops", "kernel_tune_candidate_errors",
    "kernel_tune_candidates", "kernel_tune_db_rejects",
    "kernel_tune_hits", "kernel_tune_misses", "kernel_tune_searches",
    "kernel_tune_verify_fails",
    "lazy_bg_aot_fallbacks", "lazy_bg_compile_failures",
    "lazy_bg_compiles", "lazy_bg_pickups", "lazy_bg_replays",
    "lazy_block_ns", "lazy_blocks", "lazy_cache_hits",
    "lazy_deferred_checks", "lazy_donated_buffers",
    "lazy_donation_fallbacks", "lazy_flushes", "lazy_verify_passes",
    "naninf_donation_suppressed", "naninf_trips",
    "preemption_drains", "retry_attempts",
    "serve_admitted", "serve_adoptions", "serve_backpressure",
    "serve_cancelled", "serve_compiles", "serve_cow_copies",
    "serve_crash_detected", "serve_deadline_expired",
    "serve_deadline_shed", "serve_decode_steps",
    "serve_draft_accepted", "serve_draft_proposed",
    "serve_engine_errors", "serve_failed", "serve_handoffs",
    "serve_http_bind_failed", "serve_http_requests",
    "serve_occupancy_live", "serve_occupancy_slots",
    "serve_pages_allocated", "serve_pages_freed", "serve_pages_parked",
    "serve_pages_shared", "serve_pages_unparked", "serve_pool_damaged",
    "serve_pool_restores", "serve_pool_shrunk", "serve_preempted",
    "serve_prefill_chunks", "serve_prefills",
    "serve_prefix_blocks_shared", "serve_prefix_evicted",
    "serve_prefix_hits", "serve_prefix_misses",
    "serve_reattached", "serve_reattached_blocks", "serve_relayed",
    "serve_reprefill_tokens", "serve_reprefill_tokens_saved",
    "serve_requests", "serve_requeued", "serve_restart_mttr_ms",
    "serve_restarts", "serve_retired", "serve_shed",
    "serve_snapshot_failed", "serve_snapshot_rejected",
    "serve_snapshots", "serve_tail_prefills", "serve_tokens",
    "serve_trace_evicted", "serve_wedge_detected", "serve_wedged_close",
    "stability_barrier_timeouts", "stability_coordinated_trips",
    "stability_halts", "stability_observed", "stability_readbacks",
    "stability_rollbacks", "stability_skips", "stability_trips",
    "watchdog_trips", "wus_enabled",
})


def reset_counters():
    _counters.clear()


# -- memory accounting --------------------------------------------------------
_mem: Dict[str, int] = {
    "live_bytes": 0, "live_arrays": 0, "peak_live_bytes": 0,
    "last_delta_bytes": 0, "censuses": 0,
}


def memory_census() -> Dict[str, int]:
    """Walk ``jax.live_arrays()`` and refresh the gauges: current live
    device-buffer bytes/count, the delta since the previous census, and the
    process high-water mark. Called per lazy flush while memory profiling is
    active; cheap enough to call directly at snapshot points (bench)."""
    total = 0
    count = 0
    try:
        for a in jax.live_arrays():
            try:
                total += int(a.nbytes)
                count += 1
            except Exception:
                pass
    except Exception:
        return dict(_mem)
    _mem["last_delta_bytes"] = total - _mem["live_bytes"]
    _mem["live_bytes"] = total
    _mem["live_arrays"] = count
    _mem["censuses"] += 1
    if total > _mem["peak_live_bytes"]:
        _mem["peak_live_bytes"] = total
    return dict(_mem)


def memory_stats() -> Dict[str, int]:
    """Last-census gauges WITHOUT a fresh walk (safe mid-crash)."""
    return dict(_mem)


def _memory_active() -> bool:
    if _enabled and _memory_on:
        return True
    try:
        from ..framework import flags as _flags

        return bool(_flags.flag("FLAGS_profile_memory", False))
    except Exception:
        return False


# Native host recorder (runtime_cpp/trace.cc) when built — GIL-cheap record.
_native = None
_native_rec = None
_native_spans = False
_native_tried = False


def _native_recorder():
    global _native, _native_rec, _native_spans, _native_tried
    if _native_rec is not None or _native_tried:
        return _native_rec
    _native_tried = True
    try:
        from ..core import native as _native_mod

        _native = _native_mod.lib()
        if _native is not None:
            _native_rec = _native.ptt_create(1 << 16)
            _native_spans = bool(getattr(_native_mod, "HAS_SPANS", False))
    except Exception:
        _native = None
    return _native_rec


def _record(name: str, t0: int, tid: int = 0):
    """Hot-path event sink: dispatch/lazy/jit call this with a start stamp
    taken only when ``_enabled`` was already true (reference records every
    traced op the same way, imperative/tracer.cc:177). Events land in
    exactly ONE sink — the C++ ring when built, else the Python list —
    and ``export()``/``summary()`` merge the sinks."""
    t1 = time.perf_counter_ns()
    if not _enabled:
        return
    rec = _native_recorder()
    if rec is not None:
        nid = _native.ptt_intern(rec, name.encode())
        _native.ptt_record(rec, nid, tid, t0, t1)
    else:
        _events.append(_Event(name, t0, t1, tid))


class RecordEvent:
    """Reference: platform/profiler.h RecordEvent push/pop. Events land in
    the C++ ring buffer when the native runtime is built (Python list
    otherwise — one sink, merged at export)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()

    def end(self):
        if _enabled and self._t0 is not None:
            _record(self.name, self._t0)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def _reset_session():
    """Clear every session sink (python events, span list + attr table,
    native rings) so a new recording starts from an empty timeline."""
    _events.clear()
    spans._reset_session()
    rec = _native_recorder()
    if rec is not None:
        _native.ptt_reset(rec)


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Step-state schedule for ``Profiler.step()`` (reference
    ``profiler.make_scheduler``): after ``skip_first`` warmup steps, cycle
    through ``closed`` CLOSED steps, ``ready`` READY steps and ``record``
    recording steps (the last of which is RECORD_AND_RETURN — the trace is
    handed to ``on_trace_ready`` at the next ``step()``). ``repeat`` bounds
    the number of cycles (0 = unlimited)."""
    closed, ready, record = int(closed), int(ready), int(record)
    repeat, skip_first = int(repeat), int(skip_first)
    if record < 1:
        raise ValueError("make_scheduler: record must be >= 1")
    if min(closed, ready, repeat, skip_first) < 0:
        raise ValueError("make_scheduler: negative phase length")
    cycle = closed + ready + record

    def schedule(step: int) -> int:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


class Profiler:
    """Host-span profiler with an optional step scheduler.

    Without a scheduler, ``start()`` records until ``stop()`` (legacy
    behavior). With ``scheduler=make_scheduler(...)``, call ``step()`` once
    per train step: recording turns on only for the scheduled windows, and
    ``on_trace_ready(prof)`` fires at the end of each RECORD_AND_RETURN
    window (and at ``stop()`` if a window is still open)."""

    def __init__(
        self,
        targets=None,
        scheduler=None,
        on_trace_ready=None,
        timer_only=False,
        record_shapes=False,
        profile_memory=False,
        with_flops=False,
    ):
        self.timer_only = timer_only
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.profile_memory = profile_memory
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._jax_tracing = False
        self._trace_dir = None

    # -- state machine -----------------------------------------------------
    def _recording(self) -> bool:
        return self.current_state in (
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN,
        )

    def _apply(self, new_state: int):
        global _enabled
        was = self._recording()
        self.current_state = new_state
        now = self._recording()
        if now and not was:
            _enabled = True
            if not self.timer_only and not self._jax_tracing:
                self._trace_dir = os.environ.get(
                    "PADDLE_TPU_TRACE_DIR", "/tmp/paddle_tpu_trace"
                )
                try:
                    jax.profiler.start_trace(self._trace_dir)
                    self._jax_tracing = True
                except Exception:
                    self._jax_tracing = False
        elif was and not now:
            _enabled = False
            if self._jax_tracing:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._jax_tracing = False

    def start(self):
        global _memory_on
        self.step_num = 0
        _reset_session()
        if self.profile_memory:
            _memory_on = True
        first = (
            self.scheduler(0) if self.scheduler is not None else ProfilerState.RECORD
        )
        self._apply(first)

    def stop(self):
        global _memory_on
        was = self._recording()
        self._apply(ProfilerState.CLOSED)
        if self.profile_memory:
            _memory_on = False
        if was and self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self):
        """Advance the scheduler one train step. Drives the CLOSED → READY →
        RECORD → RECORD_AND_RETURN transitions; when the step that just
        finished was RECORD_AND_RETURN, the collected trace is handed to
        ``on_trace_ready`` and the session buffers reset for the next
        cycle."""
        finished_window = self.current_state == ProfilerState.RECORD_AND_RETURN
        self.step_num += 1
        new = (
            self.scheduler(self.step_num)
            if self.scheduler is not None
            else ProfilerState.RECORD
        )
        if finished_window:
            self._apply(ProfilerState.CLOSED)
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
            _reset_session()
        self._apply(new)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- output ------------------------------------------------------------
    def export(self, path, format="json"):
        """Chrome-trace export (reference chrometracing_logger.cc) with the
        engine-counter snapshot, memory gauges and flags embedded as trace
        ``metadata`` (self-describing traces); ``format="jsonl"`` writes the
        greppable one-object-per-line stream instead."""
        from . import export as _export

        if format in ("json", "chrome"):
            _export.chrome_trace(path)
        elif format in ("jsonl", "ndjson"):
            _export.jsonl(path)
        else:
            raise ValueError(f"unknown export format {format!r}")

    def summary(self, sorted_by="total", op_detail=True, thread_sep=False, time_unit="ms"):
        """Aggregate table over events + spans: calls, total, avg, min, max
        per name (reference profiler.summary shape). ``sorted_by`` one of
        ``total``/``calls``/``avg``/``min``/``max``/``name`` (None =
        total)."""
        from . import export as _export

        div = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}.get(time_unit, 1e6)
        agg: Dict[str, list] = {}
        rows = [
            (e.name, e.end - e.start) for e in _export.merged_events()
        ] + [
            (s["name"], s["t1"] - s["t0"]) for s in _export.merged_spans()
        ]
        for name, dur in rows:
            r = agg.get(name)
            if r is None:
                agg[name] = [1, dur, dur, dur]
            else:
                r[0] += 1
                r[1] += dur
                r[2] = min(r[2], dur)
                r[3] = max(r[3], dur)

        sorted_by = sorted_by or "total"
        keys = {
            "total": lambda kv: -kv[1][1],
            "calls": lambda kv: -kv[1][0],
            "avg": lambda kv: -(kv[1][1] / kv[1][0]),
            "min": lambda kv: -kv[1][2],
            "max": lambda kv: -kv[1][3],
            "name": lambda kv: kv[0],
        }
        if sorted_by not in keys:
            raise ValueError(
                f"summary: unknown sorted_by {sorted_by!r}; expected one of "
                f"{sorted(keys)}"
            )
        u = time_unit if time_unit in ("s", "ms", "us", "ns") else "ms"
        lines = [
            f"{'name':40s} {'calls':>8s} {'total_' + u:>12s} "
            f"{'avg_' + u:>10s} {'min_' + u:>10s} {'max_' + u:>10s}"
        ]
        for name, (calls, total, mn, mx) in sorted(agg.items(), key=keys[sorted_by]):
            lines.append(
                f"{name:40s} {calls:8d} {total / div:12.3f} "
                f"{total / calls / div:10.3f} {mn / div:10.3f} {mx / div:10.3f}"
            )
        return "\n".join(lines)


@contextlib.contextmanager
def profiler_guard(**kwargs):
    p = Profiler(**kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()


# Submodules import the package (counters/memory/_enabled), so they load
# AFTER those definitions.
from . import flight  # noqa: E402,F401
from . import spans  # noqa: E402,F401
from .spans import span  # noqa: E402,F401


def events() -> List[_Event]:
    """Merged flat-event view across sinks (Python list + native ring)."""
    from . import export as _export

    return _export.merged_events()


def span_events() -> List[dict]:
    """Merged finished-span view (dicts with ids, tid, times, attrs)."""
    from . import export as _export

    return _export.merged_spans()


def export_metrics(path: Optional[str] = None, format: str = "json"):
    """Counter + memory snapshot as JSON (default) or Prometheus text
    exposition format; returns the serialized string (and writes it to
    ``path`` when given)."""
    from . import export as _export

    return _export.export_metrics(path, format=format)
