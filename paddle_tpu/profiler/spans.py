"""Structured span tracer — nested, attributed host spans.

Reference parity: the new profiler composes HostTracer events into ONE
timeline with parent/child structure (``paddle/fluid/platform/profiler/``
HostEventRecorder + chrome-trace nesting). Here spans are the coarse-grained
skeleton of a training step — ``train_step`` → ``lazy_flush`` →
``trace``/``donate``/``compile``/``execute``, ``dp_sync`` → per-bucket
collective, ``ckpt_save`` → ``serialize``/``commit`` — each carrying typed
attributes (graph node count, executable-cache key + hit/miss, donated
bytes, bucket bytes, fallback reason) so the single most important lazy-mode
question — "did this step recompile, replay a cached executable, or stall on
sync?" — is answerable from the trace.

Two sinks, different lifetimes:

* the **flight recorder** (:mod:`.flight`) receives every finished span,
  always — a bounded deque append, so the disabled-path cost is near zero
  (spans exist only at flush/step/save granularity, never per op);
* the **profiler session** receives spans only while a
  :class:`~paddle_tpu.profiler.Profiler` is recording — into the native span
  ring (``runtime_cpp/trace.cc`` ``ptt_span_record``) when built, else a
  Python list; attributes ride in a bounded side table keyed by span id and
  are re-joined at export. Exactly ONE sink holds the timing record, so
  ``export()`` never double-counts.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

__all__ = ["Span", "span", "current_span", "active_spans",
           "add_span_observer", "remove_span_observer"]

_ids = itertools.count(1)  # GIL-atomic enough; 0 means "no parent"
_tls = threading.local()

# Compact per-thread display ids (chrome traces want small ints, and
# threading.get_ident() values are neither small nor stable across runs).
_tid_map: Dict[int, int] = {}
_tid_lock = threading.Lock()


def _tid() -> int:
    ident = threading.get_ident()
    t = _tid_map.get(ident)
    if t is None:
        with _tid_lock:
            t = _tid_map.setdefault(ident, len(_tid_map))
    return t


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = []
        _tls.stack = s
    return s


class Span:
    """One finished (or in-flight) span. ``attrs`` is a plain dict the owner
    may mutate until ``__exit__`` — e.g. the flush sets ``cache=hit/miss``
    only after the executable-cache probe."""

    __slots__ = ("name", "span_id", "parent_id", "tid", "t0", "t1", "attrs")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.span_id = next(_ids)
        self.parent_id = 0
        self.tid = 0
        self.t0 = 0
        self.t1 = 0
        self.attrs = attrs

    # -- context manager ---------------------------------------------------
    def __enter__(self) -> "Span":
        st = _stack()
        self.parent_id = st[-1].span_id if st else 0
        self.tid = _tid()
        st.append(self)
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.t1 = time.perf_counter_ns()
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:  # mis-nested exit (generator teardown): repair
            st.remove(self)
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        _emit(self)
        return False

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    @property
    def dur_ns(self) -> int:
        return self.t1 - self.t0

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "tid": self.tid,
            "t0": self.t0,
            "t1": self.t1,
            "dur_us": (self.t1 - self.t0) / 1000.0,
            "attrs": dict(self.attrs),
        }

    def __repr__(self):
        return (
            f"Span({self.name!r}, id={self.span_id}, parent={self.parent_id}, "
            f"dur_us={(self.t1 - self.t0) / 1000.0:.1f}, attrs={self.attrs})"
        )


def span(name: str, **attrs) -> Span:
    """``with span("lazy_flush", nodes=n) as sp: ... sp.set(cache="hit")``"""
    return Span(name, **attrs)


def current_span() -> Optional[Span]:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def active_spans() -> List[Span]:
    """The current thread's OPEN span stack, outermost first (post-mortem
    dumps serialize this to name the span a failure happened inside)."""
    return list(getattr(_tls, "stack", ()) or ())


# -- session sink ------------------------------------------------------------
# Python-side finished spans for the recording session (used when the native
# span ring is unavailable). Attrs always live Python-side: the native ring
# holds only (name_id, tid, t0, t1, span_id, parent_id).
_span_events: List[Span] = []
_span_attrs: Dict[int, dict] = {}  # span_id -> attrs (joined at export)
_SPAN_ATTRS_MAX = 1 << 16  # matches the native ring capacity


_pkg = None  # the parent package module, bound lazily (import-order safe)

# Span observers (serving/observe.py request tracing): called with every
# FINISHED span, synchronously on the emitting thread. The empty-tuple probe
# is the entire disabled-path cost; observers must be cheap and never raise
# (a raising observer is dropped from the fan-out, never from the sinks).
_observers: tuple = ()
_observers_lock = threading.Lock()


def add_span_observer(fn) -> None:
    global _observers
    with _observers_lock:
        if fn not in _observers:
            _observers = _observers + (fn,)


def remove_span_observer(fn) -> None:
    global _observers
    with _observers_lock:
        _observers = tuple(o for o in _observers if o is not fn)


def _emit(sp: Span) -> None:
    global _pkg
    if _pkg is None:
        import sys

        _pkg = sys.modules[__package__]
    if _observers:
        for fn in _observers:
            try:
                fn(sp)
            except Exception:
                remove_span_observer(fn)
    _pkg.flight.record(sp)
    if not _pkg._enabled:
        return
    rec = _pkg._native_recorder()
    if rec is not None and _pkg._native_spans:
        nid = _pkg._native.ptt_intern(rec, sp.name.encode())
        _pkg._native.ptt_span_record(
            rec, nid, sp.tid, sp.t0, sp.t1, sp.span_id, sp.parent_id
        )
        # the native record is timing-only; attrs ride this side table until
        # export re-joins them by span id. Evict oldest when full: the ring
        # keeps the NEWEST spans, so the table must age out the same way or
        # post-wraparound spans export attr-less while dead spans pin dicts.
        if sp.attrs:
            if len(_span_attrs) >= _SPAN_ATTRS_MAX:
                _span_attrs.pop(next(iter(_span_attrs)))
            _span_attrs[sp.span_id] = dict(sp.attrs)
    else:
        _span_events.append(sp)  # Span carries its own attrs to export


def update_attrs(sp: Span, **attrs) -> None:
    """Attach attributes to an ALREADY-FINISHED span (async runtime: the
    deferred memory census lands on the producing ``lazy_flush`` span after
    it closed). Python sinks (session list, flight ring) hold the Span object
    itself, so mutating it is enough; when the span's timing record went to
    the native ring, the side-table copy is refreshed too."""
    sp.attrs.update(attrs)
    if _pkg is not None and sp.span_id in _span_attrs:
        _span_attrs[sp.span_id] = dict(sp.attrs)
    elif (
        _pkg is not None
        and _pkg._enabled
        and _pkg._native_spans
        and sp.attrs
        and _pkg._native_recorder() is not None
    ):
        if len(_span_attrs) >= _SPAN_ATTRS_MAX:
            _span_attrs.pop(next(iter(_span_attrs)))
        _span_attrs[sp.span_id] = dict(sp.attrs)


def _reset_session() -> None:
    _span_events.clear()
    _span_attrs.clear()
