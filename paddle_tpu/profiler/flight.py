"""Flight recorder — always-on bounded span/counter history + crash dumps.

A production fleet's first question after a dead worker is "what was it
doing?". The reference answers it with log spew; here an always-on ring
(``collections.deque(maxlen=N)`` of finished spans — one append per span, no
allocation beyond the span itself) keeps the last N spans at near-zero cost,
and :func:`dump` writes a JSON post-mortem containing:

* the recent finished spans (with attributes) and the OPEN span stack of the
  dumping thread (so a NaN trip names the producing ``lazy_flush`` span);
* a full engine-counter snapshot (``profiler.counters()``) and memory gauges;
* the pending lazy-graph summary (node count + tail op names);
* the flags in effect and the arming state of fault injection.

Triggers wired in this repo: the lazy-mode NaN/Inf guard (``naninf_trips``),
``PreemptionGuard.drain``, checkpoint-save failure, and (opt-in via
:func:`install_excepthook` or ``with flight.on_crash():``) any uncaught
exception in a training loop.

Env knobs:

* ``PADDLE_TPU_FLIGHT_CAPACITY`` — ring size (default 256 spans).
* ``PADDLE_TPU_FLIGHT_DIR`` — dump directory (default
  ``<tmp>/paddle_tpu_flight``).
* ``PADDLE_TPU_FLIGHT_DISABLE=1`` — turn the recorder off entirely.
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import sys
import tempfile
import threading
import time
from typing import Optional

__all__ = [
    "record", "dump", "last_dump", "recent_spans", "capacity", "enabled",
    "install_excepthook", "on_crash", "clear", "add_context_provider",
    "remove_context_provider",
]

_DISABLED = os.environ.get("PADDLE_TPU_FLIGHT_DISABLE", "").lower() in (
    "1", "true", "yes",
)
try:
    _CAPACITY = int(os.environ.get("PADDLE_TPU_FLIGHT_CAPACITY", "256") or 256)
except ValueError:  # a malformed diagnostics knob must not take down import
    _CAPACITY = 256
# the ring is deliberately lock-free: deque.append with a maxlen is atomic
# under the GIL, and record() is the per-span hot path
_ring: "collections.deque" = collections.deque(maxlen=max(_CAPACITY, 8))
_lock = threading.Lock()
_last_dump: Optional[str] = None  # guarded_by: _lock
_dump_seq = itertools.count(1)  # same-millisecond dumps must not collide


def enabled() -> bool:
    return not _DISABLED


def capacity() -> int:
    return _ring.maxlen


def record(sp) -> None:
    """Hot-path sink: one bounded-deque append per finished span."""
    if not _DISABLED:
        _ring.append(sp)


def recent_spans() -> list:
    """Snapshot of the ring, oldest first."""
    return list(_ring)


def clear() -> None:
    _ring.clear()


def _dump_dir() -> str:
    return os.environ.get("PADDLE_TPU_FLIGHT_DIR") or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_flight"
    )


# Subsystems register a provider so EVERY dump — whatever its trigger —
# carries their context: the distributed watchdog adds the cross-rank
# progress table + suspect verdict this way, so a NaN trip on rank 3 still
# shows where ranks 0-2 were. Providers run inside dump() and must be cheap;
# a provider that raises contributes an error marker instead of masking the
# dump.
_context_providers: dict = {}


def add_context_provider(name: str, fn) -> None:
    _context_providers[name] = fn


def remove_context_provider(name: str) -> None:
    _context_providers.pop(name, None)


def _provider_context() -> dict:
    out = {}
    for name, fn in list(_context_providers.items()):
        try:
            out[name] = fn()
        except Exception as e:
            out[name] = {"error": repr(e)}
    return out


def _pending_graph_summary() -> dict:
    try:
        from ..core import lazy

        return lazy.pending_summary()
    except Exception:
        return {}


def dump(reason: str, extra: Optional[dict] = None, path: Optional[str] = None) -> Optional[str]:
    """Write the post-mortem JSON; returns its path (None when disabled or
    the write itself failed — a crash dump must never mask the crash)."""
    global _last_dump
    if _DISABLED:
        return None
    from . import export as _export
    from .spans import active_spans

    try:
        from ..fault import inject

        fault_state = {"armed": inject.armed(), "fired": inject.fired_counts()}
    except Exception:
        fault_state = {}
    doc = {
        "reason": reason,
        "pid": os.getpid(),
        "active_spans": [sp.to_dict() for sp in active_spans()],
        "recent_spans": [sp.to_dict() for sp in recent_spans()],
        # one snapshot shape everywhere: traces, metrics export, crash dumps
        **_export.metrics_snapshot(),
        "pending_graph": _pending_graph_summary(),
        "fault_inject": fault_state,
        "context": _provider_context(),
        "extra": dict(extra or {}),
    }
    try:
        if path is None:
            d = _dump_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d,
                f"flight_{os.getpid()}_{int(time.time() * 1000)}"
                f"_{next(_dump_seq)}_{reason}.json",
            )
        # tmp + os.replace: a monitoring agent tailing the dump dir (or a
        # relaunch reading its predecessor's post-mortem) must never see a
        # half-written document. Hand-rolled rather than framework.io's
        # atomic_open — the dumping process is often mid-crash and this path
        # must depend on nothing beyond os/json.
        # _dump_seq in the tmp name: two threads dumping to one explicit
        # `path` must not truncate each other's in-flight tmp
        tmp = f"{path}.tmp{os.getpid()}_{next(_dump_seq)}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
        except Exception:
            try:
                os.remove(tmp)  # no .tmp litter where an agent is tailing
            except OSError:
                pass
            raise
    except Exception:
        return None
    with _lock:
        _last_dump = path
    from . import counter_inc

    counter_inc("flight_dumps")
    return path


def last_dump() -> Optional[str]:
    """Path of the most recent dump written by this process (tests)."""
    return _last_dump


# -- uncaught-exception hookup ------------------------------------------------
class on_crash:
    """``with flight.on_crash():`` around a training loop — dumps (reason
    ``uncaught_exception``) before the exception propagates."""

    def __init__(self, reason: str = "uncaught_exception"):
        self.reason = reason

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None and not issubclass(
            exc_type, (KeyboardInterrupt, SystemExit, GeneratorExit)
        ):
            dump(self.reason, extra={"exception": repr(exc)})
        return False


_hook_installed = False


def install_excepthook() -> None:
    """Chain a sys.excepthook that dumps on any uncaught exception (opt-in:
    a library must not globally rewrite excepthook at import)."""
    global _hook_installed
    if _hook_installed:
        return
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
                dump("uncaught_exception", extra={"exception": repr(exc)})
        finally:
            prev(exc_type, exc, tb)

    sys.excepthook = hook
    _hook_installed = True
