"""Trace + metrics exporters.

One merge point for the profiler's sinks (reference
``chrometracing_logger.cc`` + ``profiler_statistic.cc``): flat events and
spans each live in exactly ONE sink — the native C++ rings
(``runtime_cpp/trace.cc``) when built, else the Python lists — and the
functions here re-join them (span attributes ride a Python side table keyed
by span id, since the native ring stores only the fixed-width record).

Formats:

* :func:`chrome_trace` — ``chrome://tracing`` / Perfetto JSON; spans are
  complete ("X") events whose time containment per tid gives the nesting,
  with ``span_id``/``parent_id``/attributes in ``args`` and the counter +
  memory + flags snapshot in top-level ``metadata`` (self-describing trace);
* :func:`jsonl` — greppable one-object-per-line stream (spans, events, then
  a metrics record);
* :func:`export_metrics` — counters + memory gauges as JSON or Prometheus
  text exposition format.
"""
from __future__ import annotations

import ctypes
import json
import re
import sys
import time
from typing import Dict, List, Optional

_EVENT_BYTES = 24   # trace.cc Event: u32 name_id | u32 tid | u64 t0 | u64 t1
_SPAN_BYTES = 40    # trace.cc SpanEvent: + u64 span_id | u64 parent_id
_MAX_DRAIN = 1 << 16


def _pkg():
    return sys.modules[__package__]


def _drain(kind: str) -> list:
    """Copy the native ring out (non-destructive; the cursor keeps running —
    ``Profiler.start()`` resets it per session). Returns [] without the
    native runtime."""
    m = _pkg()
    rec = m._native_recorder()
    if rec is None:
        return []
    if kind == "span" and not m._native_spans:
        return []
    import numpy as np

    nbytes = _EVENT_BYTES if kind == "event" else _SPAN_BYTES
    buf = ctypes.create_string_buffer(nbytes * _MAX_DRAIN)
    if kind == "event":
        n = m._native.ptt_drain(rec, buf, _MAX_DRAIN)
        dt = np.dtype(
            [("name_id", "<u4"), ("tid", "<u4"), ("t0", "<u8"), ("t1", "<u8")]
        )
    else:
        n = m._native.ptt_span_drain(rec, buf, _MAX_DRAIN)
        dt = np.dtype(
            [
                ("name_id", "<u4"), ("tid", "<u4"), ("t0", "<u8"),
                ("t1", "<u8"), ("span_id", "<u8"), ("parent_id", "<u8"),
            ]
        )
    if n <= 0:
        return []
    rows = np.frombuffer(buf, dtype=dt, count=int(n))
    names: Dict[int, str] = {}

    def name_of(nid: int) -> str:
        s = names.get(nid)
        if s is None:
            raw = m._native.ptt_name(rec, nid)
            s = raw.decode(errors="replace") if raw else f"name_{nid}"
            names[nid] = s
        return s

    return [(name_of(int(r["name_id"])), r) for r in rows]


def merged_events() -> list:
    """Flat events across sinks as ``_Event`` objects, time-ordered."""
    m = _pkg()
    out = list(m._events)
    for name, r in _drain("event"):
        out.append(m._Event(name, int(r["t0"]), int(r["t1"]), int(r["tid"])))
    out.sort(key=lambda e: e.start)
    return out


def merged_spans() -> List[dict]:
    """Finished spans across sinks as dicts (attrs re-joined), time-ordered."""
    m = _pkg()
    attrs = m.spans._span_attrs
    out = [sp.to_dict() for sp in m.spans._span_events]
    for name, r in _drain("span"):
        sid = int(r["span_id"])
        out.append(
            {
                "name": name,
                "span_id": sid,
                "parent_id": int(r["parent_id"]),
                "tid": int(r["tid"]),
                "t0": int(r["t0"]),
                "t1": int(r["t1"]),
                "dur_us": (int(r["t1"]) - int(r["t0"])) / 1000.0,
                "attrs": dict(attrs.get(sid, ())),
            }
        )
    out.sort(key=lambda s: s["t0"])
    return out


def metrics_snapshot() -> dict:
    """Counters + memory gauges + flags in effect (trace metadata payload)."""
    m = _pkg()
    try:
        from ..framework.flags import _FLAGS

        flags = dict(_FLAGS)
    except Exception:
        flags = {}
    snap = {
        "ts": time.time(),
        "counters": m.counters(),
        "memory": m.memory_stats(),
        "flags": flags,
    }
    for name, (_prom, json_obj) in _provider_results():
        snap[name] = json_obj
    return snap


def chrome_trace(path: str) -> None:
    events = [
        {
            "name": e.name,
            "ph": "X",
            "cat": "op",
            "ts": e.start / 1000.0,
            "dur": (e.end - e.start) / 1000.0,
            "pid": 0,
            "tid": e.tid,
        }
        for e in merged_events()
    ]
    for s in merged_spans():
        events.append(
            {
                "name": s["name"],
                "ph": "X",
                "cat": "span",
                "ts": s["t0"] / 1000.0,
                "dur": (s["t1"] - s["t0"]) / 1000.0,
                "pid": 0,
                "tid": s["tid"],
                "args": {
                    "span_id": s["span_id"],
                    "parent_id": s["parent_id"],
                    **s["attrs"],
                },
            }
        )
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": metrics_snapshot(),
    }
    from ..framework.io import atomic_open

    # a trace viewer (or collector) opening the file mid-export must see the
    # previous trace or the whole new one, never a truncated JSON document
    with atomic_open(path, "w") as f:
        json.dump(doc, f, default=str)


def jsonl(path: str) -> None:
    """One JSON object per line: ``{"type": "span"|"event"|"metrics", ...}``
    — greppable without a trace viewer (``grep lazy_flush trace.jsonl``)."""
    from ..framework.io import atomic_open

    with atomic_open(path, "w") as f:
        for s in merged_spans():
            f.write(json.dumps({"type": "span", **s}, default=str) + "\n")
        for e in merged_events():
            f.write(
                json.dumps(
                    {
                        "type": "event",
                        "name": e.name,
                        "t0": e.start,
                        "t1": e.end,
                        "dur_us": (e.end - e.start) / 1000.0,
                        "tid": e.tid,
                    }
                )
                + "\n"
            )
        f.write(json.dumps({"type": "metrics", **metrics_snapshot()}, default=str) + "\n")


# -- metrics ------------------------------------------------------------------
_METRIC_NAME = re.compile(r"[^a-zA-Z0-9_]")

# Extra metric providers (serving/observe.py SLO histograms + drift gauges).
# A provider is `fn() -> (prom_lines, json_obj)`: the lines are appended to
# the Prometheus exposition verbatim (the provider owns its TYPE headers —
# histogram/summary types that the counter/gauge loop above can't express)
# and the JSON object lands in `metrics_snapshot()` under the provider's
# name. Providers register at their module's import; a raising provider is
# skipped, never fatal to a scrape.
_metric_providers: Dict[str, object] = {}


def register_metric_provider(name: str, fn) -> None:
    _metric_providers[name] = fn


def _provider_results():
    for name, fn in list(_metric_providers.items()):
        try:
            yield name, fn()
        except Exception:
            continue


def prometheus_text() -> str:
    """Prometheus text exposition format: every engine counter as a
    ``counter``, every memory gauge as a ``gauge``, prefixed
    ``paddle_tpu_`` — plus registered provider output (serving SLO
    histograms, derived summaries, cost-drift gauges)."""
    m = _pkg()
    lines = []
    for name, val in sorted(m.counters().items()):
        mn = "paddle_tpu_" + _METRIC_NAME.sub("_", name)
        lines.append(f"# TYPE {mn} counter")
        lines.append(f"{mn} {int(val)}")
    for name, val in sorted(m.memory_stats().items()):
        mn = "paddle_tpu_memory_" + _METRIC_NAME.sub("_", name)
        lines.append(f"# TYPE {mn} gauge")
        lines.append(f"{mn} {int(val)}")
    for _name, (prom_lines, _json) in _provider_results():
        lines.extend(prom_lines)
    return "\n".join(lines) + "\n"


def export_metrics(path: Optional[str] = None, format: str = "json") -> str:
    if format == "json":
        text = json.dumps(metrics_snapshot(), default=str)
    elif format in ("prometheus", "prom", "text"):
        text = prometheus_text()
    else:
        raise ValueError(f"unknown metrics format {format!r}")
    if path is not None:
        # the textfile-collector pattern reads this concurrently: a torn
        # metrics file is a scrape error at best, silent bad data at worst
        from ..framework.io import atomic_open

        with atomic_open(path, "w") as f:
            f.write(text)
    return text
