"""paddle.batch — the v1 batch-reader decorator (reference
python/paddle/batch.py): wraps a sample reader creator into a batch reader
creator. Kept for v1 script compatibility; new code uses paddle.io.DataLoader.
"""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    batch_size = int(batch_size)
    if batch_size <= 0:
        raise ValueError(f"batch_size should be positive, got {batch_size}")

    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
