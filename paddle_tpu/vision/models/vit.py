"""Vision Transformer — ViT-B/16, ViT-L/16 (BASELINE inference config).

The reference era ships ViT via PaddleClas; included here as a first-class
model for the ViT-L inference benchmark (BASELINE.md). Patch embedding is one
strided conv (MXU-friendly); encoder uses the fused attention functional.
"""
from __future__ import annotations

import numpy as np

from ... import nn
from ...core.tensor import Tensor
from ...ops.manipulation import concat


class PatchEmbed(nn.Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, embed_dim=768):
        super().__init__()
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = nn.Conv2D(in_chans, embed_dim, kernel_size=patch_size, stride=patch_size)

    def forward(self, x):
        x = self.proj(x)  # (B, E, H/P, W/P)
        x = x.flatten(2).transpose([0, 2, 1])  # (B, N, E)
        return x


class VisionTransformer(nn.Layer):
    def __init__(
        self, img_size=224, patch_size=16, in_chans=3, num_classes=1000,
        embed_dim=768, depth=12, num_heads=12, mlp_ratio=4.0, dropout=0.0,
    ):
        super().__init__()
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans, embed_dim)
        n = self.patch_embed.num_patches
        self.cls_token = self.create_parameter([1, 1, embed_dim], default_initializer=nn.initializer.TruncatedNormal(std=0.02))
        self.pos_embed = self.create_parameter([1, n + 1, embed_dim], default_initializer=nn.initializer.TruncatedNormal(std=0.02))
        self.pos_drop = nn.Dropout(dropout)
        enc_layer = nn.TransformerEncoderLayer(
            embed_dim, num_heads, int(embed_dim * mlp_ratio), dropout=dropout,
            activation="gelu", normalize_before=True,
        )
        self.encoder = nn.TransformerEncoder(enc_layer, depth, norm=nn.LayerNorm(embed_dim))
        self.head = nn.Linear(embed_dim, num_classes) if num_classes > 0 else nn.Identity()

    def forward(self, x):
        x = self.patch_embed(x)
        B = x.shape[0]
        cls = self.cls_token.expand([B, 1, self.cls_token.shape[2]])
        x = concat([cls, x], axis=1)
        x = self.pos_drop(x + self.pos_embed)
        x = self.encoder(x)
        return self.head(x[:, 0])


def vit_b_16(num_classes=1000, **kwargs):
    return VisionTransformer(embed_dim=768, depth=12, num_heads=12, num_classes=num_classes, **kwargs)


def vit_l_16(num_classes=1000, **kwargs):
    return VisionTransformer(embed_dim=1024, depth=24, num_heads=16, num_classes=num_classes, **kwargs)
