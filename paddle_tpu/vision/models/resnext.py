"""ResNeXt (reference python/paddle/vision/models/resnext.py) — grouped-conv
bottlenecks on the ResNet skeleton."""
from __future__ import annotations

from ... import nn


class BottleneckBlock(nn.Layer):
    expansion = 2

    def __init__(self, inplanes, planes, stride=1, cardinality=32, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, stride=stride, padding=1,
                               groups=cardinality, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.conv3 = nn.Conv2D(planes, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNeXt(nn.Layer):
    CFG = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}

    def __init__(self, depth=50, cardinality=32, num_classes=1000, with_pool=True):
        super().__init__()
        layers = self.CFG[depth]
        base_width = 128 if cardinality == 32 else 256
        self.inplanes = 64
        self.cardinality = cardinality
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(base_width, layers[0])
        self.layer2 = self._make_layer(base_width * 2, layers[1], stride=2)
        self.layer3 = self._make_layer(base_width * 4, layers[2], stride=2)
        self.layer4 = self._make_layer(base_width * 8, layers[3], stride=2)
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.fc = (
            nn.Linear(base_width * 8 * BottleneckBlock.expansion, num_classes)
            if num_classes > 0 else None
        )

    def _make_layer(self, planes, blocks, stride=1):
        downsample = None
        out = planes * BottleneckBlock.expansion
        if stride != 1 or self.inplanes != out:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, out, 1, stride=stride, bias_attr=False),
                nn.BatchNorm2D(out),
            )
        layers = [BottleneckBlock(self.inplanes, planes, stride, self.cardinality, downsample)]
        self.inplanes = out
        for _ in range(1, blocks):
            layers.append(BottleneckBlock(self.inplanes, planes, cardinality=self.cardinality))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.pool is not None:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(start_axis=1))
        return x


def resnext50_32x4d(pretrained=False, **kw):
    return ResNeXt(50, 32, **kw)


def resnext101_32x4d(pretrained=False, **kw):
    return ResNeXt(101, 32, **kw)


def resnext152_32x4d(pretrained=False, **kw):
    return ResNeXt(152, 32, **kw)
