"""MobileNetV1 (reference python/paddle/vision/models/mobilenetv1.py)."""
from __future__ import annotations

from ... import nn


class ConvBNLayer(nn.Sequential):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, groups=1):
        super().__init__(
            nn.Conv2D(in_channels, out_channels, kernel_size, stride, padding,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_channels),
            nn.ReLU(),
        )


class DepthwiseSeparable(nn.Sequential):
    def __init__(self, in_channels, out_channels1, out_channels2, num_groups, stride, scale):
        super().__init__(
            ConvBNLayer(int(in_channels * scale), int(out_channels1 * scale), 3,
                        stride=stride, padding=1, groups=int(num_groups * scale)),
            ConvBNLayer(int(out_channels1 * scale), int(out_channels2 * scale), 1),
        )


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        cfg = [
            # in, out1, out2, groups, stride
            (32, 32, 64, 32, 1),
            (64, 64, 128, 64, 2),
            (128, 128, 128, 128, 1),
            (128, 128, 256, 128, 2),
            (256, 256, 256, 256, 1),
            (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1),
            (512, 512, 1024, 512, 2),
            (1024, 1024, 1024, 1024, 1),
        ]
        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        self.blocks = nn.Sequential(
            *[DepthwiseSeparable(i, o1, o2, g, s, scale) for i, o1, o2, g, s in cfg]
        )
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.fc = nn.Linear(int(1024 * scale), num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.pool is not None:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(start_axis=1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)
