"""ShuffleNetV2 (reference python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ... import nn


def channel_shuffle(x, groups):
    import paddle_tpu as paddle

    n, c, h, w = x.shape
    x = paddle.reshape(x, [n, groups, c // groups, h, w])
    x = paddle.transpose(x, [0, 2, 1, 3, 4])
    return paddle.reshape(x, [n, c, h, w])


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride):
        super().__init__()
        self.stride = stride
        branch_features = oup // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride, 1, groups=inp, bias_attr=False),
                nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch_features, 1, bias_attr=False),
                nn.BatchNorm2D(branch_features),
                nn.ReLU(),
            )
        else:
            self.branch1 = None
        in2 = inp if stride > 1 else branch_features
        self.branch2 = nn.Sequential(
            nn.Conv2D(in2, branch_features, 1, bias_attr=False),
            nn.BatchNorm2D(branch_features),
            nn.ReLU(),
            nn.Conv2D(branch_features, branch_features, 3, stride, 1,
                      groups=branch_features, bias_attr=False),
            nn.BatchNorm2D(branch_features),
            nn.Conv2D(branch_features, branch_features, 1, bias_attr=False),
            nn.BatchNorm2D(branch_features),
            nn.ReLU(),
        )

    def forward(self, x):
        import paddle_tpu as paddle

        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    CFG = {
        0.25: (24, 24, 48, 96, 512),
        0.33: (24, 32, 64, 128, 512),
        0.5: (24, 48, 96, 192, 1024),
        1.0: (24, 116, 232, 464, 1024),
        1.5: (24, 176, 352, 704, 1024),
        2.0: (24, 244, 488, 976, 2048),
    }

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        stages_repeats = [4, 8, 4]
        c0, c1, c2, c3, c_last = self.CFG[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, c0, 3, 2, 1, bias_attr=False), nn.BatchNorm2D(c0), nn.ReLU()
        )
        self.maxpool = nn.MaxPool2D(3, 2, 1)
        stages = []
        inp = c0
        for reps, outp in zip(stages_repeats, (c1, c2, c3)):
            stages.append(InvertedResidual(inp, outp, 2))
            for _ in range(reps - 1):
                stages.append(InvertedResidual(outp, outp, 1))
            inp = outp
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(inp, c_last, 1, bias_attr=False), nn.BatchNorm2D(c_last), nn.ReLU()
        )
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.fc = nn.Linear(c_last, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.pool is not None:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(start_axis=1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(2.0, **kw)
