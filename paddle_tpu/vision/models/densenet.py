"""DenseNet (reference python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ... import nn


class _DenseLayer(nn.Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(num_input_features)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(num_input_features, bn_size * growth_rate, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1, bias_attr=False)
        self.drop_rate = drop_rate
        self.dropout = nn.Dropout(drop_rate) if drop_rate else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        import paddle_tpu as paddle

        return paddle.concat([x, out], axis=1)


class _Transition(nn.Sequential):
    def __init__(self, num_input_features, num_output_features):
        super().__init__(
            nn.BatchNorm2D(num_input_features),
            nn.ReLU(),
            nn.Conv2D(num_input_features, num_output_features, 1, bias_attr=False),
            nn.AvgPool2D(2, stride=2),
        )


class DenseNet(nn.Layer):
    CFG = {
        121: (6, 12, 24, 16),
        161: (6, 12, 36, 24),
        169: (6, 12, 32, 32),
        201: (6, 12, 48, 32),
        264: (6, 12, 64, 48),
    }

    def __init__(self, layers=121, growth_rate=32, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        block_config = self.CFG[layers]
        if layers == 161:
            growth_rate, num_init_features = 48, 96
        else:
            num_init_features = 64
        self.features = nn.Sequential(
            nn.Conv2D(3, num_init_features, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init_features),
            nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        num_features = num_init_features
        blocks = []
        for i, num_layers in enumerate(block_config):
            for j in range(num_layers):
                blocks.append(_DenseLayer(num_features + j * growth_rate, growth_rate, bn_size, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                blocks.append(_Transition(num_features, num_features // 2))
                num_features //= 2
        self.blocks = nn.Sequential(*blocks)
        self.norm_final = nn.BatchNorm2D(num_features)
        self.relu = nn.ReLU()
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.classifier = nn.Linear(num_features, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.features(x)
        x = self.relu(self.norm_final(self.blocks(x)))
        if self.pool is not None:
            x = self.pool(x)
        if self.classifier is not None:
            x = self.classifier(x.flatten(start_axis=1))
        return x


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


def densenet264(pretrained=False, **kw):
    return DenseNet(264, **kw)
