"""InceptionV3 (reference python/paddle/vision/models/inceptionv3.py) —
compact faithful block structure."""
from __future__ import annotations

from ... import nn


class ConvBN(nn.Sequential):
    def __init__(self, cin, cout, k, stride=1, padding=0):
        super().__init__(
            nn.Conv2D(cin, cout, k, stride=stride, padding=padding, bias_attr=False),
            nn.BatchNorm2D(cout),
            nn.ReLU(),
        )


def _cat(xs):
    import paddle_tpu as paddle

    return paddle.concat(xs, axis=1)


class InceptionA(nn.Layer):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.b1 = ConvBN(cin, 64, 1)
        self.b5 = nn.Sequential(ConvBN(cin, 48, 1), ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(
            ConvBN(cin, 64, 1), ConvBN(64, 96, 3, padding=1), ConvBN(96, 96, 3, padding=1)
        )
        self.pool = nn.Sequential(nn.AvgPool2D(3, 1, 1), ConvBN(cin, pool_features, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b5(x), self.b3(x), self.pool(x)])


class InceptionB(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = ConvBN(cin, 384, 3, stride=2)
        self.b3d = nn.Sequential(
            ConvBN(cin, 64, 1), ConvBN(64, 96, 3, padding=1), ConvBN(96, 96, 3, stride=2)
        )
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return _cat([self.b3(x), self.b3d(x), self.pool(x)])


class InceptionC(nn.Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = ConvBN(cin, 192, 1)
        self.b7 = nn.Sequential(
            ConvBN(cin, c7, 1),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, 192, (7, 1), padding=(3, 0)),
        )
        self.b7d = nn.Sequential(
            ConvBN(cin, c7, 1),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, 192, (1, 7), padding=(0, 3)),
        )
        self.pool = nn.Sequential(nn.AvgPool2D(3, 1, 1), ConvBN(cin, 192, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b7(x), self.b7d(x), self.pool(x)])


class InceptionD(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = nn.Sequential(ConvBN(cin, 192, 1), ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            ConvBN(cin, 192, 1),
            ConvBN(192, 192, (1, 7), padding=(0, 3)),
            ConvBN(192, 192, (7, 1), padding=(3, 0)),
            ConvBN(192, 192, 3, stride=2),
        )
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return _cat([self.b3(x), self.b7(x), self.pool(x)])


class InceptionE(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = ConvBN(cin, 320, 1)
        self.b3_1 = ConvBN(cin, 384, 1)
        self.b3_2a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bd_1 = nn.Sequential(ConvBN(cin, 448, 1), ConvBN(448, 384, 3, padding=1))
        self.bd_2a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.bd_2b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.pool = nn.Sequential(nn.AvgPool2D(3, 1, 1), ConvBN(cin, 192, 1))

    def forward(self, x):
        a = self.b3_1(x)
        d = self.bd_1(x)
        return _cat([
            self.b1(x),
            _cat([self.b3_2a(a), self.b3_2b(a)]),
            _cat([self.bd_2a(d), self.bd_2b(d)]),
            self.pool(x),
        ])


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            ConvBN(3, 32, 3, stride=2), ConvBN(32, 32, 3), ConvBN(32, 64, 3, padding=1),
            nn.MaxPool2D(3, 2), ConvBN(64, 80, 1), ConvBN(80, 192, 3), nn.MaxPool2D(3, 2),
        )
        self.blocks = nn.Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160), InceptionC(768, 160), InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048),
        )
        self.pool = nn.AdaptiveAvgPool2D(1) if with_pool else None
        self.dropout = nn.Dropout(0.5)
        self.fc = nn.Linear(2048, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.pool is not None:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(self.dropout(x.flatten(start_axis=1)))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)
