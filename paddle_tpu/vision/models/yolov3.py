"""YOLOv3 detector — the detection-model story for the PP-YOLOE BASELINE row.

Reference: the detection op stack (``paddle/fluid/operators/detection/``:
yolo_box_op.cc, yolov3_loss_op.cc, multiclass/matrix NMS) consumed by
PaddleDetection's YOLO family. TPU-first shape discipline throughout: the
whole predict path — backbone, FPN neck, heads, ``yolo_box`` decode and
matrix NMS — is static-shape (detections padded to ``keep_top_k``), so the
entire detector AOT-compiles through ``paddle_tpu.inference`` (the serving
path the reference runs through AnalysisPredictor + TensorRT).
"""
from __future__ import annotations

import numpy as np

from ... import nn
from ...nn import functional as F
from ...ops.manipulation import concat, stack
from .. import ops as vops

__all__ = ["YOLOv3", "yolov3_darknet53", "YOLOv3Postprocess"]

ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119,
           116, 90, 156, 198, 373, 326]
ANCHOR_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]


class ConvBNLeaky(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride, padding=k // 2, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)

    def forward(self, x):
        return F.leaky_relu(self.bn(self.conv(x)), negative_slope=0.1)


class DarkBlock(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.c1 = ConvBNLeaky(ch, ch // 2, k=1)
        self.c2 = ConvBNLeaky(ch // 2, ch, k=3)

    def forward(self, x):
        return x + self.c2(self.c1(x))


class DarkNet53(nn.Layer):
    """Backbone (reference: PaddleDetection darknet.py structure)."""

    def __init__(self, depths=(1, 2, 8, 8, 4)):
        super().__init__()
        self.stem = ConvBNLeaky(3, 32, 3)
        chans = [64, 128, 256, 512, 1024]
        stages = []
        cin = 32
        for ch, n in zip(chans, depths):
            stage = [ConvBNLeaky(cin, ch, 3, stride=2)]
            stage += [DarkBlock(ch) for _ in range(n)]
            stages.append(nn.Sequential(*stage))
            cin = ch
        self.stages = nn.LayerList(stages)

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        return feats[2], feats[3], feats[4]  # C3 (/8), C4 (/16), C5 (/32)


class YoloDetBlock(nn.Layer):
    def __init__(self, cin, ch):
        super().__init__()
        self.body = nn.Sequential(
            ConvBNLeaky(cin, ch, 1), ConvBNLeaky(ch, ch * 2, 3),
            ConvBNLeaky(ch * 2, ch, 1), ConvBNLeaky(ch, ch * 2, 3),
            ConvBNLeaky(ch * 2, ch, 1),
        )
        self.tip = ConvBNLeaky(ch, ch * 2, 3)

    def forward(self, x):
        route = self.body(x)
        return route, self.tip(route)


def _upsample2x(x):
    return F.interpolate(x, scale_factor=2, mode="nearest")


class YOLOv3(nn.Layer):
    """YOLOv3 with a DarkNet-53 backbone and 3-scale FPN heads."""

    def __init__(self, num_classes=80, anchors=None, anchor_masks=None,
                 depths=(1, 2, 8, 8, 4)):
        super().__init__()
        self.num_classes = int(num_classes)
        self.anchors = list(anchors or ANCHORS)
        self.anchor_masks = [list(m) for m in (anchor_masks or ANCHOR_MASKS)]
        self.backbone = DarkNet53(depths=depths)
        out_ch = [512, 256, 128]
        in_ch = [1024, 512 + 256, 256 + 128]
        self.blocks = nn.LayerList([
            YoloDetBlock(cin, ch) for cin, ch in zip(in_ch, out_ch)])
        self.routes = nn.LayerList([
            ConvBNLeaky(512, 256, 1), ConvBNLeaky(256, 128, 1)])
        na = len(self.anchor_masks[0])
        self.heads = nn.LayerList([
            nn.Conv2D(ch * 2, na * (5 + self.num_classes), 1)
            for ch in out_ch])

    def forward(self, x):
        """Raw per-scale head maps [(B, A*(5+C), H/32, ...), /16, /8]."""
        c3, c4, c5 = self.backbone(x)
        outs = []
        feat = c5
        for i, (block, head) in enumerate(zip(self.blocks, self.heads)):
            route, tip = block(feat)
            outs.append(head(tip))
            if i < 2:
                lateral = _upsample2x(self.routes[i](route))
                feat = concat([lateral, (c4, c3)[i]], axis=1)
        return outs

    def loss(self, x, gt_box, gt_label, ignore_thresh=0.7):
        """Sum of per-scale yolov3_loss (reference yolov3_loss_op.cc)."""
        outs = self(x)
        total = None
        for out, mask, down in zip(outs, self.anchor_masks, (32, 16, 8)):
            l = vops.yolov3_loss(
                out, gt_box, gt_label, anchors=self.anchors, anchor_mask=mask,
                class_num=self.num_classes, ignore_thresh=ignore_thresh,
                downsample_ratio=down,
            ).mean()
            total = l if total is None else total + l
        return total

    def decode(self, outs, img_size, conf_thresh=0.005):
        """yolo_box per scale -> (B, total, 4) boxes + (B, total, C) scores."""
        boxes, scores = [], []
        for out, mask, down in zip(outs, self.anchor_masks, (32, 16, 8)):
            sel = []
            for m in mask:
                sel += self.anchors[2 * m: 2 * m + 2]
            b, s = vops.yolo_box(
                out, img_size, anchors=sel, class_num=self.num_classes,
                conf_thresh=conf_thresh, downsample_ratio=down)
            boxes.append(b)
            scores.append(s)
        return concat(boxes, axis=1), concat(scores, axis=1)


class YOLOv3Postprocess(nn.Layer):
    """Deploy wrapper: image -> padded (B, keep_top_k, 6) detections
    [class, score, x1, y1, x2, y2] via matrix NMS — one static-shape graph
    for ``paddle.static.save_inference_model`` + Predictor."""

    def __init__(self, model, img_hw=(416, 416), score_threshold=0.05,
                 nms_top_k=100, keep_top_k=100):
        super().__init__()
        self.model = model
        self.img_hw = tuple(img_hw)
        self.score_threshold = float(score_threshold)
        self.nms_top_k = int(nms_top_k)
        self.keep_top_k = int(keep_top_k)

    def forward(self, x):
        import paddle_tpu as paddle

        b = x.shape[0]
        img_size = paddle.to_tensor(
            np.tile(np.asarray([self.img_hw], np.int32), (b, 1)))
        outs = self.model(x)
        boxes, scores = self.model.decode(outs, img_size)
        dets = []
        for i in range(b):  # static python loop: one NMS per image
            out, _, _ = vops.matrix_nms(
                boxes[i], scores[i].transpose([1, 0]),
                score_threshold=self.score_threshold,
                nms_top_k=self.nms_top_k, keep_top_k=self.keep_top_k)
            dets.append(out)
        return stack(dets, axis=0)


def yolov3_darknet53(num_classes=80, **kw):
    return YOLOv3(num_classes=num_classes, **kw)
