"""Vision transforms (reference python/paddle/vision/transforms/) — numpy-based."""
from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif arr.ndim == 3 and self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        if arr.dtype == np.uint8 or arr.max() > 1.5:
            arr = arr / 255.0
        return Tensor(arr.astype(np.float32))


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out.astype(np.float32)) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        import jax
        import jax.numpy as jnp

        hwc = arr.ndim == 3 and arr.shape[-1] <= 4
        if arr.ndim == 2:
            arr = arr[..., None]
            hwc = True
        if hwc:
            out_shape = (self.size[0], self.size[1], arr.shape[-1])
        else:
            out_shape = (arr.shape[0], self.size[0], self.size[1])
        out = np.asarray(jax.image.resize(jnp.asarray(arr), out_shape, method="bilinear"))
        return out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i : i + th, j : j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            arr = np.pad(arr, [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[i : i + th, j : j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.asarray(img)[:, ::-1])
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.asarray(img)[::-1])
        return np.asarray(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        factor = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(arr * factor, 0, 255 if arr.max() > 1.5 else 1.0)


# functional API
def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.ascontiguousarray(np.asarray(img)[:, ::-1])


def vflip(img):
    return np.ascontiguousarray(np.asarray(img)[::-1])


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)
