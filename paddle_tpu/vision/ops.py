"""Vision ops (reference python/paddle/vision/ops.py + detection ops in
paddle/fluid/operators/detection/). Host-side where shapes are dynamic (NMS),
XLA where static (roi_align, box coding, deform conv via gather)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Reference: detection/nms ops — dynamic output ⇒ host implementation."""
    b = np.asarray(as_tensor(boxes)._data, dtype=np.float64)
    s = np.asarray(as_tensor(scores)._data) if scores is not None else np.arange(len(b))[::-1]
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.maximum(0, xx2 - xx1) * np.maximum(0, yy2 - yy1)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size", box_normalized=True, axis=0):
    pb = as_tensor(prior_box)
    tb = as_tensor(target_box)
    pbv = as_tensor(prior_box_var) if prior_box_var is not None else None

    def fn(pb, tb, *rest, code_type="encode_center_size"):
        pw = pb[:, 2] - pb[:, 0]
        ph = pb[:, 3] - pb[:, 1]
        px = pb[:, 0] + pw / 2
        py = pb[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0]
            th = tb[:, 3] - tb[:, 1]
            tx = tb[:, 0] + tw / 2
            ty = tb[:, 1] + th / 2
            out = jnp.stack(
                [(tx - px) / pw, (ty - py) / ph, jnp.log(tw / pw), jnp.log(th / ph)], axis=-1
            )
        else:
            dx, dy, dw, dh = tb[..., 0], tb[..., 1], tb[..., 2], tb[..., 3]
            cx = dx * pw + px
            cy = dy * ph + py
            w = jnp.exp(dw) * pw
            h = jnp.exp(dh) * ph
            out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
        if rest:
            out = out / rest[0] if code_type == "encode_center_size" else out
        return out

    args = [pb, tb] + ([pbv] if pbv is not None else [])
    return eager_call("box_coder", fn, args, {"code_type": code_type})


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    x, boxes = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)

    def fn(feat, rois, output_size, spatial_scale, aligned):
        oh, ow = output_size
        offset = 0.5 if aligned else 0.0

        def one_roi(roi):
            x1, y1, x2, y2 = roi * spatial_scale - offset
            ys = y1 + (jnp.arange(oh) + 0.5) * (y2 - y1) / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * (x2 - x1) / ow
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            coords = jnp.stack([gy.reshape(-1), gx.reshape(-1)])

            def sample_channel(ch):
                return jax.scipy.ndimage.map_coordinates(ch, coords, order=1, mode="constant").reshape(oh, ow)

            return jax.vmap(sample_channel)(feat[0])

        return jax.vmap(one_roi)(rois)

    return eager_call(
        "roi_align", fn, [x, boxes],
        {"output_size": tuple(output_size), "spatial_scale": spatial_scale, "aligned": aligned},
    )


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    x, img_size = as_tensor(x), as_tensor(img_size)
    anchors = list(anchors)
    na = len(anchors) // 2

    def fn(x, img_size, anchors=None, class_num=0, conf_thresh=0.0, downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
        n, c, h, w = x.shape
        an = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
        na = an.shape[0]
        x = x.reshape(n, na, 5 + class_num, h, w)
        gx, gy = jnp.meshgrid(jnp.arange(w), jnp.arange(h), indexing="xy")
        bx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / w
        by = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / h
        bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / (downsample_ratio * w)
        bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / (downsample_ratio * h)
        conf = jax.nn.sigmoid(x[:, :, 4])
        probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
        img_h = img_size[:, 0].reshape(n, 1, 1, 1).astype(jnp.float32)
        img_w = img_size[:, 1].reshape(n, 1, 1, 1).astype(jnp.float32)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        mask = conf.reshape(n, -1, 1) > conf_thresh
        scores = jnp.where(mask, scores, 0.0)
        return boxes, scores

    out = eager_call(
        "yolo_box", fn, [x, img_size],
        {"anchors": tuple(anchors), "class_num": class_num, "conf_thresh": conf_thresh,
         "downsample_ratio": downsample_ratio, "clip_bbox": clip_bbox, "scale_x_y": scale_x_y},
        differentiable=False,
    )
    return out[0], out[1]


def _roi_batch_ids(boxes_num, n_rois):
    """Per-RoI image index from the boxes_num split (reference RoisNum)."""
    if boxes_num is None:
        return np.zeros(n_rois, np.int32)
    counts = np.asarray(as_tensor(boxes_num)._data).reshape(-1).astype(np.int64)
    return np.repeat(np.arange(len(counts)), counts).astype(np.int32)[:n_rois]


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool each RoI to a fixed grid (reference detection/roi_pool_op):
    every output cell is the max over a dense sample grid covering its bin."""
    xt, bt = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = int(output_size[0]), int(output_size[1])
    batch_ids = _roi_batch_ids(boxes_num, int(bt.shape[0]))
    from ..core.tensor import Tensor as _T

    bid_t = _T(jnp.asarray(batch_ids), stop_gradient=True)

    S = 4  # samples per bin edge: max over S*S points approximates bin max

    def fn(feat, rois, bids, oh=0, ow=0, scale=1.0):
        N, C, H, W = feat.shape

        def one_roi(roi, bid):
            x1, y1, x2, y2 = roi * scale
            # S dense samples inside each of the oh/ow bins
            ys = y1 + (y2 - y1) * (jnp.arange(oh * S) + 0.5) / (oh * S)
            xs = x1 + (x2 - x1) * (jnp.arange(ow * S) + 0.5) / (ow * S)
            yi = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, W - 1)
            v = feat[bid][:, yi][:, :, xi]  # (C, oh*S, ow*S)
            return v.reshape(C, oh, S, ow, S).max(axis=(2, 4))

        return jax.vmap(one_roi)(rois, bids)

    return eager_call(
        "roi_pool", fn, [xt, bt, bid_t],
        attrs={"oh": oh, "ow": ow, "scale": float(spatial_scale)},
    )


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI average pool (reference detection/psroi_pool_op)."""
    xt, bt = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = int(output_size[0]), int(output_size[1])
    batch_ids = _roi_batch_ids(boxes_num, int(bt.shape[0]))
    from ..core.tensor import Tensor as _T

    bid_t = _T(jnp.asarray(batch_ids), stop_gradient=True)
    S = 4

    def fn(feat, rois, bids, oh=0, ow=0, scale=1.0):
        N, C, H, W = feat.shape
        out_c = C // (oh * ow)

        def one_roi(roi, bid):
            x1, y1, x2, y2 = roi * scale
            ys = y1 + (y2 - y1) * (jnp.arange(oh * S) + 0.5) / (oh * S)
            xs = x1 + (x2 - x1) * (jnp.arange(ow * S) + 0.5) / (ow * S)
            yi = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, W - 1)
            f = feat[bid][:, yi][:, :, xi]  # (C, oh*S, ow*S)
            f = f.reshape(out_c, oh, ow, oh, S, ow, S)

            # position-sensitive: channel block (i,j) is averaged over bin (i,j)
            def cell(i, j):
                return f[:, i, j, i, :, j, :].mean(axis=(-1, -2))  # (out_c,)

            grid = jax.vmap(lambda i: jax.vmap(lambda j: cell(i, j))(jnp.arange(ow)))(
                jnp.arange(oh)
            )  # (oh, ow, out_c)
            return jnp.moveaxis(grid, -1, 0)

        return jax.vmap(one_roi)(rois, bids)

    return eager_call(
        "psroi_pool", fn, [xt, bt, bid_t],
        attrs={"oh": oh, "ow": ow, "scale": float(spatial_scale)},
    )


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference detection/prior_box_op)."""
    it, imt = as_tensor(input), as_tensor(image)
    ratios = list(aspect_ratios)
    if flip:
        ratios = ratios + [1.0 / r for r in ratios if r != 1.0]

    H, W = int(it.shape[-2]), int(it.shape[-1])
    IH, IW = int(imt.shape[-2]), int(imt.shape[-1])
    step_h = steps[1] or IH / H
    step_w = steps[0] or IW / W

    sizes = []
    for k, ms in enumerate(min_sizes):
        for r in ratios:
            sizes.append((ms * (r ** 0.5), ms / (r ** 0.5)))
        if max_sizes:
            mx = max_sizes[k]
            sizes.append(((ms * mx) ** 0.5, (ms * mx) ** 0.5))
    sizes = np.asarray(sizes, np.float32)  # (P, 2) as (w, h)

    cy = (np.arange(H) + offset) * step_h
    cx = (np.arange(W) + offset) * step_w
    gx, gy = np.meshgrid(cx, cy)
    centers = np.stack([gx, gy], -1)[..., None, :]  # (H, W, 1, 2)
    wh = sizes[None, None]  # (1, 1, P, 2)
    mins = (centers - wh / 2) / np.asarray([IW, IH], np.float32)
    maxs = (centers + wh / 2) / np.asarray([IW, IH], np.float32)
    boxes = np.concatenate([mins, maxs], -1).astype(np.float32)  # (H, W, P, 4)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), boxes.shape).copy()
    return Tensor(jnp.asarray(boxes), stop_gradient=True), Tensor(jnp.asarray(var), stop_gradient=True)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None, name=None):
    """Assign RoIs to FPN levels (reference detection/distribute_fpn_proposals_op).
    Host-side (dynamic shapes), like the reference's CPU kernel."""
    rois = np.asarray(as_tensor(fpn_rois)._data)
    w = rois[:, 2] - rois[:, 0] + (1 if pixel_offset else 0)
    h = rois[:, 3] - rois[:, 1] + (1 if pixel_offset else 0)
    scale = np.sqrt(np.maximum(w * h, 1e-6))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, nums, order = [], [], []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        outs.append(Tensor(jnp.asarray(rois[idx]), stop_gradient=True))
        nums.append(Tensor(jnp.asarray(np.asarray([len(idx)], np.int32)), stop_gradient=True))
        order.append(idx)
    restore = np.argsort(np.concatenate(order)) if order else np.zeros(0, np.int64)
    return outs, Tensor(jnp.asarray(restore.astype(np.int32)), stop_gradient=True), nums


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 (reference operators/deformable_conv_op.cu):
    bilinear-sample the input at offset-shifted taps, then contract — a
    gather + matmul that XLA fuses; the MXU does the contraction."""
    xt, ot, wt = as_tensor(x), as_tensor(offset), as_tensor(weight)
    args = [xt, ot, wt]
    if mask is not None:
        args.append(as_tensor(mask))
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    kh, kw = int(wt.shape[-2]), int(wt.shape[-1])

    def fn(feat, off, w, *rest, sh=1, sw=1, ph=0, pw=0, dh=1, dw=1, kh=3, kw=3, groups=1):
        msk = rest[0] if rest else None
        N, C, H, W = feat.shape
        OC = w.shape[0]
        OH = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        OW = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        feat_p = jnp.pad(feat, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        Hp, Wp = H + 2 * ph, W + 2 * pw
        # offsets: (N, dg*kh*kw*2, OH, OW) interleaved (dy, dx) PER TAP —
        # the reference/mmcv layout (deformable_conv_op channel order)
        dg = off.shape[1] // (2 * kh * kw)
        off = off.reshape(N, dg, kh * kw, 2, OH, OW)
        cpg = C // dg  # channels per deformable group

        def sample(feat_n, off_n, msk_n):
            def group_sample(feat_g, off_g, msk_g):
                # feat_g (cpg, Hp, Wp); off_g (kh*kw, 2, OH, OW); msk_g
                # (kh*kw, OH, OW) or () sentinel
                dy = off_g[:, 0].reshape(kh, kw, OH, OW)
                dx = off_g[:, 1].reshape(kh, kw, OH, OW)
                # tap positions per (kh, kw, OH, OW)
                yy = (jnp.arange(OH) * sh)[None, None, :, None] + (jnp.arange(kh) * dh)[:, None, None, None] + dy
                xx = (jnp.arange(OW) * sw)[None, None, None, :] + (jnp.arange(kw) * dw)[None, :, None, None] + dx
                y0 = jnp.floor(yy)
                x0 = jnp.floor(xx)
                wy = yy - y0
                wx = xx - x0

                def gat(yi, xi):
                    inb = (yi >= 0) & (yi < Hp) & (xi >= 0) & (xi < Wp)
                    v = feat_g[:, jnp.clip(yi, 0, Hp - 1), jnp.clip(xi, 0, Wp - 1)]
                    return jnp.where(inb[None], v, 0.0)

                y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
                v = (gat(y0i, x0i) * (1 - wy) * (1 - wx) + gat(y0i, x0i + 1) * (1 - wy) * wx
                     + gat(y0i + 1, x0i) * wy * (1 - wx) + gat(y0i + 1, x0i + 1) * wy * wx)
                if msk_g.ndim:
                    v = v * msk_g.reshape(kh, kw, OH, OW)[None]
                return v  # (cpg, kh, kw, OH, OW)

            feat_grp = feat_n.reshape(dg, cpg, Hp, Wp)
            msk_grp = (
                msk_n.reshape(dg, kh * kw, OH, OW)
                if msk_n.ndim else jnp.broadcast_to(msk_n, (dg,))
            )
            v = jax.vmap(group_sample)(feat_grp, off_n, msk_grp)
            return v.reshape(C, kh, kw, OH, OW)

        if msk is not None:
            cols = jax.vmap(sample)(feat_p, off, msk)
        else:
            zero = jnp.zeros(())  # 0-d sentinel: "no mask"
            cols = jax.vmap(lambda f, o: sample(f, o, zero))(feat_p, off)
        cols = cols.reshape(N, C, kh, kw, OH, OW)
        G = groups
        if G == 1:
            return jnp.einsum("nckhij,ockh->noij", cols, w)
        # grouped conv: contract each channel group with its weight block
        cols_g = cols.reshape(N, G, C // G, kh, kw, OH, OW)
        w_g = w.reshape(G, w.shape[0] // G, C // G, kh, kw)
        out = jnp.einsum("ngckhij,gockh->ngoij", cols_g, w_g)
        return out.reshape(N, w.shape[0], OH, OW)

    out = eager_call(
        "deform_conv2d", fn, args,
        attrs={"sh": stride[0], "sw": stride[1], "ph": padding[0], "pw": padding[1],
               "dh": dilation[0], "dw": dilation[1], "kh": kh, "kw": kw,
               "groups": int(groups)},
    )
    if bias is not None:
        out = out + as_tensor(bias).reshape([1, -1, 1, 1])
    return out


def _make_deform_conv_layer():
    from ..nn.layer.layers import Layer

    class DeformConv2D(Layer):
        """Layer over deform_conv2d (reference vision/ops.py DeformConv2D);
        parameters register through the Layer machinery so optimizers and
        state_dict see them."""

        def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                     padding=0, dilation=1, deformable_groups=1, groups=1,
                     weight_attr=None, bias_attr=None):
            super().__init__()
            k = kernel_size if isinstance(kernel_size, (tuple, list)) else (kernel_size, kernel_size)
            self.weight = self.create_parameter(
                [out_channels, in_channels // groups, k[0], k[1]], attr=weight_attr
            )
            self.bias = (
                None if bias_attr is False
                else self.create_parameter([out_channels], attr=bias_attr, is_bias=True)
            )
            self.stride, self.padding, self.dilation = stride, padding, dilation
            self.deformable_groups, self.groups = deformable_groups, groups

        def forward(self, x, offset, mask=None):
            return deform_conv2d(
                x, offset, self.weight, self.bias, self.stride, self.padding,
                self.dilation, self.deformable_groups, self.groups, mask,
            )

    return DeformConv2D


DeformConv2D = _make_deform_conv_layer()


def _pairwise_iou(a, b, off=0.0):
    """(N, 4) x (M, 4) xyxy -> (N, M) IoU; off=1.0 for unnormalized boxes."""
    area_a = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    area_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    x1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    y1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    x2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    y2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(x2 - x1 + off, 0) * jnp.maximum(y2 - y1 + off, 0)
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-10)


# -- Round-5 detection op family ---------------------------------------------
# Reference: paddle/fluid/operators/detection/*.cc. All STATIC-SHAPE and
# jit-safe: "suppression" ops use score decay (matrix NMS) or masked top-k
# instead of dynamic output counts, so they compile into AOT serving graphs.

def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU matrix (detection/iou_similarity_op.cc).
    x: (N, 4), y: (M, 4) xyxy -> (N, M)."""
    x, y = as_tensor(x), as_tensor(y)

    def fn(a, b, box_normalized):
        return _pairwise_iou(a, b, 0.0 if box_normalized else 1.0)

    return eager_call("iou_similarity", fn, [x, y],
                      {"box_normalized": bool(box_normalized)})


def box_clip(boxes, img_shape, name=None):
    """Clip xyxy boxes to image bounds (detection/box_clip_op.cc).
    boxes: (..., 4); img_shape: (2,) [h, w]."""
    boxes, img_shape = as_tensor(boxes), as_tensor(img_shape)

    def fn(b, im):
        h, w = im[0], im[1]
        return jnp.stack([
            jnp.clip(b[..., 0], 0, w - 1), jnp.clip(b[..., 1], 0, h - 1),
            jnp.clip(b[..., 2], 0, w - 1), jnp.clip(b[..., 3], 0, h - 1),
        ], axis=-1)

    return eager_call("box_clip", fn, [boxes, img_shape])


def anchor_generator(input, anchor_sizes, aspect_ratios, stride,
                     variances=(0.1, 0.1, 0.2, 0.2), offset=0.5, name=None):
    """Dense anchors over a feature map (detection/anchor_generator_op.cc).
    input: (N, C, H, W). Returns (anchors (H, W, A, 4), variances same)."""
    input = as_tensor(input)

    def fn(x, anchor_sizes, aspect_ratios, stride, variances, offset):
        h, w = x.shape[2], x.shape[3]
        cx = (jnp.arange(w, dtype=jnp.float32) + offset) * stride[0]
        cy = (jnp.arange(h, dtype=jnp.float32) + offset) * stride[1]
        shapes = []
        for s in anchor_sizes:
            for r in aspect_ratios:
                bw = s * np.sqrt(r)
                bh = s / np.sqrt(r)
                shapes.append((bw, bh))
        ws = jnp.asarray([sh[0] for sh in shapes], jnp.float32)
        hs = jnp.asarray([sh[1] for sh in shapes], jnp.float32)
        gx = cx[None, :, None]
        gy = cy[:, None, None]
        anchors = jnp.stack([
            jnp.broadcast_to(gx - ws / 2, (h, w, len(shapes))),
            jnp.broadcast_to(gy - hs / 2, (h, w, len(shapes))),
            jnp.broadcast_to(gx + ws / 2, (h, w, len(shapes))),
            jnp.broadcast_to(gy + hs / 2, (h, w, len(shapes))),
        ], axis=-1)
        var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), anchors.shape)
        return anchors, var

    return eager_call(
        "anchor_generator", fn, [input],
        {"anchor_sizes": tuple(float(s) for s in anchor_sizes),
         "aspect_ratios": tuple(float(r) for r in aspect_ratios),
         "stride": tuple(float(s) for s in (stride if isinstance(stride, (list, tuple)) else (stride, stride))),
         "variances": tuple(float(v) for v in variances),
         "offset": float(offset)},
        differentiable=False,
    )


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variances=(0.1, 0.1, 0.2, 0.2), clip=False, step=0.0,
                      offset=0.5, name=None):
    """Density prior boxes (detection/density_prior_box_op.cc): each density d
    subdivides the cell into d x d shifted centers for every fixed size."""
    input, image = as_tensor(input), as_tensor(image)

    def fn(x, im, densities, fixed_sizes, fixed_ratios, variances, clip, step, offset):
        h, w = x.shape[2], x.shape[3]
        img_h, img_w = im.shape[2], im.shape[3]
        step_x = step or img_w / w
        step_y = step or img_h / h
        boxes = []
        for d, fs in zip(densities, fixed_sizes):
            for r in fixed_ratios:
                bw = fs * np.sqrt(r) / img_w
                bh = fs / np.sqrt(r) / img_h
                shift = 1.0 / d
                for di in range(d):
                    for dj in range(d):
                        ox = (dj + 0.5) * shift - 0.5 + offset
                        oy = (di + 0.5) * shift - 0.5 + offset
                        cx = (jnp.arange(w, dtype=jnp.float32)[None, :] + ox) * step_x / img_w
                        cy = (jnp.arange(h, dtype=jnp.float32)[:, None] + oy) * step_y / img_h
                        boxes.append(jnp.stack([
                            jnp.broadcast_to(cx - bw / 2, (h, w)),
                            jnp.broadcast_to(cy - bh / 2, (h, w)),
                            jnp.broadcast_to(cx + bw / 2, (h, w)),
                            jnp.broadcast_to(cy + bh / 2, (h, w)),
                        ], axis=-1))
        out = jnp.stack(boxes, axis=2)  # (H, W, A, 4)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), out.shape)
        return out, var

    return eager_call(
        "density_prior_box", fn, [input, image],
        {"densities": tuple(int(d) for d in densities),
         "fixed_sizes": tuple(float(s) for s in fixed_sizes),
         "fixed_ratios": tuple(float(r) for r in fixed_ratios),
         "variances": tuple(float(v) for v in variances),
         "clip": bool(clip), "step": float(step), "offset": float(offset)},
        differentiable=False,
    )


def bipartite_match(dist_mat, name=None):
    """Greedy bipartite matching (detection/bipartite_match_op.cc): each
    column matched to at most one row, best-first. dist: (N, M) similarity.
    Returns (match_indices (M,) row per column or -1, match_dist (M,))."""
    dist_mat = as_tensor(dist_mat)

    def fn(d):
        n, m = d.shape

        def body(_, carry):
            dd, idx, val = carry
            flat = jnp.argmax(dd)
            i, j = flat // m, flat % m
            best = dd[i, j]
            take = best > -jnp.inf
            idx = jnp.where(take, idx.at[j].set(i), idx)
            val = jnp.where(take, val.at[j].set(best), val)
            dd = jnp.where(take, dd.at[i, :].set(-jnp.inf).at[:, j].set(-jnp.inf), dd)
            return dd, idx, val

        idx0 = jnp.full((m,), -1, jnp.asarray(0).dtype)  # follow x64 mode
        val0 = jnp.zeros((m,), d.dtype)
        _, idx, val = jax.lax.fori_loop(0, min(n, m), body, (d, idx0, val0))
        return idx, val

    return eager_call("bipartite_match", fn, [dist_mat], differentiable=False)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=100, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=-1, normalized=True,
               name=None):
    """Matrix NMS (detection/matrix_nms_op.cc; SOLOv2) — parallel score DECAY
    instead of sequential suppression: TPU-native, fully static shapes.
    bboxes: (N, 4); scores: (C, N) per-class. Returns (out (keep_top_k, 6)
    [class, score, x1, y1, x2, y2], index (keep_top_k,), rois_num ())."""
    bboxes, scores = as_tensor(bboxes), as_tensor(scores)

    def fn(boxes, sc, score_threshold, post_threshold, nms_top_k, keep_top_k,
           use_gaussian, gaussian_sigma, background_label, normalized):
        c, n = sc.shape
        k = min(nms_top_k, n)
        off = 0.0 if normalized else 1.0

        def per_class(cls_scores):
            top_s, top_i = jax.lax.top_k(cls_scores, k)
            b = boxes[top_i]
            iou = _pairwise_iou(b, b, off)
            # iou[i, j] for i < j: suppressor i (higher score) vs j
            iou = jnp.triu(iou, 1)
            # compensate_i: how suppressed box i itself already is
            comp = jnp.max(iou, axis=0)
            if use_gaussian:
                decay = jnp.min(jnp.where(
                    jnp.triu(jnp.ones((k, k), bool), 1),
                    jnp.exp((comp[:, None] ** 2 - iou ** 2) / gaussian_sigma),
                    jnp.inf), axis=0)
            else:
                decay = jnp.min(jnp.where(
                    jnp.triu(jnp.ones((k, k), bool), 1),
                    (1.0 - iou) / jnp.maximum(1.0 - comp[:, None], 1e-10),
                    jnp.inf), axis=0)
            decay = jnp.where(jnp.isfinite(decay), decay, 1.0)
            s = top_s * decay
            s = jnp.where(top_s > score_threshold, s, 0.0)
            return s, top_i

        cls_ids = jnp.arange(c)
        dec_s, dec_i = jax.vmap(per_class)(sc)  # (C, k)
        if background_label >= 0:
            dec_s = dec_s.at[background_label].set(0.0)
        flat_s = dec_s.reshape(-1)
        flat_i = dec_i.reshape(-1)
        flat_c = jnp.repeat(cls_ids, k)
        kk = min(keep_top_k, flat_s.shape[0])
        sel_s, sel = jax.lax.top_k(flat_s, kk)
        sel_box = boxes[flat_i[sel]]
        sel_c = flat_c[sel].astype(boxes.dtype)
        ok = sel_s > post_threshold
        out = jnp.concatenate(
            [sel_c[:, None], sel_s[:, None], sel_box], axis=1)
        out = jnp.where(ok[:, None], out, -1.0)
        return out, jnp.where(ok, flat_i[sel], -1), ok.sum()

    return eager_call(
        "matrix_nms", fn, [bboxes, scores],
        {"score_threshold": float(score_threshold),
         "post_threshold": float(post_threshold),
         "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
         "use_gaussian": bool(use_gaussian),
         "gaussian_sigma": float(gaussian_sigma),
         "background_label": int(background_label),
         "normalized": bool(normalized)},
        differentiable=False,
    )


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=100,
                   keep_top_k=100, nms_threshold=0.5, normalized=True,
                   background_label=-1, name=None):
    """Static-shape multiclass NMS (detection/multiclass_nms_op.cc): per-class
    hard suppression emulated by score decay with threshold 1 (a box whose
    IoU with any higher-scored kept box exceeds nms_threshold is zeroed),
    computed as a fixed-point over the score-sorted triangular IoU matrix."""
    bboxes, scores = as_tensor(bboxes), as_tensor(scores)

    def fn(boxes, sc, score_threshold, nms_top_k, keep_top_k, nms_threshold,
           normalized, background_label):
        c, n = sc.shape
        k = min(nms_top_k, n)
        off = 0.0 if normalized else 1.0

        def per_class(cls_scores):
            top_s, top_i = jax.lax.top_k(cls_scores, k)
            b = boxes[top_i]
            iou = jnp.triu(_pairwise_iou(b, b, off), 1)
            over = iou > nms_threshold

            # sequential hard-NMS as a fori fixed point over sorted boxes:
            # keep[i] iff no kept j<i overlaps i
            def body(i, keep):
                sup = jnp.any(over[:, i] & keep)
                return keep.at[i].set(~sup & (top_s[i] > score_threshold))

            keep = jax.lax.fori_loop(
                0, k, body, jnp.zeros((k,), bool).at[0].set(top_s[0] > score_threshold))
            return jnp.where(keep, top_s, 0.0), top_i

        dec_s, dec_i = jax.vmap(per_class)(sc)
        if background_label >= 0:
            dec_s = dec_s.at[background_label].set(0.0)
        flat_s = dec_s.reshape(-1)
        flat_i = dec_i.reshape(-1)
        flat_c = jnp.repeat(jnp.arange(c), k)
        kk = min(keep_top_k, flat_s.shape[0])
        sel_s, sel = jax.lax.top_k(flat_s, kk)
        ok = sel_s > 0
        out = jnp.concatenate([
            flat_c[sel].astype(boxes.dtype)[:, None], sel_s[:, None],
            boxes[flat_i[sel]]], axis=1)
        out = jnp.where(ok[:, None], out, -1.0)
        return out, jnp.where(ok, flat_i[sel], -1), ok.sum()

    return eager_call(
        "multiclass_nms", fn, [bboxes, scores],
        {"score_threshold": float(score_threshold), "nms_top_k": int(nms_top_k),
         "keep_top_k": int(keep_top_k), "nms_threshold": float(nms_threshold),
         "normalized": bool(normalized), "background_label": int(background_label)},
        differentiable=False,
    )


def target_assign(x, match_indices, mismatch_value=0, name=None):
    """Gather per-column targets by match indices (detection/target_assign_op).
    x: (N, D); match_indices: (M,) row ids or -1. Returns (out (M, D), weight
    (M, 1))."""
    x, match_indices = as_tensor(x), as_tensor(match_indices)

    def fn(xv, mi, mismatch_value):
        ok = mi >= 0
        out = xv[jnp.clip(mi, 0, xv.shape[0] - 1)]
        out = jnp.where(ok[:, None], out, jnp.asarray(mismatch_value, xv.dtype))
        return out, ok.astype(xv.dtype)[:, None]

    return eager_call("target_assign", fn, [x, match_indices],
                      {"mismatch_value": float(mismatch_value)},
                      differentiable=False)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh=0.7, downsample_ratio=32, use_label_smooth=False,
                name=None):
    """YOLOv3 training loss for one scale (detection/yolov3_loss_op.cc).
    x: (N, A*(5+C), H, W); gt_box: (N, G, 4) xywh normalized to [0,1];
    gt_label: (N, G) int (-1 pads). Objectness uses the best-anchor
    assignment; predictions overlapping any gt above ignore_thresh are
    excluded from the no-object loss."""
    x, gt_box, gt_label = as_tensor(x), as_tensor(gt_box), as_tensor(gt_label)

    def fn(xv, gb, gl, anchors, anchor_mask, class_num, ignore_thresh,
           downsample_ratio, use_label_smooth):
        n, _, h, w = xv.shape
        a = len(anchor_mask)
        xv = xv.reshape(n, a, 5 + class_num, h, w)
        tx, ty = jax.nn.sigmoid(xv[:, :, 0]), jax.nn.sigmoid(xv[:, :, 1])
        tw, th = xv[:, :, 2], xv[:, :, 3]
        obj_logit = xv[:, :, 4]
        cls_logit = xv[:, :, 5:]  # (N, A, C, H, W)
        all_anchors = jnp.asarray(np.asarray(anchors, np.float32).reshape(-1, 2))
        sel = all_anchors[jnp.asarray(list(anchor_mask))]  # (A, 2) pixels
        img_size = downsample_ratio * jnp.asarray([w, h], jnp.float32)

        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        px = (tx + gx) / w
        py = (ty + gy) / h
        pw = jnp.exp(jnp.clip(tw, -10, 10)) * sel[None, :, 0, None, None] / img_size[0]
        ph = jnp.exp(jnp.clip(th, -10, 10)) * sel[None, :, 1, None, None] / img_size[1]

        valid = gl >= 0  # (N, G)
        # best anchor per gt (by shape IoU against ALL anchors, as reference)
        gw = gb[..., 2] * img_size[0]
        gh = gb[..., 3] * img_size[1]
        inter = (jnp.minimum(gw[..., None], all_anchors[None, None, :, 0])
                 * jnp.minimum(gh[..., None], all_anchors[None, None, :, 1]))
        union = (gw * gh)[..., None] + (all_anchors[:, 0] * all_anchors[:, 1])[None, None, :] - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # (N, G)
        # cell of each gt
        ci = jnp.clip((gb[..., 0] * w).astype(jnp.int32), 0, w - 1)
        cj = jnp.clip((gb[..., 1] * h).astype(jnp.int32), 0, h - 1)

        # build targets by scatter over (N, A, H, W)
        tobj = jnp.zeros((n, a, h, w))
        t_x = jnp.zeros((n, a, h, w)); t_y = jnp.zeros((n, a, h, w))
        t_w = jnp.zeros((n, a, h, w)); t_h = jnp.zeros((n, a, h, w))
        t_cls = jnp.zeros((n, a, class_num, h, w))
        bscale = jnp.zeros((n, a, h, w))
        bidx = jnp.arange(n)[:, None] * jnp.ones_like(best)
        # which of OUR anchors (if any) is the best match
        local = jnp.full_like(best, -1)
        for li, am in enumerate(anchor_mask):
            local = jnp.where(best == am, li, local)
        ok = valid & (local >= 0)
        la = jnp.clip(local, 0, a - 1)
        tobj = tobj.at[bidx, la, cj, ci].max(ok.astype(tobj.dtype))
        put = lambda t, v: t.at[bidx, la, cj, ci].add(jnp.where(ok, v, 0.0))
        # duplicate gts in one (anchor, cell) AVERAGE their targets: a summed
        # t_x of ~2 against a sigmoid output (and a BCE class target of 2)
        # would reward unbounded logits in crowded scenes
        cnt = jnp.maximum(
            jnp.zeros((n, a, h, w)).at[bidx, la, cj, ci].add(ok.astype(jnp.float32)),
            1.0)
        t_x = put(t_x, gb[..., 0] * w - ci) / cnt
        t_y = put(t_y, gb[..., 1] * h - cj) / cnt
        t_w = put(t_w, jnp.log(jnp.maximum(gw / jnp.maximum(sel[la][..., 0], 1e-6), 1e-6))) / cnt
        t_h = put(t_h, jnp.log(jnp.maximum(gh / jnp.maximum(sel[la][..., 1], 1e-6), 1e-6))) / cnt
        bscale = put(bscale, 2.0 - gb[..., 2] * gb[..., 3]) / cnt
        smooth = 1.0 / max(class_num, 1) if use_label_smooth else 0.0
        onehot = jax.nn.one_hot(jnp.clip(gl, 0, class_num - 1), class_num)
        onehot = onehot * (1 - smooth) + smooth / class_num
        t_cls = t_cls.at[bidx[..., None], la[..., None],
                         jnp.arange(class_num)[None, None, :], cj[..., None],
                         ci[..., None]].add(
            jnp.where(ok[..., None], onehot, 0.0)) / cnt[:, :, None]

        # ignore mask: predicted box IoU vs any gt > thresh
        pb = jnp.stack([px - pw / 2, py - ph / 2, px + pw / 2, py + ph / 2], -1)
        gbx = jnp.stack([gb[..., 0] - gb[..., 2] / 2, gb[..., 1] - gb[..., 3] / 2,
                         gb[..., 0] + gb[..., 2] / 2, gb[..., 1] + gb[..., 3] / 2], -1)
        pbf = pb.reshape(n, -1, 4)
        iou = jax.vmap(_pairwise_iou)(pbf, gbx)
        iou = jnp.where(valid[:, None, :], iou, 0.0)
        ignore = (jnp.max(iou, -1) > ignore_thresh).reshape(n, a, h, w)

        bce = lambda lg, t: jnp.maximum(lg, 0) - lg * t + jnp.log1p(jnp.exp(-jnp.abs(lg)))
        loss_xy = (bscale * ((tx - t_x) ** 2 + (ty - t_y) ** 2) * tobj).sum((1, 2, 3))
        loss_wh = (bscale * ((tw - t_w) ** 2 + (th - t_h) ** 2) * tobj).sum((1, 2, 3))
        noobj = (1.0 - tobj) * (1.0 - ignore.astype(tobj.dtype))
        loss_obj = (bce(obj_logit, tobj) * (tobj + noobj)).sum((1, 2, 3))
        loss_cls = (bce(cls_logit, t_cls) * tobj[:, :, None]).sum((1, 2, 3, 4))
        return loss_xy + loss_wh + loss_obj + loss_cls

    return eager_call(
        "yolov3_loss", fn, [x, gt_box, gt_label],
        {"anchors": tuple(float(v) for v in np.asarray(anchors).reshape(-1)),
         "anchor_mask": tuple(int(m) for m in anchor_mask),
         "class_num": int(class_num), "ignore_thresh": float(ignore_thresh),
         "downsample_ratio": int(downsample_ratio),
         "use_label_smooth": bool(use_label_smooth)},
    )
