"""Vision ops (reference python/paddle/vision/ops.py + detection ops in
paddle/fluid/operators/detection/). Host-side where shapes are dynamic (NMS),
XLA where static (roi_align, box coding, deform conv via gather)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Reference: detection/nms ops — dynamic output ⇒ host implementation."""
    b = np.asarray(as_tensor(boxes)._data, dtype=np.float64)
    s = np.asarray(as_tensor(scores)._data) if scores is not None else np.arange(len(b))[::-1]
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.maximum(0, xx2 - xx1) * np.maximum(0, yy2 - yy1)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size", box_normalized=True, axis=0):
    pb = as_tensor(prior_box)
    tb = as_tensor(target_box)
    pbv = as_tensor(prior_box_var) if prior_box_var is not None else None

    def fn(pb, tb, *rest, code_type="encode_center_size"):
        pw = pb[:, 2] - pb[:, 0]
        ph = pb[:, 3] - pb[:, 1]
        px = pb[:, 0] + pw / 2
        py = pb[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0]
            th = tb[:, 3] - tb[:, 1]
            tx = tb[:, 0] + tw / 2
            ty = tb[:, 1] + th / 2
            out = jnp.stack(
                [(tx - px) / pw, (ty - py) / ph, jnp.log(tw / pw), jnp.log(th / ph)], axis=-1
            )
        else:
            dx, dy, dw, dh = tb[..., 0], tb[..., 1], tb[..., 2], tb[..., 3]
            cx = dx * pw + px
            cy = dy * ph + py
            w = jnp.exp(dw) * pw
            h = jnp.exp(dh) * ph
            out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
        if rest:
            out = out / rest[0] if code_type == "encode_center_size" else out
        return out

    args = [pb, tb] + ([pbv] if pbv is not None else [])
    return eager_call("box_coder", fn, args, {"code_type": code_type})


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    x, boxes = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)

    def fn(feat, rois, output_size, spatial_scale, aligned):
        oh, ow = output_size
        offset = 0.5 if aligned else 0.0

        def one_roi(roi):
            x1, y1, x2, y2 = roi * spatial_scale - offset
            ys = y1 + (jnp.arange(oh) + 0.5) * (y2 - y1) / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * (x2 - x1) / ow
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            coords = jnp.stack([gy.reshape(-1), gx.reshape(-1)])

            def sample_channel(ch):
                return jax.scipy.ndimage.map_coordinates(ch, coords, order=1, mode="constant").reshape(oh, ow)

            return jax.vmap(sample_channel)(feat[0])

        return jax.vmap(one_roi)(rois)

    return eager_call(
        "roi_align", fn, [x, boxes],
        {"output_size": tuple(output_size), "spatial_scale": spatial_scale, "aligned": aligned},
    )


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    x, img_size = as_tensor(x), as_tensor(img_size)
    anchors = list(anchors)
    na = len(anchors) // 2

    def fn(x, img_size, anchors=None, class_num=0, conf_thresh=0.0, downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
        n, c, h, w = x.shape
        an = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
        na = an.shape[0]
        x = x.reshape(n, na, 5 + class_num, h, w)
        gx, gy = jnp.meshgrid(jnp.arange(w), jnp.arange(h), indexing="xy")
        bx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / w
        by = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / h
        bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / (downsample_ratio * w)
        bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / (downsample_ratio * h)
        conf = jax.nn.sigmoid(x[:, :, 4])
        probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
        img_h = img_size[:, 0].reshape(n, 1, 1, 1).astype(jnp.float32)
        img_w = img_size[:, 1].reshape(n, 1, 1, 1).astype(jnp.float32)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        mask = conf.reshape(n, -1, 1) > conf_thresh
        scores = jnp.where(mask, scores, 0.0)
        return boxes, scores

    out = eager_call(
        "yolo_box", fn, [x, img_size],
        {"anchors": tuple(anchors), "class_num": class_num, "conf_thresh": conf_thresh,
         "downsample_ratio": downsample_ratio, "clip_bbox": clip_bbox, "scale_x_y": scale_x_y},
        differentiable=False,
    )
    return out[0], out[1]


class DeformConv2D:
    def __init__(self, *a, **k):
        raise NotImplementedError("DeformConv2D: planned (gather-based Pallas kernel)")
