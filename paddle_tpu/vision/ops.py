"""Vision ops (reference python/paddle/vision/ops.py + detection ops in
paddle/fluid/operators/detection/). Host-side where shapes are dynamic (NMS),
XLA where static (roi_align, box coding, deform conv via gather)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Reference: detection/nms ops — dynamic output ⇒ host implementation."""
    b = np.asarray(as_tensor(boxes)._data, dtype=np.float64)
    s = np.asarray(as_tensor(scores)._data) if scores is not None else np.arange(len(b))[::-1]
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.maximum(0, xx2 - xx1) * np.maximum(0, yy2 - yy1)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size", box_normalized=True, axis=0):
    pb = as_tensor(prior_box)
    tb = as_tensor(target_box)
    pbv = as_tensor(prior_box_var) if prior_box_var is not None else None

    def fn(pb, tb, *rest, code_type="encode_center_size"):
        pw = pb[:, 2] - pb[:, 0]
        ph = pb[:, 3] - pb[:, 1]
        px = pb[:, 0] + pw / 2
        py = pb[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0]
            th = tb[:, 3] - tb[:, 1]
            tx = tb[:, 0] + tw / 2
            ty = tb[:, 1] + th / 2
            out = jnp.stack(
                [(tx - px) / pw, (ty - py) / ph, jnp.log(tw / pw), jnp.log(th / ph)], axis=-1
            )
        else:
            dx, dy, dw, dh = tb[..., 0], tb[..., 1], tb[..., 2], tb[..., 3]
            cx = dx * pw + px
            cy = dy * ph + py
            w = jnp.exp(dw) * pw
            h = jnp.exp(dh) * ph
            out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
        if rest:
            out = out / rest[0] if code_type == "encode_center_size" else out
        return out

    args = [pb, tb] + ([pbv] if pbv is not None else [])
    return eager_call("box_coder", fn, args, {"code_type": code_type})


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    x, boxes = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)

    def fn(feat, rois, output_size, spatial_scale, aligned):
        oh, ow = output_size
        offset = 0.5 if aligned else 0.0

        def one_roi(roi):
            x1, y1, x2, y2 = roi * spatial_scale - offset
            ys = y1 + (jnp.arange(oh) + 0.5) * (y2 - y1) / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * (x2 - x1) / ow
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            coords = jnp.stack([gy.reshape(-1), gx.reshape(-1)])

            def sample_channel(ch):
                return jax.scipy.ndimage.map_coordinates(ch, coords, order=1, mode="constant").reshape(oh, ow)

            return jax.vmap(sample_channel)(feat[0])

        return jax.vmap(one_roi)(rois)

    return eager_call(
        "roi_align", fn, [x, boxes],
        {"output_size": tuple(output_size), "spatial_scale": spatial_scale, "aligned": aligned},
    )


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    x, img_size = as_tensor(x), as_tensor(img_size)
    anchors = list(anchors)
    na = len(anchors) // 2

    def fn(x, img_size, anchors=None, class_num=0, conf_thresh=0.0, downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
        n, c, h, w = x.shape
        an = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
        na = an.shape[0]
        x = x.reshape(n, na, 5 + class_num, h, w)
        gx, gy = jnp.meshgrid(jnp.arange(w), jnp.arange(h), indexing="xy")
        bx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / w
        by = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / h
        bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / (downsample_ratio * w)
        bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / (downsample_ratio * h)
        conf = jax.nn.sigmoid(x[:, :, 4])
        probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
        img_h = img_size[:, 0].reshape(n, 1, 1, 1).astype(jnp.float32)
        img_w = img_size[:, 1].reshape(n, 1, 1, 1).astype(jnp.float32)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        mask = conf.reshape(n, -1, 1) > conf_thresh
        scores = jnp.where(mask, scores, 0.0)
        return boxes, scores

    out = eager_call(
        "yolo_box", fn, [x, img_size],
        {"anchors": tuple(anchors), "class_num": class_num, "conf_thresh": conf_thresh,
         "downsample_ratio": downsample_ratio, "clip_bbox": clip_bbox, "scale_x_y": scale_x_y},
        differentiable=False,
    )
    return out[0], out[1]


def _roi_batch_ids(boxes_num, n_rois):
    """Per-RoI image index from the boxes_num split (reference RoisNum)."""
    if boxes_num is None:
        return np.zeros(n_rois, np.int32)
    counts = np.asarray(as_tensor(boxes_num)._data).reshape(-1).astype(np.int64)
    return np.repeat(np.arange(len(counts)), counts).astype(np.int32)[:n_rois]


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool each RoI to a fixed grid (reference detection/roi_pool_op):
    every output cell is the max over a dense sample grid covering its bin."""
    xt, bt = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = int(output_size[0]), int(output_size[1])
    batch_ids = _roi_batch_ids(boxes_num, int(bt.shape[0]))
    from ..core.tensor import Tensor as _T

    bid_t = _T(jnp.asarray(batch_ids), stop_gradient=True)

    S = 4  # samples per bin edge: max over S*S points approximates bin max

    def fn(feat, rois, bids, oh=0, ow=0, scale=1.0):
        N, C, H, W = feat.shape

        def one_roi(roi, bid):
            x1, y1, x2, y2 = roi * scale
            # S dense samples inside each of the oh/ow bins
            ys = y1 + (y2 - y1) * (jnp.arange(oh * S) + 0.5) / (oh * S)
            xs = x1 + (x2 - x1) * (jnp.arange(ow * S) + 0.5) / (ow * S)
            yi = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, W - 1)
            v = feat[bid][:, yi][:, :, xi]  # (C, oh*S, ow*S)
            return v.reshape(C, oh, S, ow, S).max(axis=(2, 4))

        return jax.vmap(one_roi)(rois, bids)

    return eager_call(
        "roi_pool", fn, [xt, bt, bid_t],
        attrs={"oh": oh, "ow": ow, "scale": float(spatial_scale)},
    )


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI average pool (reference detection/psroi_pool_op)."""
    xt, bt = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = int(output_size[0]), int(output_size[1])
    batch_ids = _roi_batch_ids(boxes_num, int(bt.shape[0]))
    from ..core.tensor import Tensor as _T

    bid_t = _T(jnp.asarray(batch_ids), stop_gradient=True)
    S = 4

    def fn(feat, rois, bids, oh=0, ow=0, scale=1.0):
        N, C, H, W = feat.shape
        out_c = C // (oh * ow)

        def one_roi(roi, bid):
            x1, y1, x2, y2 = roi * scale
            ys = y1 + (y2 - y1) * (jnp.arange(oh * S) + 0.5) / (oh * S)
            xs = x1 + (x2 - x1) * (jnp.arange(ow * S) + 0.5) / (ow * S)
            yi = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, W - 1)
            f = feat[bid][:, yi][:, :, xi]  # (C, oh*S, ow*S)
            f = f.reshape(out_c, oh, ow, oh, S, ow, S)

            # position-sensitive: channel block (i,j) is averaged over bin (i,j)
            def cell(i, j):
                return f[:, i, j, i, :, j, :].mean(axis=(-1, -2))  # (out_c,)

            grid = jax.vmap(lambda i: jax.vmap(lambda j: cell(i, j))(jnp.arange(ow)))(
                jnp.arange(oh)
            )  # (oh, ow, out_c)
            return jnp.moveaxis(grid, -1, 0)

        return jax.vmap(one_roi)(rois, bids)

    return eager_call(
        "psroi_pool", fn, [xt, bt, bid_t],
        attrs={"oh": oh, "ow": ow, "scale": float(spatial_scale)},
    )


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference detection/prior_box_op)."""
    it, imt = as_tensor(input), as_tensor(image)
    ratios = list(aspect_ratios)
    if flip:
        ratios = ratios + [1.0 / r for r in ratios if r != 1.0]

    H, W = int(it.shape[-2]), int(it.shape[-1])
    IH, IW = int(imt.shape[-2]), int(imt.shape[-1])
    step_h = steps[1] or IH / H
    step_w = steps[0] or IW / W

    sizes = []
    for k, ms in enumerate(min_sizes):
        for r in ratios:
            sizes.append((ms * (r ** 0.5), ms / (r ** 0.5)))
        if max_sizes:
            mx = max_sizes[k]
            sizes.append(((ms * mx) ** 0.5, (ms * mx) ** 0.5))
    sizes = np.asarray(sizes, np.float32)  # (P, 2) as (w, h)

    cy = (np.arange(H) + offset) * step_h
    cx = (np.arange(W) + offset) * step_w
    gx, gy = np.meshgrid(cx, cy)
    centers = np.stack([gx, gy], -1)[..., None, :]  # (H, W, 1, 2)
    wh = sizes[None, None]  # (1, 1, P, 2)
    mins = (centers - wh / 2) / np.asarray([IW, IH], np.float32)
    maxs = (centers + wh / 2) / np.asarray([IW, IH], np.float32)
    boxes = np.concatenate([mins, maxs], -1).astype(np.float32)  # (H, W, P, 4)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), boxes.shape).copy()
    return Tensor(jnp.asarray(boxes), stop_gradient=True), Tensor(jnp.asarray(var), stop_gradient=True)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None, name=None):
    """Assign RoIs to FPN levels (reference detection/distribute_fpn_proposals_op).
    Host-side (dynamic shapes), like the reference's CPU kernel."""
    rois = np.asarray(as_tensor(fpn_rois)._data)
    w = rois[:, 2] - rois[:, 0] + (1 if pixel_offset else 0)
    h = rois[:, 3] - rois[:, 1] + (1 if pixel_offset else 0)
    scale = np.sqrt(np.maximum(w * h, 1e-6))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, nums, order = [], [], []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        outs.append(Tensor(jnp.asarray(rois[idx]), stop_gradient=True))
        nums.append(Tensor(jnp.asarray(np.asarray([len(idx)], np.int32)), stop_gradient=True))
        order.append(idx)
    restore = np.argsort(np.concatenate(order)) if order else np.zeros(0, np.int64)
    return outs, Tensor(jnp.asarray(restore.astype(np.int32)), stop_gradient=True), nums


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 (reference operators/deformable_conv_op.cu):
    bilinear-sample the input at offset-shifted taps, then contract — a
    gather + matmul that XLA fuses; the MXU does the contraction."""
    xt, ot, wt = as_tensor(x), as_tensor(offset), as_tensor(weight)
    args = [xt, ot, wt]
    if mask is not None:
        args.append(as_tensor(mask))
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    kh, kw = int(wt.shape[-2]), int(wt.shape[-1])

    def fn(feat, off, w, *rest, sh=1, sw=1, ph=0, pw=0, dh=1, dw=1, kh=3, kw=3, groups=1):
        msk = rest[0] if rest else None
        N, C, H, W = feat.shape
        OC = w.shape[0]
        OH = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        OW = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        feat_p = jnp.pad(feat, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        Hp, Wp = H + 2 * ph, W + 2 * pw
        # offsets: (N, dg*kh*kw*2, OH, OW) interleaved (dy, dx) PER TAP —
        # the reference/mmcv layout (deformable_conv_op channel order)
        dg = off.shape[1] // (2 * kh * kw)
        off = off.reshape(N, dg, kh * kw, 2, OH, OW)
        cpg = C // dg  # channels per deformable group

        def sample(feat_n, off_n, msk_n):
            def group_sample(feat_g, off_g, msk_g):
                # feat_g (cpg, Hp, Wp); off_g (kh*kw, 2, OH, OW); msk_g
                # (kh*kw, OH, OW) or () sentinel
                dy = off_g[:, 0].reshape(kh, kw, OH, OW)
                dx = off_g[:, 1].reshape(kh, kw, OH, OW)
                # tap positions per (kh, kw, OH, OW)
                yy = (jnp.arange(OH) * sh)[None, None, :, None] + (jnp.arange(kh) * dh)[:, None, None, None] + dy
                xx = (jnp.arange(OW) * sw)[None, None, None, :] + (jnp.arange(kw) * dw)[None, :, None, None] + dx
                y0 = jnp.floor(yy)
                x0 = jnp.floor(xx)
                wy = yy - y0
                wx = xx - x0

                def gat(yi, xi):
                    inb = (yi >= 0) & (yi < Hp) & (xi >= 0) & (xi < Wp)
                    v = feat_g[:, jnp.clip(yi, 0, Hp - 1), jnp.clip(xi, 0, Wp - 1)]
                    return jnp.where(inb[None], v, 0.0)

                y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
                v = (gat(y0i, x0i) * (1 - wy) * (1 - wx) + gat(y0i, x0i + 1) * (1 - wy) * wx
                     + gat(y0i + 1, x0i) * wy * (1 - wx) + gat(y0i + 1, x0i + 1) * wy * wx)
                if msk_g.ndim:
                    v = v * msk_g.reshape(kh, kw, OH, OW)[None]
                return v  # (cpg, kh, kw, OH, OW)

            feat_grp = feat_n.reshape(dg, cpg, Hp, Wp)
            msk_grp = (
                msk_n.reshape(dg, kh * kw, OH, OW)
                if msk_n.ndim else jnp.broadcast_to(msk_n, (dg,))
            )
            v = jax.vmap(group_sample)(feat_grp, off_n, msk_grp)
            return v.reshape(C, kh, kw, OH, OW)

        if msk is not None:
            cols = jax.vmap(sample)(feat_p, off, msk)
        else:
            zero = jnp.zeros(())  # 0-d sentinel: "no mask"
            cols = jax.vmap(lambda f, o: sample(f, o, zero))(feat_p, off)
        cols = cols.reshape(N, C, kh, kw, OH, OW)
        G = groups
        if G == 1:
            return jnp.einsum("nckhij,ockh->noij", cols, w)
        # grouped conv: contract each channel group with its weight block
        cols_g = cols.reshape(N, G, C // G, kh, kw, OH, OW)
        w_g = w.reshape(G, w.shape[0] // G, C // G, kh, kw)
        out = jnp.einsum("ngckhij,gockh->ngoij", cols_g, w_g)
        return out.reshape(N, w.shape[0], OH, OW)

    out = eager_call(
        "deform_conv2d", fn, args,
        attrs={"sh": stride[0], "sw": stride[1], "ph": padding[0], "pw": padding[1],
               "dh": dilation[0], "dw": dilation[1], "kh": kh, "kw": kw,
               "groups": int(groups)},
    )
    if bias is not None:
        out = out + as_tensor(bias).reshape([1, -1, 1, 1])
    return out


def _make_deform_conv_layer():
    from ..nn.layer.layers import Layer

    class DeformConv2D(Layer):
        """Layer over deform_conv2d (reference vision/ops.py DeformConv2D);
        parameters register through the Layer machinery so optimizers and
        state_dict see them."""

        def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                     padding=0, dilation=1, deformable_groups=1, groups=1,
                     weight_attr=None, bias_attr=None):
            super().__init__()
            k = kernel_size if isinstance(kernel_size, (tuple, list)) else (kernel_size, kernel_size)
            self.weight = self.create_parameter(
                [out_channels, in_channels // groups, k[0], k[1]], attr=weight_attr
            )
            self.bias = (
                None if bias_attr is False
                else self.create_parameter([out_channels], attr=bias_attr, is_bias=True)
            )
            self.stride, self.padding, self.dilation = stride, padding, dilation
            self.deformable_groups, self.groups = deformable_groups, groups

        def forward(self, x, offset, mask=None):
            return deform_conv2d(
                x, offset, self.weight, self.bias, self.stride, self.padding,
                self.dilation, self.deformable_groups, self.groups, mask,
            )

    return DeformConv2D


DeformConv2D = _make_deform_conv_layer()
