"""Vision datasets (reference python/paddle/vision/datasets/).

Zero-egress environment: MNIST/CIFAR load from local files when present
(``PADDLE_TPU_DATA_HOME``), else generate a deterministic synthetic set with
the same shapes/label space so training pipelines run end-to-end.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset


from ...io import data_home


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        images, labels = self._load()
        self.images, self.labels = images, labels

    def _load(self):
        base = os.path.join(data_home(), "mnist")
        prefix = "train" if self.mode == "train" else "t10k"
        img_f = os.path.join(base, f"{prefix}-images-idx3-ubyte.gz")
        lab_f = os.path.join(base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(img_f) and os.path.exists(lab_f):
            with gzip.open(img_f, "rb") as f:
                magic, n, h, w = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, h, w)
            with gzip.open(lab_f, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), dtype=np.uint8)
            return images, labels.astype(np.int64)
        # synthetic fallback (deterministic)
        rng = np.random.RandomState(0 if self.mode == "train" else 1)
        n = 60000 if self.mode == "train" else 10000
        n = min(n, int(os.environ.get("PADDLE_TPU_SYNTH_N", "4096")))
        labels = rng.randint(0, 10, n).astype(np.int64)
        images = np.zeros((n, 28, 28), np.uint8)
        for i, l in enumerate(labels):  # class-dependent blobs → learnable
            images[i, (l * 2 + 2) : (l * 2 + 6), 4:24] = 200
            images[i] += rng.randint(0, 40, (28, 28)).astype(np.uint8)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[..., None]  # HWC
        label = np.asarray(self.labels[idx], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = min(50000 if mode == "train" else 10000, int(os.environ.get("PADDLE_TPU_SYNTH_N", "4096")))
        self.labels = rng.randint(0, 10, n).astype(np.int64)
        self.images = rng.randint(0, 255, (n, 32, 32, 3)).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], dtype=np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        super().__init__(data_file, mode, transform, download, backend)
        rng = np.random.RandomState(2)
        self.labels = rng.randint(0, 100, len(self.labels)).astype(np.int64)


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.samples = []
        self.transform = transform
        exts = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        if os.path.isdir(root):
            for dirpath, _, files in sorted(os.walk(root)):
                for fn in sorted(files):
                    if fn.lower().endswith(exts):
                        self.samples.append(os.path.join(dirpath, fn))

    def __getitem__(self, idx):
        path = self.samples[idx]
        if path.endswith(".npy"):
            img = np.load(path)
        else:
            raise RuntimeError("image decoding unavailable (no PIL in env); use .npy")
        if self.transform:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class DatasetFolder(ImageFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        super().__init__(root, loader, extensions, transform, is_valid_file)
        self.classes = sorted({os.path.basename(os.path.dirname(p)) for p in self.samples})
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}

    def __getitem__(self, idx):
        (img,) = super().__getitem__(idx)
        label = self.class_to_idx[os.path.basename(os.path.dirname(self.samples[idx]))]
        return img, np.asarray(label, np.int64)
