"""Retry-with-backoff, shared by elastic heartbeats, TCPStore ops and
checkpoint I/O.

Reference parity: the elastic manager retries etcd operations and the fleet
filesystem layer retries HDFS ops; here one helper covers every transient-I/O
seam so a single flaky store round-trip doesn't get promoted to a dead-worker
verdict or a lost checkpoint. Each performed retry bumps the profiler counter
``retry_attempts``.
"""
from __future__ import annotations

import time
from typing import Callable, Tuple, Type

# Transient-looking errors. InjectedFault subclasses OSError, so injected
# store/checkpoint failures exercise exactly this path.
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    OSError, ConnectionError, TimeoutError,
)


def _counter(name: str, n: int = 1):
    try:
        from .. import profiler

        profiler.counter_inc(name, n)
    except Exception:
        pass


def retry_call(
    fn: Callable,
    *args,
    retries: int = 3,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    exceptions: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE,
    on_retry: Callable = None,
    sleep: Callable = time.sleep,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``; on a retryable error, back off
    exponentially (``base_delay * 2**attempt``, capped at ``max_delay``) and
    try again up to ``retries`` more times. The final failure re-raises."""
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except exceptions as e:
            if attempt >= retries:
                raise
            delay = min(max_delay, base_delay * (2 ** attempt))
            attempt += 1
            _counter("retry_attempts")
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)


def retrying(**retry_kwargs):
    """Decorator form of :func:`retry_call`."""

    def deco(fn):
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, **retry_kwargs, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "retrying")
        wrapped.__doc__ = fn.__doc__
        return wrapped

    return deco


__all__ = ["retry_call", "retrying", "DEFAULT_RETRYABLE"]
