"""Training stability sentinel — anomaly detection, batch quarantine,
sample-exact auto-rollback.

The most common production training failure is not a crash but a *finite*
divergence: a loss spike or gradient explosion silently poisons the weights
and the run burns chips for hours before a human notices. The NaN/Inf guard
(PR 2) only trips on non-finite values — and the async runtime's deferred
guard explicitly allows one poisoned optimizer step to commit before the
trip. This module closes the loop over the recovery machinery PR 8 built
(crash-safe/coordinated checkpoints, sample-exact ``DataLoader`` state,
``program_rng`` capture):

* **Signals**, computed device-side as ONE fused scalar pack riding the
  step's own flush (no extra host sync points; the readback is a single
  4-float vector per step, attributed through ``lazy.timed_block``):
  ``loss``, ``grad_norm`` (global L2 over all grads), ``nonfinite`` (rate of
  non-finite grad/loss elements), ``upd_ratio`` (first-order update/param
  norm ratio, ``lr·‖g‖/‖p‖`` — exact for SGD, a proxy for adaptive rules).
* **Robust statistics**: per-signal median/MAD over a bounded window with a
  warmup gate; a sample is anomalous when its ONE-SIDED robust z-score
  exceeds ``zmax`` — only upward deviations trip (a falling loss or a
  shrinking grad norm is convergence, not instability). Non-finite signals
  are anomalous unconditionally (no warmup). Anomalous samples are never
  folded into the statistics.
* **Policy ladder** on a trip: **(1) skip** — discard the step's update
  (only possible when detection is synchronous: eager mode or
  ``FLAGS_lazy_async=0``, where the verdict lands BEFORE the optimizer
  applies the update) and quarantine the batch; **(2) rollback** — restore
  model + optimizer + LR-scheduler + RNG + DataLoader state from the newest
  verified anchor checkpoint STRICTLY OLDER than the poisoned step
  (``resume(max_step=...)``) and let the caller replay with the quarantined
  batch skipped at the index level; **(3) halt** — structured
  :class:`StabilityError` + flight-recorder post-mortem naming the tripping
  signal with the full signal history.

  A trip that surfaces ≤1 step late (lazy-async deferral, or the engine's
  donated fused step where the update has committed by the time the loss is
  readable) escalates straight to rollback — skip would leave the poisoned
  update in the weights.

Anchor protocol (with :class:`~paddle_tpu.distributed.checkpoint.AutoCheckpoint`
or ``CoordinatedCheckpoint``): the sentinel pins (``protect``) the newest
anchor whose step has been JUDGED CLEAN, so checkpoint GC can never collect
the one checkpoint a rollback needs — an anchor saved in the detection
window may already contain the poisoned update and is skipped via
``max_step`` and invalidated after a rollback.

Zero-cost disabled path: nothing here is imported by the training loop until
a sentinel is constructed; ``hapi.Model.fit`` and the engine pay one flag /
attribute probe per step, the ``core/lazy.py`` drain tap is a single
``is not None`` check per flush, and no threads are created (the tier-1
inert tripwire pins all three).
"""
from __future__ import annotations

import collections
import json
import math
import os
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "SIGNALS", "StabilityError", "StabilityVerdict", "QuarantineLog",
    "StabilitySentinel", "VerdictBarrier", "last_signals",
]

SIGNALS = ("loss", "grad_norm", "nonfinite", "upd_ratio")
# robust z denominator: 1.4826·MAD (normal-consistent) + a 2%-of-median
# relative floor so a converged, nearly-constant signal doesn't trip on
# numerical wobble while a 100x spike still scores in the thousands
_MAD_SCALE = 1.4826
_REL_FLOOR = 0.02


class StabilityError(RuntimeError):
    """The sentinel exhausted its policy ladder (or had no rollback anchor).
    Carries the tripping signal, its value/z-score and the recent history."""

    def __init__(self, message: str, verdict: "StabilityVerdict" = None,
                 history: Optional[list] = None):
        super().__init__(message)
        self.verdict = verdict
        self.history = list(history or ())


class StabilityVerdict:
    """One anomaly decision. ``action`` is ``"skip"``/``"rollback"``/
    ``"halt"``; ``late`` means the flagged step's update had already
    committed when the signal became readable (deferred detection);
    ``origin_rank`` names the rank whose detector tripped when the verdict
    arrived through the cross-rank :class:`VerdictBarrier` (None = local)."""

    __slots__ = ("action", "step", "pos", "signal", "value", "zscore",
                 "late", "signals", "origin_rank")

    def __init__(self, action, step, pos, signal, value, zscore, late, signals,
                 origin_rank=None):
        self.action = action
        self.step = int(step)
        self.pos = pos
        self.signal = signal
        self.value = float(value)
        self.zscore = float(zscore)
        self.late = bool(late)
        self.signals = dict(signals)
        self.origin_rank = origin_rank

    def to_dict(self) -> dict:
        return {
            "action": self.action, "step": self.step, "pos": self.pos,
            "signal": self.signal, "value": self.value, "zscore": self.zscore,
            "late": self.late, "signals": self.signals,
            "origin_rank": self.origin_rank,
        }

    def __repr__(self):
        return (f"StabilityVerdict({self.action}, step={self.step}, "
                f"signal={self.signal}, value={self.value:.4g}, "
                f"z={self.zscore:.1f}, late={self.late})")


class QuarantineLog:
    """Bounded in-memory record (plus optional JSONL file) of quarantined
    batches: step, loader position, sample indices and the signal values
    that condemned them. The training loop consults :meth:`is_quarantined`
    during replay so a rolled-back run skips the bad batch window at the
    index level."""

    def __init__(self, path: Optional[str] = None, capacity: int = 1024):
        self._path = path
        self._entries: "collections.deque" = collections.deque(maxlen=capacity)
        self._steps: set = set()
        self._positions: set = set()

    def add(self, step: int, pos=None, sample_indices=None,
            signals: Optional[dict] = None, action: str = "skip") -> dict:
        if len(self._entries) == self._entries.maxlen:
            # keep the membership index in lockstep with the bounded ring:
            # drop the evicted record's keys unless a surviving entry still
            # claims them (rare; the scan is per-eviction, not per-lookup)
            old = self._entries[0]
            if not any(e["step"] == old["step"] for e in list(self._entries)[1:]):
                self._steps.discard(old["step"])
            if old["pos"] is not None and not any(
                e["pos"] == old["pos"] for e in list(self._entries)[1:]
            ):
                self._positions.discard(tuple(old["pos"]))
        rec = {
            "step": int(step),
            "pos": list(pos) if pos is not None else None,
            "sample_indices": (
                [int(i) for i in sample_indices]
                if sample_indices is not None else None
            ),
            "signals": dict(signals or {}),
            "action": action,
        }
        self._entries.append(rec)
        self._steps.add(int(step))
        if pos is not None:
            self._positions.add(tuple(pos))
        if self._path:
            try:
                with open(self._path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError:
                pass  # the quarantine decision must not die with its log line
        return rec

    def is_quarantined(self, pos=None, step: Optional[int] = None) -> bool:
        if pos is not None and tuple(pos) in self._positions:
            return True
        return step is not None and int(step) in self._steps

    def entries(self) -> List[dict]:
        return list(self._entries)

    def __len__(self):
        return len(self._entries)


_SEVERITY = {"skip": 1, "rollback": 2, "halt": 3}


class VerdictBarrier:
    """Store-mediated cross-rank verdict agreement (the PR 13 follow-up to
    deterministic world-wide trips).

    With all-reduced gradients a spike trips every rank's detector in the
    same step, so coordinated rollback falls out of determinism. A
    rank-LOCAL anomaly — host memory corrupting one rank's batch, a bad
    DataLoader worker — trips ONE detector, and without coordination that
    rank rolls back alone while its peers march on: the world diverges.
    This barrier reuses :class:`~paddle_tpu.distributed.coord.CommitBarrier`
    rounds so every rank leaves each step boundary with the SAME verdict:

    1. each rank publishes its local verdict (if any) for the round, then
       acks the round's two-phase barrier — after rank 0's commit record no
       rank can still be writing;
    2. every rank reads every peer's verdict and adopts the most severe one
       posted anywhere (ties broken by z-score, then rank);
    3. ranks whose own detector stayed silent fold the adopted verdict into
       their sentinel (:meth:`StabilitySentinel.adopt`): same quarantine
       entry, same ladder rung — the subsequent ``rollback`` then resolves
       one anchor world-wide through the existing store-mediated resume
       agreement.

    ``exchange`` must be called once per step attempt on EVERY rank, in
    lockstep (rounds are monotonic and never reused, so no ``reset`` litter
    race exists). A barrier timeout degrades to the local verdict — a dead
    peer is the watchdog's jurisdiction, and stalling recovery on it would
    hang the healthy ranks.
    """

    def __init__(self, store, world_size: int, rank: int, sentinel=None,
                 prefix: str = "stability", timeout_s: float = 60.0):
        from ..distributed.coord import CommitBarrier

        self.store = store
        self.world_size = int(world_size)
        self.rank = int(rank)
        self.prefix = prefix
        self.timeout_s = float(timeout_s)
        self._bar = CommitBarrier(store, world_size, rank,
                                  prefix=f"{prefix}/bar")
        self._sentinel = weakref.ref(sentinel) if sentinel is not None else None
        self._round = 0

    def exchange(self, verdict: Optional[StabilityVerdict]
                 ) -> Optional[StabilityVerdict]:
        """One coordination round: publish this rank's ``verdict`` (or
        None), synchronize, return the world-agreed verdict (or None)."""
        from .. import profiler as _prof

        tag = self._round
        self._round += 1
        if verdict is not None:
            self.store.set(
                f"{self.prefix}/v/{tag}/r{self.rank}",
                json.dumps(verdict.to_dict()),
            )
        try:
            self._bar.ack(tag)
            self._bar.commit(tag, self.timeout_s)
        except Exception:
            _prof.counter_inc("stability_barrier_timeouts")
            return verdict
        # bounded store footprint: round N's commit proves every rank left
        # round N-1 long ago, so its barrier keys and this rank's verdict
        # key can go — one live round instead of one key pair per step
        if tag:
            self._bar.reset(tag - 1)
            self.store.delete_key(f"{self.prefix}/v/{tag - 1}/r{self.rank}")
        # most severe verdict posted anywhere, ties broken by z-score then
        # LOWEST rank — the full key is identical on every rank, so equal
        # (severity, z) verdicts (e.g. two rank-local nonfinite trips, both
        # z=inf) still resolve to ONE world-wide choice
        cands = [(self.rank, verdict)] if verdict is not None else []
        for r in range(self.world_size):
            if r == self.rank:
                continue
            raw = self.store.get(f"{self.prefix}/v/{tag}/r{r}")
            if not raw:
                continue
            d = json.loads(raw)
            cands.append((r, StabilityVerdict(
                d["action"], d["step"],
                tuple(d["pos"]) if d.get("pos") else None,
                d["signal"], d["value"], d["zscore"], True,
                d.get("signals") or {}, origin_rank=r,
            )))
        if not cands:
            return None
        _, best = max(
            cands,
            key=lambda rv: (_SEVERITY.get(rv[1].action, 0), rv[1].zscore, -rv[0]),
        )
        if best.origin_rank is not None and verdict is None:
            # a remote detector tripped and the LOCAL one stayed silent:
            # fold the verdict into the local sentinel so quarantine +
            # ladder state stay world-consistent. A rank whose own verdict
            # was merely OUTRANKED already consumed its rung (and
            # quarantined the same world-shared batch) in _judge — adopting
            # on top would double-count the incident budget and desync the
            # ladders across ranks.
            s = self._sentinel() if self._sentinel is not None else None
            if s is not None:
                s.adopt(best)
        return best


# -- device-side signal pack --------------------------------------------------
# One fn per (n_grads, n_params, has_loss, has_lr) arity so the lazy flush
# signature (keyed explicitly) and jax.jit caches stay stable across steps.
_packers: Dict[tuple, Callable] = {}
_packers_jit: Dict[tuple, Callable] = {}


def _packer(ng: int, npar: int, has_loss: bool, has_lr: bool) -> Callable:
    fn = _packers.get((ng, npar, has_loss, has_lr))
    if fn is not None:
        return fn
    import jax.numpy as jnp

    def pack(*args, _ng=ng, _np=npar, _hl=has_loss, _hlr=has_lr):
        i = 0
        loss = jnp.mean(args[i].astype(jnp.float32)) if _hl else jnp.float32(0)
        i += 1 if _hl else 0
        lr = args[i].astype(jnp.float32) if _hlr else jnp.float32(0)
        i += 1 if _hlr else 0
        grads = args[i:i + _ng]
        params = args[i + _ng:i + _ng + _np]
        if grads:
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
            gnorm = jnp.sqrt(sq)
            bad = sum(jnp.sum(~jnp.isfinite(g)) for g in grads)
            total = float(sum(int(np.prod(g.shape)) if g.shape else 1 for g in grads))
            nonfinite = bad.astype(jnp.float32) / jnp.float32(total)
        else:
            gnorm = jnp.float32(0)
            nonfinite = jnp.float32(0)
        if _hl:
            nonfinite = jnp.maximum(
                nonfinite, 1.0 - jnp.isfinite(loss).astype(jnp.float32)
            )
        if params and _hlr and grads:
            psq = sum(jnp.sum(jnp.square(p.astype(jnp.float32))) for p in params)
            upd = lr * gnorm / (jnp.sqrt(psq) + 1e-12)
        else:
            upd = jnp.float32(0)
        return jnp.stack([loss, gnorm, nonfinite, upd])

    _packers[(ng, npar, has_loss, has_lr)] = pack
    return pack


# -- active-sentinel registry (the core/lazy.py drain tap) --------------------
_active: "weakref.WeakSet" = weakref.WeakSet()
_last_signals: Dict[str, float] = {}  # most recent judged signals (any sentinel)


def last_signals() -> Dict[str, float]:
    """The most recently judged signal values across all sentinels (plus
    ``loss_ema``) — folded into every BENCH JSON line."""
    return dict(_last_signals)


def _tap_all() -> None:
    """core/lazy.py calls this at the deferred-guard drain points while at
    least one sentinel is active: a NON-BLOCKING readiness sweep so verdicts
    for already-finished steps are staged without waiting for the next
    ``observe``. Must never raise and never force a flush."""
    for s in list(_active):
        try:
            s._tap()
        except Exception:
            pass


def _register(s: "StabilitySentinel") -> None:
    from ..core import lazy as lazy_mod
    from ..profiler import flight as _flight

    _active.add(s)
    lazy_mod._stability_tap = _tap_all
    _flight.add_context_provider("stability", _flight_context)


def _unregister(s: "StabilitySentinel") -> None:
    _active.discard(s)
    if not _active:
        from ..core import lazy as lazy_mod
        from ..profiler import flight as _flight

        lazy_mod._stability_tap = None
        _flight.remove_context_provider("stability")


def _flight_context() -> dict:
    out = []
    for s in list(_active):
        out.append(s._context())
    return {"sentinels": out, "last_signals": dict(_last_signals)}


class _SignalStats:
    """Median/MAD over a bounded window, with warmup. Anomalous samples are
    reported but NOT folded in (a quarantined spike must not shift the
    baseline it was judged against)."""

    __slots__ = ("window", "warmup", "zmax", "_ring")

    def __init__(self, window: int, warmup: int, zmax: float):
        self.window = int(window)
        # warmup > window would keep the detector in warmup FOREVER (the
        # ring can never outgrow its maxlen) — clamp so the configuration
        # degrades to "full-window warmup" instead of a silently dead check
        self.warmup = min(int(warmup), self.window)
        self.zmax = float(zmax)
        self._ring: "collections.deque" = collections.deque(maxlen=self.window)

    def score(self, x: float) -> Tuple[bool, float]:
        """(anomalous, robust_z) — does NOT fold ``x`` in. One-sided: only
        UPWARD deviations count; a loss/grad-norm falling faster than its
        history is convergence, not instability."""
        if not math.isfinite(x):
            return True, float("inf")
        if len(self._ring) < self.warmup:
            return False, 0.0
        ring = np.asarray(self._ring, np.float64)
        med = float(np.median(ring))
        mad = float(np.median(np.abs(ring - med)))
        denom = _MAD_SCALE * mad + _REL_FLOOR * abs(med) + 1e-9
        z = (x - med) / denom
        return z > self.zmax, z

    def fold(self, x: float) -> None:
        if math.isfinite(x):
            self._ring.append(x)

    def judge(self, x: float) -> Tuple[bool, float]:
        """(anomalous, robust_z). Folds ``x`` in iff it is not anomalous.
        The sentinel itself uses score()/fold() separately so that NO
        signal of an anomalous step — not even the ones below threshold —
        contaminates the baselines."""
        bad, z = self.score(x)
        if not bad:
            self.fold(x)
        return bad, z


class StabilitySentinel:
    """Watches per-step training signals and escalates anomalies through the
    skip → rollback → halt policy ladder. See the module docstring for the
    protocol; :meth:`observe` is the one per-step entry point.

    Threading: the sentinel itself creates no threads; ``_tap`` runs on the
    training thread (inside the lazy drain), but a second training thread
    sharing a sentinel is legal, so the pending queue / verdict stash /
    history are lock-guarded.
    """

    def __init__(
        self,
        window: Optional[int] = None,
        warmup: Optional[int] = None,
        zmax: Optional[float] = None,
        max_skips: Optional[int] = None,
        max_rollbacks: Optional[int] = None,
        cooldown: Optional[int] = None,
        anchor=None,
        state: Optional[dict] = None,
        state_fn: Optional[Callable[[], dict]] = None,
        post_restore: Optional[Callable[[dict], None]] = None,
        quarantine: Optional[QuarantineLog] = None,
        name: str = "sentinel",
    ):
        from ..framework import flags as _flags

        def _f(v, flag, cast):
            return cast(_flags.flag(flag)) if v is None else cast(v)

        self.name = name
        self.window = _f(window, "FLAGS_stability_window", int)
        self.warmup = _f(warmup, "FLAGS_stability_warmup", int)
        self.zmax = _f(zmax, "FLAGS_stability_zmax", float)
        self.max_skips = _f(max_skips, "FLAGS_stability_max_skips", int)
        self.max_rollbacks = _f(max_rollbacks, "FLAGS_stability_max_rollbacks", int)
        self.cooldown = _f(cooldown, "FLAGS_stability_cooldown", int)
        self.anchor = anchor
        self._state = state
        self._state_fn = state_fn
        self._post_restore = post_restore
        qdir = _flags.flag("FLAGS_stability_quarantine_dir", "") or ""
        qpath = None
        if quarantine is None and qdir:
            os.makedirs(qdir, exist_ok=True)
            qpath = os.path.join(qdir, f"quarantine_{os.getpid()}_{name}.jsonl")
        self.quarantine = quarantine if quarantine is not None else QuarantineLog(qpath)
        self._lock = threading.Lock()
        # deferred signal handles awaiting readback, oldest first; judged at
        # the next observe (≤1 step late) or opportunistically by the drain
        # tap when already ready
        self._pending: List[dict] = []  # guarded_by: _lock
        self._stash: List[StabilityVerdict] = []  # guarded_by: _lock
        self._history: "collections.deque" = collections.deque(maxlen=128)  # guarded_by: _lock
        # stats per statistical signal; `nonfinite` is judged absolutely
        self._stats = {
            k: _SignalStats(self.window, self.warmup, self.zmax)
            for k in ("loss", "grad_norm", "upd_ratio")
        }
        self._loss_ema: Optional[float] = None
        # incident ladder state (training-thread only)
        self._skips_used = 0
        self._rollbacks_used = 0
        self._clean_streak = 0
        # anchor-pin protocol
        self._anchor_steps: List[int] = []
        self._pinned: Optional[int] = None
        self._last_clean_step = -1
        self._next_note: Optional[tuple] = None  # (pos, indices, indices_fn)
        self._closed = False
        _register(self)

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Unregister the drain tap / flight provider and release any pinned
        anchor. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self.anchor is not None and self._pinned is not None:
            try:
                self.anchor.release(self._pinned)
            except Exception:
                pass
        _unregister(self)

    @classmethod
    def from_flags(cls, anchor=None, **kw) -> "StabilitySentinel":
        """Build from the ``FLAGS_stability_*`` registry; an anchor dir set
        via ``FLAGS_stability_ckpt_dir`` provides the rollback checkpoint."""
        from ..framework import flags as _flags

        if anchor is None:
            d = _flags.flag("FLAGS_stability_ckpt_dir", "") or ""
            if d:
                from ..distributed.checkpoint import AutoCheckpoint

                anchor = AutoCheckpoint(
                    d,
                    interval_steps=int(_flags.flag("FLAGS_stability_anchor_interval")),
                    keep_last=2,
                )
        return cls(anchor=anchor, **kw)

    @classmethod
    def for_engine(cls, engine, anchor, extras: Optional[dict] = None, **kw
                   ) -> "StabilitySentinel":
        """Sentinel wired to a :class:`HybridParallelEngine`: anchors carry
        params + engine-resident ZeRO optimizer shards (``engine_state_dict``
        syncs them back), restore re-applies accumulators and invalidates the
        sharded state so the next step repacks (the PR 3 failed-step recovery
        path). ``extras`` (loader, rng, ...) join the checkpoint tree."""
        from ..distributed.checkpoint import engine_apply_state, engine_state_dict

        extras = dict(extras or {})

        def state_fn():
            st = engine_state_dict(engine)
            st.update(extras)
            return st

        s = cls(
            anchor=anchor, state_fn=state_fn,
            post_restore=lambda st: engine_apply_state(engine, st), **kw,
        )
        engine.attach_sentinel(s)
        return s

    # -- per-step entry points --------------------------------------------
    def observe(
        self,
        step: int,
        loss=None,
        grads: Sequence = (),
        params: Sequence = (),
        lr: Optional[float] = None,
        pos=None,
        sample_indices=None,
        indices_fn: Optional[Callable[[], Optional[list]]] = None,
        committed: bool = False,
        stash: bool = False,
    ) -> Optional[StabilityVerdict]:
        """Feed one step's signals. Returns a verdict for THIS step (sync
        detection → skip is possible) or for an OLDER deferred step (late →
        rollback), or None.

        ``committed=True`` marks observations whose update has already been
        applied (the engine's donated fused step) — a trip can then only
        roll back. ``stash=True`` additionally parks the verdict for a later
        :meth:`take_verdict` (the engine hook uses it so the training loop
        polls after ``train_step`` returns)."""
        from ..core import lazy as lazy_mod
        from ..framework import flags as _flags
        from .. import profiler as _prof

        _prof.counter_inc("stability_observed")
        # 1) judge anything deferred from earlier steps (force-read: ≤1 step
        #    late is the contract, and by now the device has long finished)
        verdict = self._drain(before_step=step, force=True)
        # 2) this step's fused signal pack
        handle = self._pack_handle(loss, grads, params, lr)
        if handle is not None:
            if pos is None and self._next_note is not None:
                pos, noted_indices, noted_fn = self._next_note
                sample_indices = sample_indices or noted_indices
                indices_fn = indices_fn or noted_fn
            self._next_note = None
            entry = {
                "step": int(step), "pos": tuple(pos) if pos is not None else None,
                "indices": (list(sample_indices) if sample_indices is not None
                            else None),
                "indices_fn": indices_fn, "handle": handle,
                "committed": bool(committed),
            }
            defer = committed or (
                lazy_mod.lazy_enabled()
                and bool(_flags.flag("FLAGS_lazy_async", True))
            )
            if defer:
                with self._lock:
                    self._pending.append(entry)
            else:
                v = self._judge(entry, self._read(entry), late=False)
                verdict = verdict or v
        if verdict is not None and stash:
            with self._lock:
                self._stash.append(verdict)
        return verdict

    def take_verdict(self) -> Optional[StabilityVerdict]:
        """Pop a verdict staged by the drain tap or a ``stash=True`` observe
        (the engine integration's polling side)."""
        with self._lock:
            return self._stash.pop(0) if self._stash else None

    def poll(self) -> Optional[StabilityVerdict]:
        """Force-judge everything still deferred (end of epoch / loop exit)."""
        return self._drain(before_step=None, force=True)

    def is_quarantined(self, pos=None, step: Optional[int] = None) -> bool:
        return self.quarantine.is_quarantined(pos=pos, step=step)

    def note_batch(self, pos, sample_indices=None,
                   indices_fn: Optional[Callable[[], Optional[list]]] = None
                   ) -> None:
        """Associate the NEXT committed observation with a loader position /
        sample indices. The engine step path observes loss-only signals and
        does not know which batch it is running — the training loop calls
        this right before ``train_step`` so a quarantine entry still names
        the batch, and the chaos spikes target the batch ordinal (stable
        across a replay) instead of the optimizer step count (which drifts
        once a quarantined batch is skipped)."""
        self._next_note = (
            tuple(pos) if pos is not None else None, sample_indices, indices_fn,
        )

    def note_anchor(self, step: int) -> None:
        """Record that an anchor checkpoint committed at ``step`` (feeds the
        pin protocol)."""
        self._anchor_steps.append(int(step))
        del self._anchor_steps[:-32]
        self._advance_pin()

    def maybe_anchor(self, step: int, state: Optional[dict] = None) -> bool:
        """Periodic anchor save through the configured checkpoint; returns
        True when a checkpoint committed at ``step``."""
        if self.anchor is None:
            return False
        st = self._state_tree(state)
        if st is None:
            return False
        if self.anchor.maybe_save(step, st):
            self.note_anchor(step)
            return True
        return False

    # -- chaos spikes ------------------------------------------------------
    def maybe_spike(self, arrays, step=None, rank=None):
        """Consult the ``loss.spike``/``grad.spike`` injection points at the
        step boundary and scale every floating batch array device-side (the
        engine hook — poisons the step the way a corrupt batch would)."""
        from . import inject as _inject

        if not _inject.armed():
            return arrays
        note = self._next_note
        if note is not None and note[0] is not None:
            # spikes target BATCHES: the noted loader position is stable
            # across a replay, the optimizer step count is not
            step = note[0][1]
        scale = None
        for point in ("loss.spike", "grad.spike"):
            s = _inject.spike(point, step=step, rank=rank)
            if s is not None:
                scale = s if scale is None else scale * s
        if scale is None:
            return arrays
        import jax.numpy as jnp

        out = [
            a * jnp.asarray(scale, a.dtype)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a
            for a in arrays
        ]
        return type(arrays)(out) if isinstance(arrays, tuple) else out

    # -- rollback / halt ---------------------------------------------------
    def rollback(self, verdict: StabilityVerdict, state: Optional[dict] = None
                 ) -> int:
        """Restore the newest verified anchor STRICTLY OLDER than the
        poisoned step and quarantine that step; returns the anchor step the
        caller replays from. Raises :class:`StabilityError` when no eligible
        anchor exists (degrades to halt)."""
        from ..core import lazy as lazy_mod
        from ..profiler import flight as _flight
        from ..profiler import spans as _spans
        from .. import profiler as _prof

        st = self._state_tree(state)
        if self.anchor is None or st is None:
            self.halt(verdict, reason="rollback requested but no anchor configured")
        with _spans.span("stability_rollback", step=verdict.step,
                         signal=verdict.signal) as sp:
            # drop the poisoned timeline's deferred signal handles BEFORE
            # flushing: the flush below runs the drain tap, which must not
            # judge a stale entry (its signals were computed on the poisoned
            # weights) and quarantine a healthy batch
            with self._lock:
                del self._pending[:]
                del self._stash[:]
            # materialize any half-recorded step so the restore does not
            # write through a pending graph
            lazy_mod.flush()
            anchor_step = self.anchor.resume(st, max_step=verdict.step - 1)
            if anchor_step < 0:
                self.halt(
                    verdict,
                    reason=f"no verified anchor older than step {verdict.step}",
                )
            # anchors saved inside the detection window may carry the
            # poisoned update — a skipped (quarantined) step will never be
            # re-saved by the replay, so drop them now
            for a in list(self._anchor_steps):
                if anchor_step < a <= verdict.step:
                    try:
                        self.anchor.invalidate(a)
                    except Exception:
                        pass
                    self._anchor_steps.remove(a)
            # pin the anchor we are replaying from until the replay commits
            # a newer clean one (keep_last GC must not eat the active anchor)
            self._pin(anchor_step)
            self._last_clean_step = min(self._last_clean_step, anchor_step)
            if self._post_restore is not None:
                self._post_restore(st)
            sp.set(anchor_step=anchor_step)
        _prof.counter_inc("stability_rollbacks")
        _flight.dump(
            "stability_rollback",
            extra={"verdict": verdict.to_dict(), "anchor_step": anchor_step},
        )
        return anchor_step

    def adopt(self, verdict: StabilityVerdict) -> StabilityVerdict:
        """Fold a verdict ANOTHER rank reached (:class:`VerdictBarrier`)
        into this sentinel: quarantine the condemned batch locally (loader
        positions are world-shared in lockstep data-parallel loops) and
        consume the same ladder rung, so the coordinated replay skips the
        batch on every rank and the incident budget stays consistent with
        the rank that actually tripped."""
        from .. import profiler as _prof

        _prof.counter_inc("stability_coordinated_trips")
        self._clean_streak = 0
        if verdict.action == "rollback":
            self._rollbacks_used += 1
        elif verdict.action == "skip":
            self._skips_used += 1
        if verdict.action in ("skip", "rollback"):
            self.quarantine.add(
                verdict.step, pos=verdict.pos, signals=verdict.signals,
                action=verdict.action,
            )
        with self._lock:
            self._history.append({
                "step": verdict.step, **verdict.signals,
                "anomaly": verdict.signal,
                "adopted_from_rank": verdict.origin_rank,
            })
        return verdict

    def halt(self, verdict: StabilityVerdict, reason: str = "") -> None:
        """Terminal rung: flight post-mortem naming the tripping signal,
        then a structured :class:`StabilityError`."""
        from ..profiler import flight as _flight
        from .. import profiler as _prof

        _prof.counter_inc("stability_halts")
        with self._lock:
            history = list(self._history)
        _flight.dump(
            "stability_halt",
            extra={
                "verdict": verdict.to_dict(),
                "signal": verdict.signal,
                "reason": reason or "policy ladder exhausted",
                "history": history[-32:],
            },
        )
        raise StabilityError(
            f"training stability sentinel halt: signal {verdict.signal!r} "
            f"value {verdict.value:.6g} (robust z={verdict.zscore:.1f}) at "
            f"step {verdict.step}"
            + (f" — {reason}" if reason else ""),
            verdict=verdict, history=history,
        )

    # -- internals ---------------------------------------------------------
    def _state_tree(self, state: Optional[dict]) -> Optional[dict]:
        if state is not None:
            return state
        if self._state_fn is not None:
            return self._state_fn()
        return self._state

    def _pack_handle(self, loss, grads, params, lr):
        """Record the fused signal pack (device-side). Lazy inputs stay in
        the pending graph — the pack rides the step's own flush; concrete
        inputs go through a memoized jit."""
        from ..core import lazy as lazy_mod
        from ..core.tensor import Tensor

        def arr(x):
            return x._data if isinstance(x, Tensor) else x

        loss_a = arr(loss) if loss is not None else None
        grad_as = [arr(g) for g in grads if g is not None]
        param_as = [arr(p) for p in params if p is not None]
        if loss_a is None and not grad_as:
            return None
        has_loss = loss_a is not None
        has_lr = lr is not None and param_as and grad_as
        inputs = []
        if has_loss:
            inputs.append(loss_a)
        if has_lr:
            inputs.append(np.float32(lr))
        inputs.extend(grad_as)
        inputs.extend(param_as if has_lr else [])
        npar = len(param_as) if has_lr else 0
        key = (len(grad_as), npar, bool(has_loss), bool(has_lr))
        fn = _packer(*key)
        if lazy_mod.lazy_enabled() or any(lazy_mod.is_lazy(x) for x in inputs):
            (out,), _ = lazy_mod.record(
                "stability_signals", fn, inputs, key=("stability_signals",) + key
            )
            return out
        jfn = _packers_jit.get(key)
        if jfn is None:
            import jax

            jfn = _packers_jit[key] = jax.jit(fn)
        return jfn(*inputs)

    def _read(self, entry) -> np.ndarray:
        """The one per-step host readback: a 4-float vector, attributed
        through ``lazy.timed_block`` like every sanctioned device wait."""
        from ..core import lazy as lazy_mod
        from .. import profiler as _prof

        h = entry["handle"]
        v = h._value() if lazy_mod.is_lazy(h) else h
        v = lazy_mod.timed_block(v, "stability_signals")
        _prof.counter_inc("stability_readbacks")
        return np.asarray(v, np.float64)

    def _ready(self, entry) -> bool:
        from ..core import lazy as lazy_mod

        h = entry["handle"]
        if lazy_mod.is_lazy(h):
            h = h._concrete
            if h is None:
                return False
        try:
            return bool(h.is_ready())
        except Exception:
            return True

    def _tap(self) -> None:
        """Drain-tap body (rides the lazy deferred-check path): judge any
        pending entry whose device values already landed — non-blocking,
        verdicts staged for :meth:`take_verdict`/the next observe."""
        with self._lock:
            if not self._pending or not self._ready(self._pending[0]):
                return
            entry = self._pending.pop(0)
        v = self._judge(entry, self._read(entry), late=True)
        if v is not None:
            with self._lock:
                self._stash.append(v)

    def _drain(self, before_step: Optional[int], force: bool
               ) -> Optional[StabilityVerdict]:
        verdict = None
        while True:
            with self._lock:
                if not self._pending:
                    break
                nxt = self._pending[0]
                if before_step is not None and nxt["step"] >= before_step:
                    break
                if not force and not self._ready(nxt):
                    break
                self._pending.pop(0)
            v = self._judge(nxt, self._read(nxt), late=True)
            verdict = verdict or v
        if verdict is None:
            with self._lock:
                if self._stash:
                    verdict = self._stash.pop(0)
        return verdict

    def _judge(self, entry, values: np.ndarray, late: bool
               ) -> Optional[StabilityVerdict]:
        """Update statistics with one step's signal vector and escalate on
        anomaly. ``late`` entries (deferred/committed) can only roll back."""
        from .. import profiler as _prof

        sig = {k: float(values[i]) for i, k in enumerate(SIGNALS)}
        worst: Optional[Tuple[str, float, float]] = None
        if sig["nonfinite"] > 0.0 or not all(math.isfinite(v) for v in sig.values()):
            worst = ("nonfinite", sig["nonfinite"], float("inf"))
        else:
            # score first, fold only if the WHOLE step is clean: on an
            # anomalous step even the below-threshold signals are suspect
            # (a spiked batch inflates all of them) and must not walk the
            # baselines upward
            scores = {
                k: self._stats[k].score(sig[k])
                for k in ("grad_norm", "loss", "upd_ratio")
            }
            for k, (bad, z) in scores.items():
                if bad and (worst is None or z > worst[2]):
                    worst = (k, sig[k], z)
            if worst is None:
                for k in scores:
                    self._stats[k].fold(sig[k])
        if math.isfinite(sig["loss"]):
            self._loss_ema = (
                sig["loss"] if self._loss_ema is None
                else 0.98 * self._loss_ema + 0.02 * sig["loss"]
            )
        rec = {"step": entry["step"], **sig, "anomaly": worst[0] if worst else None}
        with self._lock:
            self._history.append(rec)
        _last_signals.update(sig)
        _last_signals["loss_ema"] = self._loss_ema if self._loss_ema is not None else sig["loss"]
        if worst is None:
            self._clean_streak += 1
            if self._clean_streak >= self.cooldown:
                self._skips_used = 0
                self._rollbacks_used = 0
            self._last_clean_step = max(self._last_clean_step, entry["step"])
            self._advance_pin()
            return None
        # -- anomaly: escalate through the ladder --------------------------
        _prof.counter_inc("stability_trips")
        self._clean_streak = 0
        late = late or entry["committed"]
        if not late and self._skips_used < self.max_skips:
            action = "skip"
            self._skips_used += 1
        elif self.anchor is not None and self._rollbacks_used < self.max_rollbacks:
            action = "rollback"
            self._rollbacks_used += 1
        else:
            action = "halt"
        verdict = StabilityVerdict(
            action, entry["step"], entry["pos"], worst[0], worst[1], worst[2],
            late, sig,
        )
        if action in ("skip", "rollback"):
            indices = entry["indices"]
            if indices is None and entry["indices_fn"] is not None:
                try:
                    indices = entry["indices_fn"]()
                except Exception:
                    indices = None
            self.quarantine.add(
                entry["step"], pos=entry["pos"], sample_indices=indices,
                signals=sig, action=action,
            )
            if action == "skip":
                _prof.counter_inc("stability_skips")
        from ..profiler import spans as _spans

        with _spans.span("stability_trip", step=entry["step"], signal=worst[0],
                         action=action, late=late):
            pass
        return verdict

    # -- anchor pinning ----------------------------------------------------
    def _pin(self, step: int) -> None:
        if self.anchor is None or step == self._pinned:
            return
        try:
            self.anchor.protect(step)
            if self._pinned is not None:
                self.anchor.release(self._pinned)
        except Exception:
            pass
        self._pinned = step

    def _advance_pin(self) -> None:
        """Pin the newest anchor whose step is JUDGED CLEAN — an anchor saved
        in the detection window may hold the poisoned update, so the pin
        trails the judgment horizon by design."""
        if self.anchor is None:
            return
        safe = [a for a in self._anchor_steps if a <= self._last_clean_step]
        if safe:
            self._pin(max(safe))

    def _context(self) -> dict:
        with self._lock:
            hist = list(self._history)[-16:]
        return {
            "name": self.name,
            "recent_signals": hist,
            "incident": {
                "skips_used": self._skips_used,
                "rollbacks_used": self._rollbacks_used,
                "clean_streak": self._clean_streak,
            },
            "quarantined": len(self.quarantine),
            "pinned_anchor": self._pinned,
            "pending": len(self._pending),
        }
