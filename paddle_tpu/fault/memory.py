"""HBM exhaustion resilience — preflight admission + the OOM recovery ladder.

Device memory was the last unmanaged failure class in the robustness stack:
an XLA ``RESOURCE_EXHAUSTED`` was a raw crash wherever it fired — the lazy
flush, the fused engine step, a serving step. Following the LazyTensor
discipline of making runtime state observable and recoverable
(arXiv:2102.13267) and the ZeRO insight that memory pressure should be
traded for recomputation/communication rather than failure
(arXiv:2004.13336), this module makes OOM a *managed* condition:

* **Classifier** (:func:`is_oom` / :func:`classify`) — ONE place that
  decides whether an exception is a device-memory exhaustion (the
  ``XlaRuntimeError`` type or the ``RESOURCE_EXHAUSTED``/out-of-memory
  status text, chained causes included). Every ``except`` that can see an
  OOM in the dispatch layers routes through it (analysis ``oom-handler``
  lint rule).
* **Preflight admission** (:func:`preflight`) — at compile time the lazy
  flush captures each executable's ``memory_analysis()`` (via
  ``cost_model.executable_memory``) keyed like the executable cache; before
  each dispatch the predicted extra footprint (temp + output − donated/alias
  bytes) plus the current live-array census is compared against the device
  budget (``FLAGS_hbm_budget_bytes``, default backend capacity −
  ``FLAGS_hbm_reserve_bytes``). ``FLAGS_hbm_admission`` picks the policy:
  ``off`` (one flag probe per flush — the whole disabled path), ``warn``,
  or ``enforce`` (structured :class:`HbmBudgetExceeded` BEFORE the device
  is touched). Predictions ride the ``compile``/``lazy_flush`` spans.
* **Recovery ladder** when ``RESOURCE_EXHAUSTED`` fires anyway: classify →
  :func:`free_pressure` (evict cold lazy executable-cache entries, refresh
  the live census, shrink serving-pool admission headroom) → retry once →
  (engine training step only) degrade through the existing
  ``grad_accumulate`` scan path at 2×/4× microbatching — bit-identical to a
  run configured with that accumulation from the start → halt with a
  :class:`HbmExhausted` + flight post-mortem carrying the census, the
  per-executable memory attributions and every recovery attempt.

Chaos: ``hbm.oom`` / ``hbm.pressure`` (fault/inject.py) synthesize
``RESOURCE_EXHAUSTED`` at named dispatch sites / sustained pressure;
tests/test_memory_pressure.py is the suite.

Zero-cost disabled path: nothing imports this module until an exception is
being classified or ``FLAGS_hbm_admission`` is flipped on — the tier-1
inert tripwire pins that the classifier and the preflight are never called
by an unconfigured training loop.
"""
from __future__ import annotations

import collections
import threading
import warnings
import weakref
from typing import Callable, Dict, List, Optional

__all__ = [
    "HbmBudgetExceeded", "HbmExhausted", "is_oom", "classify", "note_oom",
    "preflight", "free_pressure", "budget_bytes", "last_prediction",
    "attributions", "note_executable", "post_mortem",
    "register_pressure_handler",
]

# RESOURCE_EXHAUSTED status text markers (jaxlib renders the absl status
# code into the message; PjRt allocators add their own out-of-memory prose).
# The full set is consulted only for the XLA runtime-error types; a PLAIN
# exception must carry one of the unambiguous markers — "Failed to
# allocate" alone appears in plenty of non-device errors (inodes, TLS,
# sockets) and must not conjure a phantom memory incident.
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED", "Resource exhausted", "Out of memory",
    "out of memory", "OOM when allocating", "Failed to allocate",
)
_OOM_MARKERS_STRONG = (
    "RESOURCE_EXHAUSTED", "Resource exhausted", "Out of memory",
    "out of memory", "OOM when allocating",
)


class HbmBudgetExceeded(RuntimeError):
    """Preflight admission rejected a dispatch: the predicted footprint
    would exceed the device budget. Raised BEFORE the device is touched —
    the executable is compiled and cached, nothing was dispatched. Carries
    the numbers the message names so callers can react programmatically."""

    def __init__(self, where: str, predicted_bytes: int, live_bytes: int,
                 budget_bytes: int, peak_bytes: int = 0):
        super().__init__(
            f"HBM admission rejected dispatch at '{where}': predicted "
            f"{predicted_bytes} bytes (live census {live_bytes} + executable "
            f"peak {peak_bytes}) exceeds budget {budget_bytes} bytes "
            f"(FLAGS_hbm_admission=enforce; raise FLAGS_hbm_budget_bytes, "
            f"free buffers, or shrink the step)"
        )
        self.where = where
        self.predicted_bytes = int(predicted_bytes)
        self.live_bytes = int(live_bytes)
        self.budget_bytes = int(budget_bytes)
        self.peak_bytes = int(peak_bytes)


class HbmExhausted(RuntimeError):
    """The OOM recovery ladder ran out of rungs (or recovery was impossible
    — donated inputs already invalidated). Carries the attempts made and
    the flight post-mortem path; ``__cause__`` is the original
    ``RESOURCE_EXHAUSTED``."""

    def __init__(self, where: str, attempts: List[dict],
                 dump_path: Optional[str] = None):
        names = [a.get("action", "?") for a in attempts]
        super().__init__(
            f"HBM exhausted at '{where}' and the recovery ladder failed "
            f"(attempts: {names or ['none possible']}; post-mortem: "
            f"{dump_path or 'unavailable'})"
        )
        self.where = where
        self.attempts = list(attempts)
        self.dump_path = dump_path


# -- classifier ---------------------------------------------------------------
def classify(exc: BaseException) -> Optional[dict]:
    """The ONE decision point for "is this a device-memory exhaustion".
    Walks the cause/context chain; matches the ``XlaRuntimeError`` binding
    type by name (imports of jaxlib internals stay out of the hot path) AND
    the RESOURCE_EXHAUSTED status markers, so both real PjRt errors and the
    synthesized ``hbm.oom`` chaos payloads classify identically. Returns
    ``{"kind": "hbm_oom", "type": ..., "message": ...}`` or None."""
    seen = set()
    e: Optional[BaseException] = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        msg = str(e)
        typename = type(e).__name__
        if typename in ("XlaRuntimeError", "JaxRuntimeError") or isinstance(
                e, MemoryError):
            if any(m in msg for m in _OOM_MARKERS) or isinstance(e, MemoryError):
                return {"kind": "hbm_oom", "type": typename,
                        "message": msg[:500]}
        elif any(m in msg for m in _OOM_MARKERS_STRONG) and isinstance(e, Exception):
            # some wrappers re-raise the status text under a plain
            # RuntimeError (and the chaos fallback does when the binding is
            # not constructible) — but only the unambiguous markers count
            # for a non-XLA type
            return {"kind": "hbm_oom", "type": typename, "message": msg[:500]}
        e = e.__cause__ or e.__context__
    return None


def is_oom(exc: BaseException) -> bool:
    return classify(exc) is not None


# -- budget -------------------------------------------------------------------
_budget_cache: List[Optional[int]] = [None]  # resolved once per process


def budget_bytes(refresh: bool = False) -> int:
    """The device budget the admission check compares against:
    ``FLAGS_hbm_budget_bytes`` when set, else the backend-reported capacity
    (``device.memory_stats()['bytes_limit']``) minus
    ``FLAGS_hbm_reserve_bytes``. 0 = no budget resolvable (CPU reports no
    capacity): admission still predicts and attributes, never rejects."""
    from ..framework import flags

    explicit = int(flags.flag("FLAGS_hbm_budget_bytes", 0) or 0)
    if explicit:
        return explicit
    if _budget_cache[0] is None or refresh:
        cap = 0
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            cap = int((stats or {}).get("bytes_limit", 0) or 0)
        except Exception:
            cap = 0
        if cap:
            cap = max(cap - int(flags.flag("FLAGS_hbm_reserve_bytes", 0) or 0), 0)
        _budget_cache[0] = cap
    return _budget_cache[0]


# -- per-executable attribution registry -------------------------------------
_lock = threading.Lock()
_ATTR_MAX = 256
_attr: "collections.OrderedDict" = collections.OrderedDict()  # guarded_by: _lock
_events: "collections.deque" = collections.deque(maxlen=32)  # guarded_by: _lock
_last: Dict[str, int] = {}  # most recent preflight numbers (BENCH line)
_warned: set = set()  # guarded_by: _lock
_provider_installed = False


def note_executable(key: str, mem: Optional[dict]) -> None:
    """Record one executable's memory analysis, keyed like the executable
    cache (the flush-signature hash) — the post-mortem's per-executable
    attribution table."""
    if mem is None:
        return
    with _lock:
        _attr[key] = dict(mem)
        _attr.move_to_end(key)
        while len(_attr) > _ATTR_MAX:
            _attr.popitem(last=False)
    _ensure_provider()


def analyze_compiled(compiled, key: Optional[str] = None) -> Optional[dict]:
    """``cost_model.executable_memory`` + registry note in one call (the
    lazy flush's compile-time capture)."""
    from ..cost_model import executable_memory

    mem = executable_memory(compiled)
    if mem is not None and key is not None:
        note_executable(key, mem)
    return mem


def attributions(top: int = 16) -> List[dict]:
    """The per-executable memory table, largest peak first."""
    with _lock:
        rows = [{"key": k, **v} for k, v in _attr.items()]
    rows.sort(key=lambda r: -r.get("peak_bytes", 0))
    return rows[:top]


def last_prediction() -> Dict[str, int]:
    """Most recent preflight numbers (predicted/live/budget bytes) — folded
    into every BENCH JSON line."""
    return dict(_last)


# -- preflight admission ------------------------------------------------------
def preflight(mem: Optional[dict], where: str, span=None,
              donated_bytes: int = 0) -> Optional[Dict[str, int]]:
    """Compare the executable's predicted footprint against the device
    budget BEFORE dispatch. ``mem`` is the compile-time
    ``executable_memory`` dict (None — e.g. a background-compile replay
    step — predicts nothing and admits).

    Estimate = current live-array census + temp + output −
    max(alias, donated) bytes: the arguments are already IN the census, and
    outputs aliasing donated inputs must not count twice — backends that
    honor the aliasing hint report it as ``alias_bytes``; backends that
    silently decline (CPU) leave alias at 0, so the donation mask's own
    byte count is the fallback correction (the donated buffers die at
    dispatch either way).

    Policy per ``FLAGS_hbm_admission``: ``warn`` warns once per call site,
    ``enforce`` raises :class:`HbmBudgetExceeded`. Callers gate on the flag
    — this function is never reached when admission is ``off`` (pinned by
    the tier-1 inert tripwire).
    """
    from .. import profiler as _prof
    from ..framework import flags

    _ensure_provider()
    census = _prof.memory_census()
    live = int(census.get("live_bytes", 0))
    if mem is None:
        pred = {"hbm_live_bytes": live}
        if span is not None:
            span.set(**pred)
        return None
    extra = (int(mem.get("temp_bytes", 0)) + int(mem.get("output_bytes", 0))
             - max(int(mem.get("alias_bytes", 0)), int(donated_bytes)))
    extra = max(extra, 0)
    pressure = 0
    from . import inject as _inject

    if _inject._armed:
        pressure = _inject.pressure_bytes()
    predicted = live + extra + pressure
    budget = budget_bytes()
    peak = int(mem.get("peak_bytes", 0))
    _prof.counter_inc("hbm_admission_checks")
    _last.update(
        hbm_predicted_peak_bytes=predicted, hbm_live_bytes=live,
        hbm_extra_bytes=extra, hbm_budget_bytes=budget,
        hbm_exec_peak_bytes=peak,
    )
    if span is not None:
        span.set(
            hbm_predicted_peak_bytes=predicted, hbm_live_bytes=live,
            hbm_extra_bytes=extra, hbm_budget_bytes=budget,
        )
    if budget and predicted > budget:
        _prof.counter_inc("hbm_admission_rejects")
        mode = str(flags.flag("FLAGS_hbm_admission", "off"))
        if mode == "enforce":
            raise HbmBudgetExceeded(where, predicted, live, budget, peak)
        with _lock:
            first = where not in _warned
            _warned.add(where)
        if first:
            warnings.warn(
                f"HBM admission: predicted {predicted} bytes exceeds budget "
                f"{budget} bytes at '{where}' (FLAGS_hbm_admission=warn — "
                f"dispatching anyway)",
                RuntimeWarning,
            )
    return _last.copy()


# -- pressure relief ----------------------------------------------------------
# Subsystems that can give memory back under pressure register a handler
# (weakly bound): the serving engine parks KV blocks (admission headroom
# shrink → backpressure), future residents can drop caches. Handlers run on
# the CALLING thread and must be cheap + thread-safe (the serving handler
# only sets a request flag its scheduler thread applies).
_pressure_handlers: Dict[str, Callable[[], Optional[dict]]] = {}


def register_pressure_handler(name: str, fn, owner=None) -> None:
    """Register a pressure-relief callback. With ``owner`` given, the
    handler is dropped automatically once the owner is collected (serving
    engines come and go; a dead engine must not pin itself here — the
    weakref's finalizer pops the registry entry)."""
    if owner is not None:
        wr = weakref.ref(owner, lambda _r, _n=name: _pressure_handlers.pop(_n, None))
        orig = fn

        def fn(_wr=wr, _orig=orig):  # noqa: F811 — deliberate rebind
            o = _wr()
            return _orig(o) if o is not None else None

    _pressure_handlers[name] = fn


def unregister_pressure_handler(name: str) -> None:
    _pressure_handlers.pop(name, None)


def free_pressure(reason: str = "oom") -> dict:
    """The ladder's give-memory-back rung: evict cold lazy executable-cache
    entries (compiled programs pin temp allocations and constants), run the
    pressure handlers (serving pool shrink), refresh the live census.
    Returns a summary dict that joins the recovery-attempt record."""
    from .. import profiler as _prof
    from ..core import lazy as lazy_mod

    evicted = lazy_mod.evict_cold()
    if evicted:
        _prof.counter_inc("hbm_cache_evicted", evicted)
    handlers = {}
    for name, fn in list(_pressure_handlers.items()):
        try:
            handlers[name] = fn()
        except Exception as e:
            handlers[name] = {"error": repr(e)}
    census = _prof.memory_census()
    return {
        "reason": reason,
        "evicted_executables": evicted,
        "handlers": handlers,
        "live_bytes": census.get("live_bytes", 0),
    }


# -- event log + post-mortem --------------------------------------------------
def note_oom(where: str, exc: BaseException) -> dict:
    """Record one classified OOM (counter + bounded event ring feeding the
    flight context provider). Returns the classification."""
    from .. import profiler as _prof

    info = classify(exc) or {"kind": "hbm_oom", "type": type(exc).__name__,
                             "message": str(exc)[:500]}
    info["where"] = where
    _prof.counter_inc("hbm_oom_trips")
    with _lock:
        _events.append(dict(info))
    _ensure_provider()
    return info


def post_mortem(where: str, attempts: List[dict],
                exc: Optional[BaseException] = None) -> Optional[str]:
    """Flight dump for an unrecovered exhaustion: the live census, the
    per-executable memory attributions, the budget, and every recovery
    attempt the ladder made."""
    from .. import profiler as _prof
    from ..profiler import flight

    try:
        census = _prof.memory_census()
    except Exception:
        census = _prof.memory_stats()
    return flight.dump(
        "hbm_exhausted",
        extra={
            "where": where,
            "census": dict(census),
            "budget_bytes": budget_bytes(),
            "attributions": attributions(),
            "attempts": list(attempts),
            "exception": repr(exc) if exc is not None else None,
        },
    )


def _context() -> dict:
    with _lock:
        events = list(_events)
    return {
        "budget_bytes": budget_bytes(),
        "last_prediction": dict(_last),
        "recent_oom": events[-8:],
        "attributions": attributions(top=8),
    }


def _ensure_provider() -> None:
    """Install the flight context provider on first real use — every crash
    dump from then on carries the budget, the last prediction, and the OOM
    event tail. Never installed by an unconfigured loop (this module is not
    even imported there)."""
    global _provider_installed
    if not _provider_installed:
        from ..profiler import flight

        flight.add_context_provider("hbm", _context)
        _provider_installed = True
