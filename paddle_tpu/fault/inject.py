"""Deterministic fault injection.

Every injection point has a stable name (see :data:`POINTS`) and is consulted
only when the framework is armed, so the production hot path pays one
attribute check. Points are addressable from the environment
(``PADDLE_FAULT_INJECT``) or programmatically (:func:`arm`), which makes
crash-at-any-point resume testable without patching internals — the gap
SURVEY.md flags even in the reference stack ("no systematic fault-injection
framework").

Spec grammar (env var or :func:`arm` string form)::

    point[:k=v[,k=v...]][;point2:...]

    PADDLE_FAULT_INJECT="ckpt.write:at=2,times=4;preempt.sigterm:step=3"

Keys (all optional; values are ints except ``op``):

* ``at=N``    — fire on the Nth matching call of this point (1-based).
* ``from=N``  — fire on every matching call from the Nth on (persistent
  failures that must defeat the retry helper).
* ``step=K``  — fire when the call context carries ``step == K``.
* ``op=NAME`` — only calls whose context carries ``op == NAME`` match.
* ``rank=R``  — only calls whose context carries ``rank == R`` match (chaos
  specs shared by a whole world target one rank).
* ``call=N``  — with ``op=``: the Nth call of that op (alias of ``at``).
* ``times=M`` — fire at most M times total (default: unlimited).
* ``ms=N``    — ``rank.slow`` payload: straggler delay in milliseconds.
* ``exit=N``  — ``rank.kill`` payload: exit code (default 137).
* ``bytes=N`` — ``hbm.pressure`` payload: synthetic live-byte pressure added
  to the preflight admission estimate while armed.
* ``blocks=N`` — ``hbm.pressure`` payload: serving KV blocks parked
  (admission headroom shrink) when the point fires at a scheduler step.

Failure-type points (``store.op``, ``ckpt.write``, ``ckpt.serialize``,
``ckpt.ack``, ``ckpt.commit``) raise :class:`InjectedFault` (an ``OSError``,
so the shared retry helper treats it as transient); ``preempt.sigterm``
delivers a real SIGTERM; ``tensor.nan`` overwrites the first element of the
named op's output with NaN (threaded through eager and lazy dispatch).
Chaos points (``rank.kill`` / ``rank.hang`` / ``rank.slow`` /
``collective.drop``) execute their action in-process via :func:`chaos` /
:func:`chaos_drop`, threaded through the distributed watchdog's progress
publications and guarded collectives. Serving chaos points (``serve.crash``
/ ``serve.wedge`` / ``serve.slow_step`` / ``serve.pool_corrupt``) are
consulted by the serving engine's scheduler thread at every step boundary —
they drive the ServingSupervisor recovery suite (tests/test_serving_chaos.py).
``serve.wedge`` wedges the scheduler thread forever by default (the
supervisor abandons it); ``ms=N`` bounds the wedge for detection-only tests.
``serve.snapshot_corrupt`` fires inside ``Engine.snapshot`` (crash re-attach
and handoff captures alike) and tears the exported pool bookkeeping —
``Engine.adopt`` must reject the capture with ``SnapshotError`` and fall
back whole to re-prefill recovery (tests/test_serving_snapshot.py).
Training-stability chaos points (``loss.spike`` / ``grad.spike``) are
consulted at the step boundary via :func:`spike` — they scale the step's
loss/gradients by ``scale=`` (or poison them non-finite with
``nonfinite=1``) and drive the StabilitySentinel skip/rollback suites
(tests/test_stability_sentinel.py, tests/test_stability_chaos.py).
Memory-pressure chaos points (``hbm.oom`` / ``hbm.pressure``) drive the OOM
recovery ladder (fault/memory.py): ``hbm.oom`` synthesizes an XLA
``RESOURCE_EXHAUSTED`` at a named dispatch site (:func:`maybe_hbm_oom`,
``op=`` selects the site — ``lazy_flush``, ``engine.step``, ``engine.accum``,
``serve.step``); ``hbm.pressure`` models sustained pressure (``bytes=``
inflates the preflight admission estimate while armed, ``blocks=`` parks
serving KV blocks — tests/test_memory_pressure.py).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

# Registered injection point names -> where they are threaded. The tripwire
# test in tests/test_fault_tolerance.py asserts every name here is exercised.
POINTS: Dict[str, str] = {
    "store.op": "TCPStore operations in fleet/elastic (set/get/add)",
    "ckpt.write": "distributed/checkpoint.py save_state_dict write path",
    "preempt.sigterm": "PreemptionGuard.check(step=k) — SIGTERM at step k",
    "tensor.nan": "core/dispatch.py eager_call — NaN into a named op's output",
    # -- chaos points (distributed watchdog harness) --------------------------
    "rank.kill": "watchdog.publish — hard-kill this rank (os._exit, default 137)",
    "rank.hang": "watchdog.publish — wedge this rank in a sleep loop forever",
    "rank.slow": "watchdog.publish — straggler delay (ms=N, default 1000)",
    "collective.drop": "watchdog.guard enter — this rank never joins the collective",
    # -- coordinated-commit crash points (checkpoint.CoordinatedCheckpoint) ---
    "ckpt.serialize": "coordinated save — crash during state serialization",
    "ckpt.ack": "coordinated save — crash after durable write, before the ack",
    "ckpt.commit": "coordinated save — crash between full acks and the commit record",
    # -- training-stability chaos points (fault/sentinel.py step boundary) ----
    "loss.spike": "train step boundary — scale the step's loss (scale=/nonfinite= payload)",
    "grad.spike": "train step boundary — scale the step's gradients (scale=/nonfinite= payload)",
    # -- serving chaos points (serving/engine.py scheduler step boundary) -----
    "serve.crash": "serving engine loop — raise inside the scheduler step",
    "serve.wedge": "serving engine loop — wedge the scheduler thread (ms=N bounds it)",
    "serve.slow_step": "serving engine loop — per-step straggler delay (ms=N, default 100)",
    "serve.pool_corrupt": "serving engine loop — break PagePool conservation (next free raises)",
    "serve.snapshot_corrupt": ("Engine.snapshot — tear the pool capture so "
                               "adopt() must reject it and fall back whole"),
    # -- HBM memory-pressure chaos points (fault/memory.py consumers) ---------
    "hbm.oom": ("named dispatch sites (op=lazy_flush/engine.step/engine.accum/"
                "serve.step) — synthesize an XLA RESOURCE_EXHAUSTED there"),
    "hbm.pressure": ("memory pressure: bytes=N inflates the admission "
                     "estimate while armed; blocks=N parks serving pool "
                     "blocks at the scheduler step boundary"),
}


class InjectedFault(OSError):
    """Raised by failure-type injection points. Subclasses OSError so the
    shared retry helper classifies it as transient (tests control persistence
    via ``times=``)."""

    def __init__(self, point: str, ctx: Optional[dict] = None):
        super().__init__(f"injected fault at '{point}' (ctx={ctx or {}})")
        self.point = point
        self.ctx = dict(ctx or {})


_lock = threading.Lock()
_armed = False
_active: Dict[str, dict] = {}
_calls: Dict[str, int] = {}
_fired: Dict[str, int] = {}
_exercised: set = set()  # every point that ever fired in this process


def _parse_spec(spec: str) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, _, raw = part.partition(":")
        point = point.strip()
        cfg: dict = {}
        for kv in raw.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            k = k.strip()
            cfg[k] = v.strip() if k == "op" else int(v)
        out[point] = cfg
    return out


def _install_dispatch_hook(mod):
    # dispatch checks a module attribute instead of importing us per op call
    try:
        from ..core import dispatch

        dispatch._fault_inject = mod
    except Exception:
        pass


def arm(spec) -> None:
    """Arm injection. ``spec`` is either the string grammar above or a dict
    ``{point: {key: value}}``. Unknown point names raise KeyError (typos in a
    fault spec must not silently disable the fault)."""
    global _armed
    cfgs = _parse_spec(spec) if isinstance(spec, str) else {
        k: dict(v) for k, v in spec.items()
    }
    for point in cfgs:
        if point not in POINTS:
            import difflib

            hint = difflib.get_close_matches(point, POINTS, n=1)
            raise KeyError(
                f"unknown injection point {point!r}"
                + (f"; did you mean {hint[0]!r}?" if hint else f"; known: {sorted(POINTS)}")
            )
    with _lock:
        _active.clear()
        _active.update(cfgs)
        _calls.clear()
        _fired.clear()
        _armed = bool(_active)
    import sys

    _install_dispatch_hook(sys.modules[__name__] if _armed else None)


def disarm() -> None:
    """Disarm all injection points (counters reset)."""
    global _armed
    with _lock:
        _active.clear()
        _calls.clear()
        _fired.clear()
        _armed = False
    _install_dispatch_hook(None)


def armed() -> bool:
    return _armed


def should_fire(point: str, step: Optional[int] = None, op: Optional[str] = None,
                rank: Optional[int] = None) -> bool:
    """Deterministically decide whether ``point`` fires for this call.
    Counts only calls that pass the ``op=``/``rank=`` filters, so ``at=N``
    means "the Nth call of that op/rank" regardless of unrelated traffic."""
    if point not in POINTS:
        raise KeyError(f"unknown injection point {point!r}; known: {sorted(POINTS)}")
    if not _armed:
        return False
    with _lock:
        cfg = _active.get(point)
        if cfg is None:
            return False
        if "op" in cfg and op != cfg["op"]:
            return False
        if "rank" in cfg and (rank is None or int(rank) != cfg["rank"]):
            return False
        n = _calls.get(point, 0) + 1
        _calls[point] = n
        at = cfg.get("at", cfg.get("call"))
        if "step" in cfg:
            fire = step is not None and int(step) == cfg["step"]
        elif at is not None:
            fire = n == at
        elif "from" in cfg:
            fire = n >= cfg["from"]
        else:
            fire = True
        if fire:
            times = cfg.get("times")
            if times is not None and _fired.get(point, 0) >= times:
                return False
            _fired[point] = _fired.get(point, 0) + 1
            _exercised.add(point)
        return fire


def check(point: str, **ctx) -> None:
    """Raise :class:`InjectedFault` when ``point`` fires (failure-type call
    sites: store ops, checkpoint writes, coordinated-commit phases)."""
    if should_fire(point, step=ctx.get("step"), op=ctx.get("op"), rank=ctx.get("rank")):
        raise InjectedFault(point, ctx)


def point_cfg(point: str) -> dict:
    """The armed config dict for ``point`` ({} when not armed) — payload
    keys like ``ms=`` / ``exit=`` that parameterize the chaos actions."""
    with _lock:
        return dict(_active.get(point) or {})


# -- chaos actions (rank.* / collective.drop payloads) -----------------------
def _hang(point: str) -> None:
    """Wedge this process: the canonical hung-rank simulation. Announces on
    stderr (the parent's logs show WHY the rank went silent), then sleeps
    until killed — it never returns."""
    import sys as _sys
    import time as _time

    _sys.stderr.write(f"paddle_tpu.fault.inject: '{point}' fired — rank wedged\n")
    _sys.stderr.flush()
    while True:
        _time.sleep(3600)


def chaos(step: Optional[int] = None, rank: Optional[int] = None,
          phase: Optional[str] = None) -> None:
    """Consult the ``rank.*`` chaos points (threaded through
    ``watchdog.publish`` at every step/phase boundary). ``rank.slow`` sleeps
    ``ms=`` milliseconds (default 1000); ``rank.hang`` wedges forever;
    ``rank.kill`` hard-exits with ``exit=`` (default 137 — SIGKILL's shell
    code, NOT resumable: the launcher sees a real failure)."""
    import time as _time

    if not _armed:
        return
    if should_fire("rank.slow", step=step, rank=rank):
        _time.sleep(point_cfg("rank.slow").get("ms", 1000) / 1000.0)
    if should_fire("rank.hang", step=step, rank=rank):
        _hang("rank.hang")
    if should_fire("rank.kill", step=step, rank=rank):
        import sys as _sys

        code = point_cfg("rank.kill").get("exit", 137)
        _sys.stderr.write(f"paddle_tpu.fault.inject: 'rank.kill' fired — exit {code}\n")
        _sys.stderr.flush()
        os._exit(code)


def chaos_drop(rank: Optional[int] = None, step: Optional[int] = None) -> None:
    """``collective.drop``: wedge this rank right before it would enter a
    guarded collective — its peers block until their watchdog deadline."""
    if _armed and should_fire("collective.drop", step=step, rank=rank):
        _hang("collective.drop")


def spike(point: str, step: Optional[int] = None,
          rank: Optional[int] = None) -> Optional[float]:
    """Consult a ``loss.spike``/``grad.spike`` point at the step boundary
    (the stability-sentinel chaos payloads). Returns the multiplier to apply
    to the step's loss/gradients — ``scale=`` (default 1000), or
    ``float('inf')`` with ``nonfinite=1`` (drives the deferred-guard window:
    a non-finite update that commits before the trip surfaces) — or None
    when the point doesn't fire. ``at=``/``step=``/``rank=`` select the
    firing call like every other point."""
    if point not in ("loss.spike", "grad.spike"):
        raise KeyError(f"not a spike point: {point!r}")
    if not _armed or not should_fire(point, step=step, rank=rank):
        return None
    cfg = point_cfg(point)
    if cfg.get("nonfinite"):
        return float("inf")
    return float(cfg.get("scale", 1000))


def exercised() -> set:
    """Point names that have fired at least once in this process."""
    return set(_exercised)


def fired_counts() -> Dict[str, int]:
    with _lock:
        return dict(_fired)


# -- hbm.* payloads (memory-pressure chaos, fault/memory.py consumers) -------
def hbm_oom_error(where: str):
    """Synthesize the error a real device OOM raises: an
    ``XlaRuntimeError`` carrying the ``RESOURCE_EXHAUSTED`` status text when
    the binding is constructible (it subclasses RuntimeError), else a plain
    RuntimeError with the same text — either way ``fault.memory.is_oom``
    classifies it exactly like the real thing."""
    msg = (
        f"RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        f"1073741824 bytes (injected hbm.oom at '{where}')"
    )
    try:
        from jaxlib.xla_extension import XlaRuntimeError

        return XlaRuntimeError(msg)
    except Exception:
        return RuntimeError(msg)


def maybe_hbm_oom(where: str, step: Optional[int] = None,
                  rank: Optional[int] = None) -> None:
    """Consult ``hbm.oom`` at a named dispatch site (``op=`` selects the
    site: ``lazy_flush`` / ``engine.step`` / ``engine.accum`` /
    ``serve.step``; ``at=``/``from=``/``step=``/``times=`` select the firing
    call). Raises the synthesized RESOURCE_EXHAUSTED *from the dispatch
    site*, so the OOM recovery ladder handles it exactly like a real one."""
    if _armed and should_fire("hbm.oom", step=step, op=where, rank=rank):
        raise hbm_oom_error(where)


def pressure_bytes() -> int:
    """Synthetic live-byte pressure (``hbm.pressure:bytes=N``), PERSISTENT
    while armed — pressure is a level, not an event, so the admission
    estimate reads the payload directly instead of consuming a
    ``should_fire`` count. 0 when unarmed or no ``bytes=`` payload."""
    if not _armed:
        return 0
    cfg = point_cfg("hbm.pressure")
    b = int(cfg.get("bytes", 0)) if cfg else 0
    if b:
        _exercised.add("hbm.pressure")
    return b


# -- tensor.nan payload ------------------------------------------------------
def poison_first_nan(res) -> bool:
    """Overwrite the first element of the first floating-point output of an
    op result (Tensor or list of Tensors) with NaN. Lazy-aware: under the
    lazy engine the poison is recorded as a graph node so the NaN is born
    INSIDE the fused flush — exactly the case the lazy-mode
    FLAGS_check_nan_inf guard exists for."""
    import jax.numpy as jnp

    from ..core import lazy as lazy_mod

    def pz(x):
        return jnp.reshape(jnp.ravel(x).at[0].set(jnp.nan), jnp.shape(x))

    ts = res if isinstance(res, (list, tuple)) else [res]
    for t in ts:
        d = getattr(t, "_data", None)
        if d is None or not hasattr(d, "dtype"):
            continue
        if not jnp.issubdtype(d.dtype, jnp.floating):
            continue
        if lazy_mod.is_lazy(d):
            (out,), _ = lazy_mod.record(
                "fault_inject_nan", pz, [d], key=("fault_inject_nan",)
            )
            t._data = out
        else:
            t._data = pz(d)
        return True
    return False


def _arm_from_env() -> None:
    spec = os.environ.get("PADDLE_FAULT_INJECT", "").strip()
    if spec:
        arm(spec)


_arm_from_env()

__all__ = [
    "POINTS", "InjectedFault", "arm", "disarm", "armed", "should_fire",
    "check", "exercised", "fired_counts", "poison_first_nan", "point_cfg",
    "chaos", "chaos_drop", "spike", "hbm_oom_error", "maybe_hbm_oom",
    "pressure_bytes",
]
