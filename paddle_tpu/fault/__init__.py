"""Fault tolerance — injection, retry, preemption drain.

The reference stack survives fleet conditions with layered machinery
(``FLAGS_check_nan_inf`` op scans, ``auto_checkpoint.py`` automatic resume,
``fleet/elastic/manager.py`` fault watch/relaunch) but has no systematic
fault-injection framework (SURVEY.md §2.4). This package is the TPU-native
fault layer that goes further:

* :mod:`~paddle_tpu.fault.inject` — deterministic, env/flag-addressable
  injection points (store op failure, checkpoint write failure, SIGTERM at
  step k, NaN into a named op's output) threaded through
  checkpoint/elastic/lazy, so crash-at-any-point behavior is testable.
* :mod:`~paddle_tpu.fault.retry` — shared retry-with-backoff helper wrapped
  around TCPStore ops, elastic heartbeats and checkpoint I/O; one transient
  store error no longer silently marks a worker dead.
* :mod:`~paddle_tpu.fault.preemption` — ``PreemptionGuard``: SIGTERM/SIGINT
  handlers that drain the pending lazy graph, force a final synchronous
  checkpoint and exit with :data:`RESUMABLE_EXIT_CODE`; the launcher and
  elastic supervisor treat that code as a clean restart.
* :mod:`~paddle_tpu.fault.sentinel` — ``StabilitySentinel``: statistical
  anomaly detection over per-step training signals (loss, global grad norm,
  update/param ratio, non-finite rate) with a skip → rollback → halt policy
  ladder, batch quarantine, and sample-exact auto-rollback to a pinned
  anchor checkpoint. Constructing a sentinel is the only thing that arms
  the per-flush drain tap; unconfigured training pays one attribute probe.
"""
from __future__ import annotations

from . import inject  # noqa: F401  (arms from PADDLE_FAULT_INJECT at import)
from . import retry  # noqa: F401
from .inject import InjectedFault  # noqa: F401
from .preemption import PreemptionGuard, RESUMABLE_EXIT_CODE  # noqa: F401
from .retry import retry_call, retrying  # noqa: F401
from .sentinel import (  # noqa: F401
    QuarantineLog, StabilityError, StabilitySentinel, StabilityVerdict,
)

__all__ = [
    "inject", "retry", "InjectedFault", "PreemptionGuard",
    "RESUMABLE_EXIT_CODE", "retry_call", "retrying",
    "QuarantineLog", "StabilityError", "StabilitySentinel", "StabilityVerdict",
]
