"""Preemption drain — survive SIGTERM with a resumable exit.

Reference parity: elastic training relies on the scheduler sending SIGTERM
before reclaiming a node (``fleet/elastic/manager.py`` watch/relaunch). Under
the lazy engine a naive handler is worse than useless: the pending graph
holds un-executed backward+optimizer work and donated input buffers, so dying
mid-flush loses a partially-applied step. ``PreemptionGuard`` drains the
pending lazy graph at a step boundary, forces a final synchronous checkpoint,
and exits with :data:`RESUMABLE_EXIT_CODE` — which the launcher and the
elastic supervisor treat as a clean restart rather than a failure.
"""
from __future__ import annotations

import signal
import sys
import threading
from typing import Callable, Optional

from .retry import _counter

# EX_TEMPFAIL: "temporary failure, retry later". Workers that drained cleanly
# exit with this; supervisors relaunch without consuming the failure budget.
RESUMABLE_EXIT_CODE = 75


class PreemptionGuard:
    """Install SIGTERM/SIGINT handlers; drain + checkpoint + resumable exit.

    Usage::

        ac = AutoCheckpoint(save_dir, interval_steps=100)
        with PreemptionGuard(checkpoint=ac) as guard:
            for step in range(start, steps):
                loss = train_step(...)
                ac.maybe_save(step, state)
                guard.check(step, state)   # drains + exits if preempted

    The handler only sets a flag — all real work (lazy flush, checkpoint
    write, exit) happens at the next ``check()`` call, i.e. at a step
    boundary where the state dict is consistent.
    """

    def __init__(
        self,
        checkpoint=None,
        signals=(signal.SIGTERM, signal.SIGINT),
        exit_code: int = RESUMABLE_EXIT_CODE,
        exit_fn: Callable[[int], None] = sys.exit,
    ):
        self.checkpoint = checkpoint
        self.signals = tuple(signals)
        self.exit_code = int(exit_code)
        self.exit_fn = exit_fn
        self._preempted = False
        self._signum: Optional[int] = None
        self._prev_handlers: dict = {}
        self._installed = False

    # -- signal plumbing ---------------------------------------------------
    def _handler(self, signum, frame):
        self._preempted = True
        self._signum = signum

    def install(self) -> "PreemptionGuard":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            # signal.signal raises off the main thread; degrade to a no-op
            # guard (check() still works when preempt() is called directly)
            return self
        for s in self.signals:
            try:
                self._prev_handlers[s] = signal.signal(s, self._handler)
            except (ValueError, OSError):
                pass
        self._installed = True
        return self

    def uninstall(self) -> None:
        for s, h in self._prev_handlers.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- step-boundary API -------------------------------------------------
    @property
    def preempted(self) -> bool:
        return self._preempted

    def preempt(self) -> None:
        """Mark the guard preempted without a real signal (tests, schedulers
        with their own notification channel)."""
        self._preempted = True

    def check(self, step: int, state_dict=None) -> bool:
        """Call once per completed step ``step``. Fires the
        ``preempt.sigterm`` injection point, and if a preemption signal has
        arrived: drains the lazy graph, writes a final synchronous checkpoint
        of ``state_dict`` at ``step``, and exits with the resumable code."""
        from . import inject

        if inject._armed and inject.should_fire("preempt.sigterm", step=step):
            signal.raise_signal(signal.SIGTERM)  # runs our handler inline
        if not self._preempted:
            return False
        self.drain(step, state_dict)
        self.exit_fn(self.exit_code)
        return True  # only reached when exit_fn returns (tests)

    def drain(self, step: Optional[int] = None, state_dict=None) -> None:
        """Flush the pending lazy graph, write a flight-recorder post-mortem
        (the preempted worker's last spans/counters survive the exit), and
        force a final synchronous checkpoint (bypasses the save interval and
        async mode)."""
        from ..core import lazy

        try:
            from ..distributed import watchdog

            # peers (and the post-mortem progress table) see this rank leave
            # through a drain, not silently stop stepping
            watchdog.publish(step=step, phase="preempt_drain", force=True)
        except Exception:
            pass
        lazy.flush()
        _counter("preemption_drains")
        try:
            from ..profiler import flight

            flight.dump(
                "preemption", extra={"step": step, "signum": self._signum}
            )
        except Exception:
            pass
        if self.checkpoint is not None and state_dict is not None and step is not None and step >= 0:
            self.checkpoint.save_now(step, state_dict, sync=True)


__all__ = ["PreemptionGuard", "RESUMABLE_EXIT_CODE"]
