"""paddle.callbacks — hapi training callbacks at the reference's top-level
path (python/paddle/callbacks.py re-exports hapi/callbacks.py)."""
from .hapi.callbacks import *  # noqa: F401,F403
from .hapi import callbacks as _cb

__all__ = getattr(_cb, "__all__", [n for n in dir(_cb) if not n.startswith("_")])
