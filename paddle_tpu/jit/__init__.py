"""paddle.jit — dygraph→static capture, compiled train steps, AOT save/load.

Parity: reference dygraph_to_static (``python/paddle/fluid/dygraph/
dygraph_to_static/program_translator.py:775`` ProgramTranslator,
``partial_program.py:116`` PartialProgramLayer) and ``paddle.jit.save/load``
(``python/paddle/fluid/dygraph/jit.py:630``).

TPU-native design: instead of AST rewriting into a ProgramDesc, capture runs
the Python forward once under JAX tracing — every paddle_tpu op is already a
pure JAX function, so the whole forward lowers to one XLA computation (the
LazyTensor insight; see PAPERS.md). The compiled executable is cached by
input shape/dtype, like the reference's program cache. ``save``/``load`` use
``jax.export`` StableHLO serialization — the analogue of saving a
ProgramDesc + params, but the artifact is an AOT-compilable module.
"""
from __future__ import annotations

import functools
import json
import os
import pickle
from typing import Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core import lazy as _lazy
from ..core import random as random_state
from ..core.compat import jax_export as _jax_export
from ..core.engine import GradNode, grad_enabled, no_grad
from ..core.tensor import Parameter, Tensor
from ..static.input import InputSpec


def _conc(a):
    """jax.jit arguments must be real buffers: materialize LazyArrays
    (lazy eager batching) before crossing into a compiled callable."""
    return _lazy.concrete(a)


def _tree_to_arrays(obj):
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_arrays(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_arrays(v) for k, v in obj.items()}
    return obj


def _tree_to_tensors(obj, stop_gradient=True):
    if isinstance(obj, jax.Array):
        return Tensor(obj, stop_gradient=stop_gradient)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_tensors(o, stop_gradient) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_tensors(v, stop_gradient) for k, v in obj.items()}
    return obj


class StaticFunction:
    """A callable whose forward is one cached XLA executable.

    Autograd: forward runs the jitted primal; if any input/param requires
    grad, a GradNode is recorded whose vjp is a second cached executable
    computing the fused forward+backward (XLA dedups the shared subgraph).
    """

    def __init__(self, function, layer=None, input_spec=None):
        self._fn = self._convert_control_flow(function)
        self._layer = layer
        self._input_spec = input_spec
        self._fwd_cache = {}
        self._bwd_cache = {}
        self._last_lowered = None

    @staticmethod
    def _convert_control_flow(function):
        """AST-convert tensor-dependent Python if/while into lax control flow
        (dy2static.py; reference program_translator.py:775). Functions whose
        source can't be rewritten keep trace-only capture."""
        import types as _types

        from . import dy2static

        raw = getattr(function, "__func__", function)
        transformed = dy2static.transform_function(raw)
        if transformed is None:
            return function
        if hasattr(function, "__self__"):
            return _types.MethodType(transformed, function.__self__)
        return transformed

    def program(self, *example_inputs):
        """Program view of the traced computation (reference
        StaticFunction.main_program / ProgramDesc introspection): blocks,
        ops, vars over the captured jaxpr."""
        from ..static.program import Program

        specs = list(example_inputs) or list(self._input_spec or [])
        if not specs:
            raise ValueError("program(): pass example inputs or set input_spec")
        return Program.from_callable(self._fn, specs, layer=self._layer)

    def _params_buffers(self):
        if self._layer is None:
            return [], []
        params = [p for _, p in self._layer.named_parameters()]
        buffers = [b for _, b in self._layer.named_buffers()]
        return params, buffers

    def _pure(self, n_params, n_buffers):
        fn = self._fn
        layer = self._layer

        def pure(args_tuple, key):
            param_arrays = args_tuple[:n_params]
            buffer_arrays = args_tuple[n_params : n_params + n_buffers]
            input_arrays = args_tuple[n_params + n_buffers :]
            params, buffers = self._params_buffers()
            saved = [(t, t._data) for t in list(params) + list(buffers)]
            try:
                for t, arr in zip(list(params) + list(buffers), list(param_arrays) + list(buffer_arrays)):
                    t._data = arr
                inputs = [Tensor(a, stop_gradient=True) for a in input_arrays]
                with random_state.traced_keys(key):
                    out = fn(*inputs) if layer is None else fn(*inputs)
                return _tree_to_arrays(out)
            finally:
                for t, arr in saved:
                    t._data = arr

        return pure

    def __call__(self, *args, **kwargs):
        params, buffers = self._params_buffers()
        input_arrays = [_conc(a._data) if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        all_arrays = tuple(_conc(p._data) for p in params) + tuple(_conc(b._data) for b in buffers) + tuple(input_arrays)
        key = random_state.next_key()
        shape_key = tuple((tuple(a.shape), str(a.dtype)) for a in all_arrays)

        n_p, n_b = len(params), len(buffers)
        pure = self._pure(n_p, n_b)

        training = self._layer.training if self._layer is not None else False
        cache_key = (shape_key, training)
        if cache_key not in self._fwd_cache:
            self._fwd_cache[cache_key] = jax.jit(pure)
        fwd = self._fwd_cache[cache_key]

        need_grad = grad_enabled() and any(not p.stop_gradient for p in params)
        outs = fwd(all_arrays, key)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        if not need_grad:
            result = [Tensor(o, stop_gradient=True) if isinstance(o, jax.Array) else o for o in out_list]
            return result[0] if single else result

        if cache_key not in self._bwd_cache:

            def bwd(arrays_tuple, cts, bwd_key):
                _, vjp_fn = jax.vjp(lambda a: pure(a, bwd_key), arrays_tuple)
                (grads,) = vjp_fn(cts)
                return grads

            self._bwd_cache[cache_key] = jax.jit(bwd)
        bwd = self._bwd_cache[cache_key]

        tensor_inputs = list(params) + list(buffers) + [
            a for a in args if isinstance(a, Tensor)
        ]
        # only params/buffers/inputs that are Tensors get routes; held arrays order = all_arrays
        input_tensors = []
        for a in args:
            input_tensors.append(a if isinstance(a, Tensor) else Tensor(np.asarray(a)))
        graph_inputs = list(params) + list(buffers) + input_tensors

        def vjp_fn(cts):
            if single:
                cts_tree = _conc(cts)
            else:
                cts_tree = tuple(_conc(c) for c in cts)
            grads = bwd(all_arrays, cts_tree, key)
            return tuple(grads)

        routes = []
        for t in graph_inputs:
            if t.stop_gradient:
                routes.append(None)
            elif t._grad_node is not None:
                routes.append(("node", t._grad_node, t._out_index))
            else:
                routes.append(("leaf", t))
        out_avals = [(tuple(o.shape), o.dtype) for o in out_list]
        node = GradNode("jit_fn", vjp_fn, routes, out_avals, multi=not single)
        import weakref

        outs_t, refs = [], []
        for i, o in enumerate(out_list):
            t = Tensor(o, stop_gradient=False)
            t._grad_node = node
            t._out_index = i
            refs.append(weakref.ref(t))
            outs_t.append(t)
        node.out_tensors = refs
        return outs_t[0] if single else outs_t

    # -- introspection -----------------------------------------------------
    def concrete_program(self, *args):
        params, buffers = self._params_buffers()
        input_arrays = [_conc(a._data) if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        all_arrays = tuple(_conc(p._data) for p in params) + tuple(_conc(b._data) for b in buffers) + tuple(input_arrays)
        pure = self._pure(len(params), len(buffers))
        return jax.jit(pure).lower(all_arrays, jax.random.PRNGKey(0))


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator/wrapper (reference ``paddle.jit.to_static`` / ``declarative``)."""

    def decorate(fn):
        from ..nn.layer.layers import Layer

        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, layer=fn, input_spec=input_spec)
            fn.forward = sf
            return fn
        if hasattr(fn, "__self__") and isinstance(fn.__self__, Layer):
            return StaticFunction(fn, layer=fn.__self__, input_spec=input_spec)
        return StaticFunction(fn, layer=None, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


def enable_to_static(flag=True):
    pass


# ---------------------------------------------------------------------------
# Compiled train step — the TPU-idiomatic hot loop
# ---------------------------------------------------------------------------
class CompiledTrainStep:
    """Compile (params, opt_state, batch) → (loss, params, opt_state) into ONE
    XLA executable: forward + backward + optimizer update, fully fused.

    This replaces the reference's per-op executor hot loop
    (``paddle/fluid/framework/executor.cc:297``) with a single compiled
    program — the architectural answer to TPU dispatch latency.
    """

    def __init__(self, model, loss_fn, optimizer, donate=True):
        from ..optimizer import Optimizer

        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.params = [p for p in model.parameters() if not p.stop_gradient]
        self.buffers = list(model.buffers())
        self._jit = None
        self._opt_state_keys = None
        self._donate = donate

    def _build(self):
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        params, buffers = self.params, self.buffers
        opt = optimizer

        def step_fn(param_arrays, opt_state, batch_arrays, lr, key):
            def loss_of(params_arrays):
                saved = [(t, t._data) for t in params + buffers]
                try:
                    for t, a in zip(params, params_arrays):
                        t._data = a
                    inputs = [Tensor(a, stop_gradient=True) for a in batch_arrays]
                    with random_state.traced_keys(key):
                        with no_grad():
                            out = loss_fn(model, *inputs)
                    return out._data if isinstance(out, Tensor) else out
                finally:
                    for t, a in saved:
                        t._data = a

            loss, grads = jax.value_and_grad(loss_of)(list(param_arrays))
            new_params, new_state = opt._functional_update(param_arrays, grads, opt_state, lr)
            return loss, new_params, new_state

        donate = (0, 1) if self._donate else ()
        self._jit = jax.jit(step_fn, donate_argnums=donate)

    def __call__(self, *batch):
        from ..profiler import spans as _spans

        with _spans.span("train_step", kind="jit"):
            return self._call_impl(*batch)

    def _call_impl(self, *batch):
        if self._jit is None:
            self._build()
        batch_arrays = tuple(_conc(b._data) if isinstance(b, Tensor) else jnp.asarray(b) for b in batch)
        param_arrays = [_conc(p._data) for p in self.params]
        opt_state = self.optimizer._functional_state(self.params)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = random_state.next_key()
        loss, new_params, new_state = self._jit(param_arrays, opt_state, batch_arrays, lr, key)
        for p, a in zip(self.params, new_params):
            p._set_data(a)
        self.optimizer._functional_restore(self.params, new_state)
        self.optimizer._step_count += 1
        return Tensor(loss)


def compile_train_step(model, loss_fn, optimizer):
    return CompiledTrainStep(model, loss_fn, optimizer)


# ---------------------------------------------------------------------------
# save / load — AOT StableHLO artifacts
# ---------------------------------------------------------------------------
def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save: serialize an inference program + params.

    Artifact layout: ``{path}.pdmodel`` = jax.export StableHLO bytes;
    ``{path}.pdiparams`` = pickled numpy state dict (cf. reference
    save_inference_model: __model__ + params).
    """
    from ..nn.layer.layers import Layer

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)

    fn = layer.forward if isinstance(layer, Layer) else layer
    if isinstance(fn, StaticFunction):
        inner_layer = fn._layer
        raw_fn = fn._fn
    else:
        inner_layer = layer if isinstance(layer, Layer) else None
        raw_fn = fn

    if input_spec is None and isinstance(fn, StaticFunction):
        input_spec = fn._input_spec
    if input_spec is None:
        raise ValueError("paddle_tpu.jit.save requires input_spec")

    specs = [
        s if isinstance(s, InputSpec) else InputSpec.from_tensor(s) for s in input_spec
    ]
    if inner_layer is not None:
        inner_layer.eval()
        params = [p for _, p in inner_layer.named_parameters()]
        buffers = [b for _, b in inner_layer.named_buffers()]
        named_state = list(inner_layer.state_dict().items())
    else:
        params, buffers, named_state = [], [], []

    def pure(*input_arrays):
        saved = [(t, t._data) for t in params + buffers]
        try:
            inputs = [Tensor(a, stop_gradient=True) for a in input_arrays]
            with random_state.traced_keys(jax.random.PRNGKey(0)):
                with no_grad():
                    out = raw_fn(*inputs)
            return _tree_to_arrays(out)
        finally:
            for t, a in saved:
                t._data = a

    # Dynamic dims (None/-1) export as symbolic shapes so the reloaded
    # artifact accepts any size there (reference save_inference_model keeps
    # dynamic batch). One shared scope across all inputs.
    has_dynamic = any(d is None or d == -1 for s in specs for d in s.shape)
    if has_dynamic:
        scope = _jax_export().SymbolicScope()
        args = []
        for si, s in enumerate(specs):
            dims = ",".join(
                f"d{si}_{di}" if (d is None or d == -1) else str(d)
                for di, d in enumerate(s.shape)
            )
            shape = _jax_export().symbolic_shape(dims, scope=scope) if dims else ()
            args.append(jax.ShapeDtypeStruct(shape, s.dtype))
    else:
        args = [jax.ShapeDtypeStruct(tuple(s.shape), s.dtype) for s in specs]
    def _export(arg_list):
        # multi-platform so a TPU-saved artifact deploys on CPU hosts too
        # (Config.disable_gpu / CPU-only serving); ops without a multi-
        # platform lowering (e.g. Pallas kernels) fall back to native-only
        try:
            return _jax_export().export(jax.jit(pure), platforms=("cpu", "tpu"))(*arg_list)
        except Exception:
            # no multi-platform lowering (e.g. Pallas kernels): retry native-
            # only; a second failure chains the original via __context__
            return _jax_export().export(jax.jit(pure))(*arg_list)

    try:
        exported = _export(args)
    except Exception:
        if not has_dynamic:
            raise
        # some ops aren't shape-polymorphic: fall back to a static export at
        # size 1 for the dynamic dims (pre-existing behavior)
        args = [
            jax.ShapeDtypeStruct(
                tuple(abs(d) if d is not None and d != -1 else 1 for d in s.shape), s.dtype
            )
            for s in specs
        ]
        exported = _export(args)
    from ..framework.io import atomic_open

    with atomic_open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    state = {k: np.asarray(v._data) for k, v in named_state}
    from ..framework.io import save as fsave

    fsave({"state": {k: Tensor(v) for k, v in state.items()}, "specs": [(list(s.shape), str(np.dtype(s.dtype)), s.name) for s in specs]}, path + ".pdiparams")

    # Trainable companion artifact: the same program exported with PARAMS AS
    # ARGUMENTS and a serialized VJP, so load→append-loss→train works without
    # the original python model (reference programs are data: append_backward
    # runs on a loaded ProgramDesc, python/paddle/fluid/backward.py:1413).
    # Buffers (BN stats, …) stay baked — finetune freezes them, like eval-mode
    # finetuning on a loaded inference program.
    if inner_layer is not None and params:
        named_params = list(inner_layer.named_parameters())
        p_names = [n for n, _ in named_params]
        p_list = [p for _, p in named_params]

        def pure_train(param_arrays, *input_arrays):
            saved = [(t, t._data) for t in p_list + buffers]
            try:
                for t, a in zip(p_list, param_arrays):
                    t._data = a
                inputs = [Tensor(a, stop_gradient=True) for a in input_arrays]
                with random_state.traced_keys(jax.random.PRNGKey(0)):
                    with no_grad():
                        out = raw_fn(*inputs)
                return _tree_to_arrays(out)
            finally:
                for t, a in saved:
                    t._data = a

        static_args = [
            jax.ShapeDtypeStruct(
                tuple(abs(d) if d is not None and d != -1 else 1 for d in s.shape),
                s.dtype,
            )
            for s in specs
        ]
        p_args = [jax.ShapeDtypeStruct(tuple(p.shape), p.dtype) for p in p_list]
        try:
            try:
                # same (possibly symbolic) feed shapes as the primal export,
                # so load→append_backward→train works at any batch size
                exp_train = _jax_export().export(jax.jit(pure_train))(p_args, *args)
            except Exception:
                # vjp not shape-polymorphic for some op: static fallback
                exp_train = _jax_export().export(jax.jit(pure_train))(p_args, *static_args)
            with atomic_open(path + ".pdtrain", "wb") as f:
                f.write(exp_train.serialize(vjp_order=1))
            with atomic_open(path + ".pdtrain.json", "w") as f:
                json.dump({"param_names": p_names}, f)
        except Exception:
            # not exportable with vjp (e.g. non-differentiable custom calls):
            # the inference artifact above is still complete
            for suffix in (".pdtrain", ".pdtrain.json"):
                if os.path.exists(path + suffix):
                    os.remove(path + suffix)


class TranslatedLayer:
    """Reloaded AOT program (reference dygraph/io.py TranslatedLayer)."""

    def __init__(self, exported, state, specs):
        self._exported = exported
        self._state = state
        self._specs = specs
        self.training = False

    def __call__(self, *args):
        arrays = [_conc(a._data) if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        outs = self._exported.call(*arrays)
        return _tree_to_tensors(outs)

    forward = __call__

    def eval(self):
        self.training = False
        return self

    def state_dict(self):
        return self._state


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        blob = f.read()
    exported = _jax_export().deserialize(blob)
    from ..framework.io import load as fload

    meta = fload(path + ".pdiparams")
    return TranslatedLayer(exported, meta["state"], meta["specs"])
