"""dygraph→static control-flow conversion (AST transform).

Parity: reference ``python/paddle/fluid/dygraph/dygraph_to_static/`` —
``program_translator.py:775`` (ProgramTranslator), ``ifelse_transformer.py``,
``loop_transformer.py``, ``logical_transformer.py``. Those rewrite
tensor-dependent Python ``if``/``while``/``for`` into ``cond``/``while`` ops
over sub-blocks; here the same source rewrite targets ``lax.cond`` /
``lax.while_loop`` through ``ops/control_flow.py``, so a ``@to_static``
function with data-dependent branches compiles to real XLA control flow.

Pipeline: ``transform_function(fn)`` grabs the source, rewrites

    if <t-pred>: A else: B        →  _jst.convert_ifelse(pred, tf, ff, vars)
    while <t-pred>: BODY          →  _jst.convert_while(cond_fn, body_fn, vars)
    for i in range(<t-bound>):    →  while-style fori loop
    a and b / a or b / not a      →  _jst.convert_logical_*

and compiles the new AST in the original function's globals (closure
variables are materialized into that namespace). The convert_* helpers pick
the path at runtime: concrete predicate → plain Python; traced tensor
predicate → lax control flow. Functions whose source can't be transformed
fall back to trace-only capture (the previous behavior).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
import types
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["transform_function", "convert_ifelse", "convert_while", "convert_logical_and",
           "convert_logical_or", "convert_logical_not", "UNDEF"]


class _Undef:
    __slots__ = ()

    def __repr__(self):
        return "<UNDEF>"


UNDEF = _Undef()


def _is_traced_tensor(x) -> bool:
    return isinstance(x, Tensor) and isinstance(x._data, jax.core.Tracer)


def _pred_value(pred):
    """→ (is_traced, concrete_bool_or_None)."""
    if _is_traced_tensor(pred):
        return True, None
    if isinstance(pred, Tensor):
        return False, bool(pred._data.reshape(()) if hasattr(pred._data, "reshape") else pred._data)
    if isinstance(pred, jax.core.Tracer):
        return True, None
    return False, bool(pred)


# -- runtime converters ------------------------------------------------------
def convert_ifelse(pred, true_fn: Callable, false_fn: Callable, vars_tuple: tuple):
    traced, val = _pred_value(pred)
    if not traced:
        return true_fn(*vars_tuple) if val else false_fn(*vars_tuple)

    from ..ops.control_flow import cond as _cond

    # vars pass through the branch CLOSURES (not lax operands), so an UNDEF
    # placeholder is fine as long as both branches assign it before use —
    # lax.cond only requires the RETURNED structures to match
    try:
        return _cond(pred, lambda: true_fn(*vars_tuple), lambda: false_fn(*vars_tuple))
    except TypeError as e:
        raise ValueError(
            "to_static: both branches of a tensor-dependent `if` must produce "
            "the same variables with matching shapes/dtypes (lax.cond "
            f"structure mismatch: {e})"
        ) from None


def convert_while(cond_fn: Callable, body_fn: Callable, vars_tuple: tuple):
    # probe the predicate on the current values
    probe = cond_fn(*vars_tuple)
    traced, _ = _pred_value(probe)
    if not traced and not any(_is_traced_tensor(v) for v in vars_tuple):
        while bool(cond_fn(*vars_tuple)):
            out = body_fn(*vars_tuple)
            vars_tuple = out if isinstance(out, tuple) else (out,)
        return vars_tuple

    from ..ops.control_flow import while_loop as _while

    if any(v is UNDEF for v in vars_tuple):
        raise ValueError(
            "to_static: every loop variable of a tensor-dependent `while` "
            "must be defined before the loop (shape-stable lax carry)"
        )
    out = _while(lambda *vs: cond_fn(*vs), lambda *vs: body_fn(*vs), list(vars_tuple))
    return tuple(out)


def convert_logical_and(a_fn, b_fn):
    a = a_fn()
    if isinstance(a, Tensor) or isinstance(a, jax.core.Tracer):
        b = b_fn()
        from ..ops.math import logical_and as _land

        return _land(a, b)
    return a and b_fn()


def convert_logical_or(a_fn, b_fn):
    a = a_fn()
    if isinstance(a, Tensor) or isinstance(a, jax.core.Tracer):
        b = b_fn()
        from ..ops.math import logical_or as _lor

        return _lor(a, b)
    return a or b_fn()


def convert_logical_not(a):
    if isinstance(a, Tensor) or isinstance(a, jax.core.Tracer):
        from ..ops.math import logical_not as _lnot

        return _lnot(a)
    return not a


# -- AST analysis ------------------------------------------------------------
class _AssignedNames(ast.NodeVisitor):
    """Names bound by statements (stores, augassign, for targets, with-as).
    Nested function defs and transformer-generated ``__jst_*`` temporaries
    are NOT user variables and never join a lax carry."""

    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)) and not node.id.startswith("__jst_"):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        pass  # helper defs are branch-local; don't descend

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _assigned(stmts) -> set:
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _LoadedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)


def _loaded(node_or_stmts) -> set:
    v = _LoadedNames()
    if isinstance(node_or_stmts, list):
        for s in node_or_stmts:
            v.visit(s)
    else:
        v.visit(node_or_stmts)
    return v.names


def _contains_return(stmts) -> bool:
    """Return/break/continue/yield at THIS function's level (nested function
    definitions — including ones this transformer generated — don't count)."""

    def scan(node) -> bool:
        if isinstance(node, (ast.Return, ast.Break, ast.Continue, ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        return any(scan(c) for c in ast.iter_child_nodes(node))

    return any(scan(s) for s in stmts)


# -- AST transformer ---------------------------------------------------------
class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites if/while/for/boolop inside ONE function body."""

    def __init__(self):
        self.counter = 0
        self.changed = False

    def _fresh(self, base):
        self.counter += 1
        return f"__jst_{base}_{self.counter}"

    def visit_If(self, node: ast.If):
        node = self.generic_visit(node)
        if not _tensor_likely(node.test):
            return node
        if _contains_return(node.body) or _contains_return(node.orelse):
            # return/break inside a tensor branch can't become lax.cond;
            # leave as-is (concrete predicates still work at runtime)
            return node
        carried = sorted(_assigned(node.body) | _assigned(node.orelse))
        self.changed = True
        tf, ff, out = self._fresh("true"), self._fresh("false"), self._fresh("ifout")
        args = ", ".join(carried)
        ret = ("return (" + ", ".join(carried) + ("," if len(carried) == 1 else "") + ")") if carried else "return ()"

        def mk_branch(name, stmts):
            f = ast.parse(f"def {name}({args}):\n    pass").body[0]
            f.body = (list(stmts) if stmts else []) + ast.parse(ret).body
            return f

        true_def = mk_branch(tf, node.body)
        false_def = mk_branch(ff, node.orelse)
        call_src = (
            f"{out} = _jst.convert_ifelse(__jst_pred, {tf}, {ff}, ({args}{',' if len(carried)==1 else ''}))"
            if carried
            else f"{out} = _jst.convert_ifelse(__jst_pred, {tf}, {ff}, ())"
        )
        pred_assign = ast.parse("__jst_pred = 0").body[0]
        pred_assign.value = node.test
        unpack = []
        if carried:
            unpack = ast.parse(f"{', '.join(carried)}{',' if len(carried)==1 else ''} = {out}").body
        prelude = []
        for n in carried:
            prelude.extend(ast.parse(
                f"try:\n    {n} = {n}\nexcept (NameError, UnboundLocalError):\n    {n} = _jst.UNDEF"
            ).body)
        new = prelude + [pred_assign, true_def, false_def] + ast.parse(call_src).body + unpack
        for s in new:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return new

    def visit_While(self, node: ast.While):
        node = self.generic_visit(node)
        if node.orelse or _contains_return(node.body):
            return node
        if not _tensor_likely(node.test):
            return node
        carried = sorted(_assigned(node.body))  # every assigned name is carried
        # names read by cond/body but never assigned are closed over naturally
        cf, bf, out = self._fresh("cond"), self._fresh("body"), self._fresh("whout")
        args = ", ".join(carried)
        if not carried:
            return node  # a while that binds nothing can't make progress via lax
        self.changed = True
        ret = "return (" + ", ".join(carried) + ("," if len(carried) == 1 else "") + ")"
        cond_def = ast.parse(f"def {cf}({args}):\n    pass").body[0]
        cond_ret = ast.parse("return 0").body[0]
        cond_ret.value = node.test
        cond_def.body = [cond_ret]
        body_def = ast.parse(f"def {bf}({args}):\n    pass").body[0]
        body_def.body = list(node.body) + ast.parse(ret).body
        call = ast.parse(
            f"{out} = _jst.convert_while({cf}, {bf}, ({args}{',' if len(carried)==1 else ''}))"
        ).body
        unpack = ast.parse(f"{', '.join(carried)}{',' if len(carried)==1 else ''} = {out}").body
        prelude = []
        for n in carried:
            prelude.extend(ast.parse(
                f"try:\n    {n} = {n}\nexcept (NameError, UnboundLocalError):\n    {n} = _jst.UNDEF"
            ).body)
        new = prelude + [cond_def, body_def] + call + unpack
        for s in new:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return new

    def visit_For(self, node: ast.For):
        node = self.generic_visit(node)
        # `for <name> in range(...)` with a possibly-tensor bound → counter
        # while-loop (then visit_While's machinery applies at runtime via
        # convert_while). Other iterables keep Python iteration.
        def _const_step(a):
            if isinstance(a, ast.Constant) and isinstance(a.value, int):
                return a.value
            if (
                isinstance(a, ast.UnaryOp)
                and isinstance(a.op, ast.USub)
                and isinstance(a.operand, ast.Constant)
                and isinstance(a.operand.value, int)
            ):
                return -a.operand.value
            return None

        args = node.iter.args
        if (
            node.orelse
            or _contains_return(node.body)
            or not isinstance(node.iter, ast.Call)
            or not isinstance(node.iter.func, ast.Name)
            or node.iter.func.id != "range"
            or not isinstance(node.target, ast.Name)
            or node.iter.keywords
            or not 1 <= len(args) <= 3
            or not any(_tensor_likely(a) for a in args)
            # step must be a POSITIVE literal (or absent): `i < stop` is only
            # correct then; negative/dynamic steps keep Python iteration
            or (len(args) == 3 and (_const_step(args[2]) is None or _const_step(args[2]) <= 0))
        ):
            return node
        i = node.target.id
        stop = self._fresh("stop")
        # loop counter is a SEPARATE carried variable so the user target
        # keeps Python for-semantics (last executed value, not one-past-end)
        cnt = self._fresh("cnt").replace("__jst_", "__for_")
        step_lit = _const_step(args[2]) if len(args) == 3 else 1

        pre = []
        init = ast.parse(f"{cnt} = 0").body[0]
        if len(args) >= 2:
            init.value = args[0]
        stop_assign = ast.parse(f"{stop} = 0").body[0]
        stop_assign.value = args[0] if len(args) == 1 else args[1]
        pre += [init, stop_assign]
        # the user target needs a defined init for the lax carry; zero-trip
        # loops leave it at start (Python would leave it unbound — accepted
        # deviation, same as the reference's loop transformer)
        pre += ast.parse(f"{i} = {cnt}").body
        wh = ast.parse(f"while {cnt} < {stop}:\n    pass").body[0]
        wh.body = (
            ast.parse(f"{i} = {cnt}").body
            + list(node.body)
            + ast.parse(f"{cnt} = {cnt} + {step_lit}").body
        )
        converted = self.visit_While(wh)
        out = pre + _as_list(converted)
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    def visit_BoolOp(self, node: ast.BoolOp):
        node = self.generic_visit(node)
        if not any(_tensor_likely(v) for v in node.values):
            return node
        self.changed = True
        fn = "convert_logical_and" if isinstance(node.op, ast.And) else "convert_logical_or"
        expr = node.values[-1]
        for prev in reversed(node.values[:-1]):
            lam_a = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=prev,
            )
            lam_b = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=expr,
            )
            expr = ast.Call(
                func=ast.Attribute(value=ast.Name(id="_jst", ctx=ast.Load()), attr=fn, ctx=ast.Load()),
                args=[lam_a, lam_b],
                keywords=[],
            )
        ast.copy_location(expr, node)
        ast.fix_missing_locations(expr)
        return expr

    def visit_UnaryOp(self, node: ast.UnaryOp):
        node = self.generic_visit(node)
        if isinstance(node.op, ast.Not) and _tensor_likely(node.operand):
            self.changed = True
            call = ast.Call(
                func=ast.Attribute(value=ast.Name(id="_jst", ctx=ast.Load()), attr="convert_logical_not", ctx=ast.Load()),
                args=[node.operand],
                keywords=[],
            )
            ast.copy_location(call, node)
            ast.fix_missing_locations(call)
            return call
        return node


def _tensor_likely(expr) -> bool:
    """Static heuristic: could this predicate be a Tensor? Comparisons over
    names/calls/attributes → yes; pure literal/constant arithmetic → no.
    False negatives only skip conversion (python path still correct for
    concrete values); false positives cost one runtime type check."""
    for n in ast.walk(expr):
        if isinstance(n, (ast.Name, ast.Call, ast.Attribute, ast.Subscript)):
            return True
    return False


def transform_function(fn):
    """Return fn with tensor control flow converted, or None if the source
    can't be transformed (lambda, builtins, C extensions, exotic closures)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None

    if getattr(fn, "_not_to_static", False):
        return None  # explicitly opted out of AST conversion

    def _is_jit_decorator(d):
        # strip only our own entry points (@to_static / @paddle.jit.to_static,
        # possibly called with options); anything else (functools.wraps, user
        # wrappers, @not_to_static) would be silently dropped — and
        # @not_to_static in particular means the OPPOSITE of convert-me
        target = d.func if isinstance(d, ast.Call) else d
        name = target.attr if isinstance(target, ast.Attribute) else (
            target.id if isinstance(target, ast.Name) else None
        )
        return name in ("to_static", "declarative")

    kept = [d for d in fdef.decorator_list if not _is_jit_decorator(d)]
    if kept:
        return None  # unknown decorators: fall back to trace-only capture
    fdef.decorator_list = []  # run undecorated
    tr = _ControlFlowTransformer()
    fdef.body = [s2 for s in fdef.body for s2 in _as_list(tr.visit(s))]
    ast.fix_missing_locations(tree)
    if not tr.changed:
        # nothing converted: keep the ORIGINAL function (live globals, no
        # snapshot semantics for plain trace-only capture)
        return None

    glb = dict(fn.__globals__)
    from . import dy2static as _jst_mod

    glb["_jst"] = _jst_mod
    # materialize closure variables into the exec namespace
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents  # closure OVERRIDES a same-named global
            except ValueError:
                pass
    try:
        code = compile(tree, filename=f"<to_static {fn.__name__}>", mode="exec")
        ns: dict = {}
        exec(code, glb, ns)
        new_fn = ns[fdef.name]
    except Exception:
        return None
    new_fn.__wrapped_original__ = fn
    return new_fn


def _as_list(x):
    return x if isinstance(x, list) else [x]
