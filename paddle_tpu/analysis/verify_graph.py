"""Lazy-graph IR verifier (``FLAGS_lazy_verify``).

A structural pass over the pending ``_Graph`` in ``core/lazy.py`` run
immediately before dispatch. The graph's wiring descriptors, leaf table and
cache-signature parts are built INCREMENTALLY at record time (PR 6) — fast,
but a single bookkeeping slip there turns into a wrong executable served
from the flush cache or a donated-and-still-referenced buffer, i.e. silent
corruption or a nondeterministic crash far from the bug. This pass
re-derives every incremental structure from ground truth (the nodes and
their live input objects) and cross-checks:

* **acyclicity / topological wiring** — every ``("n", gix, out_ix)``
  descriptor references a STRICTLY EARLIER node (the graph is append-only;
  a forward or self reference is a cycle) and ``out_ix < nodes[gix].n_out``;
* **leaf-table consistency** — ``leaves`` / ``leaf_pos`` / ``leaf_avals``
  agree, every ``("l", j)`` descriptor is in range, and ``direct_uses``
  matches an actual recount of leaf occurrences (the donation mask's
  refcount budget is built from it);
* **donation-mask soundness** — every donated leaf index is a live,
  non-deleted ``jax.Array`` and the frame-isolated refcount test still
  proves it dead (nothing outside the graph references it); a donated leaf
  that a user alias still reaches would be destroyed under them;
* **signature determinism** — the cache signature re-derived from the wired
  graph equals the incrementally-memoized one (``keyparts`` +
  ``leaf_avals``), so the executable cache can never serve a stale program;
* **deferred-check bookkeeping** — entries queued for the async runtime's
  off-critical-path NaN scan / memory census are well-formed.

Violations raise :class:`GraphInvariantError` naming the offending node
(index + op name) and rule. The disabled path costs one flag probe per
flush (pinned by a tier-1 tripwire + ``bench_verify_overhead``).
"""
from __future__ import annotations

from typing import Optional, Sequence

__all__ = ["GraphInvariantError", "verify_before_dispatch", "verify_graph"]


class GraphInvariantError(RuntimeError):
    """A lazy-graph structural invariant does not hold. Carries the rule
    name and (when attributable) the offending node's index and op name so
    tests and post-mortems can pin the exact corruption."""

    def __init__(self, rule: str, message: str,
                 node_index: Optional[int] = None,
                 op_name: Optional[str] = None):
        loc = ""
        if node_index is not None:
            loc = f" [node {node_index}" + (f" ({op_name})" if op_name else "") + "]"
        super().__init__(f"lazy-graph invariant violated: {rule}{loc}: {message}")
        self.rule = rule
        self.node_index = node_index
        self.op_name = op_name


def _fail(rule, message, node_index=None, op_name=None):
    raise GraphInvariantError(rule, message, node_index, op_name)


def _op_name(node) -> str:
    try:
        return str(node.key[0])
    except Exception:
        return "?"


def verify_graph(g) -> None:
    """Check the wiring/leaf-table/signature invariants of a pending
    ``_Graph`` (donation and deferred state are flush-scoped — see
    :func:`verify_before_dispatch` for the full pre-dispatch pass)."""
    from ..core import lazy as lazy_mod

    nodes = g.nodes
    n_nodes = len(nodes)
    leaves = g.leaves
    n_leaves = len(leaves)

    if not (len(g.descs) == len(g.keyparts) == n_nodes):
        _fail(
            "wiring",
            f"per-node tables out of step: {n_nodes} nodes, "
            f"{len(g.descs)} descriptors, {len(g.keyparts)} signature parts",
        )
    if not (len(g.leaf_avals) == n_leaves == len(g.leaf_pos)):
        _fail(
            "leaf-table",
            f"{n_leaves} leaves vs {len(g.leaf_avals)} leaf avals vs "
            f"{len(g.leaf_pos)} leaf positions",
        )
    for j in range(n_leaves):
        if g.leaf_pos.get(id(leaves[j])) != j:
            _fail(
                "leaf-table",
                f"leaf {j} is not indexed at its own position "
                f"(leaf_pos says {g.leaf_pos.get(id(leaves[j]))!r})",
            )

    recount: dict = {}
    for i, node in enumerate(nodes):
        name = _op_name(node)
        if node.gix != i:
            _fail("wiring", f"node.gix={node.gix} disagrees with position", i, name)
        if node.graph is not g:
            _fail("wiring", "node does not belong to this graph epoch", i, name)
        if node.out_refs is None or len(node.out_refs) != node.n_out:
            _fail(
                "wiring",
                f"{0 if node.out_refs is None else len(node.out_refs)} output "
                f"refs for n_out={node.n_out}", i, name,
            )
        descs = g.descs[i]
        inputs = node.inputs
        if len(descs) != len(inputs):
            _fail(
                "wiring",
                f"{len(descs)} descriptors for {len(inputs)} inputs", i, name,
            )
        for d, x in zip(descs, inputs):
            if d[0] == "n":
                _, gix, out_ix = d
                if not (0 <= gix < i):
                    _fail(
                        "acyclicity",
                        f"input references node {gix} — not strictly earlier "
                        "in the append-only order (cycle or dangling wire)",
                        i, name,
                    )
                if not (0 <= out_ix < nodes[gix].n_out):
                    _fail(
                        "wiring",
                        f"input output-index {out_ix} out of range for node "
                        f"{gix} (n_out={nodes[gix].n_out})", i, name,
                    )
                if not (isinstance(x, lazy_mod.LazyArray) and x._concrete is None):
                    _fail(
                        "wiring",
                        f"descriptor says node-output {gix}:{out_ix} but the "
                        "stored input is not a pending LazyArray", i, name,
                    )
                if x._node is not nodes[gix] or x._idx != out_ix:
                    _fail(
                        "wiring",
                        f"pending input wired to node {gix}:{out_ix} but the "
                        "LazyArray points elsewhere", i, name,
                    )
            elif d[0] == "l":
                j = d[1]
                if not (0 <= j < n_leaves):
                    _fail(
                        "leaf-table",
                        f"input references leaf {j} of {n_leaves} (dangling leaf)",
                        i, name,
                    )
                if leaves[j] is not x:
                    _fail(
                        "leaf-table",
                        f"leaf {j} in the table is not the object this node "
                        "recorded as its input", i, name,
                    )
                recount[id(x)] = recount.get(id(x), 0) + 1
            else:
                _fail("wiring", f"unknown descriptor kind {d[0]!r}", i, name)

    tracked = {k: v for k, v in g.direct_uses.items() if v}
    if recount != tracked:
        bad = next(
            i for i in (set(recount) | set(tracked))
            if recount.get(i, 0) != tracked.get(i, 0)
        )
        jx = next(
            (j for j in range(n_leaves) if id(leaves[j]) == bad), None
        )
        _fail(
            "leaf-table",
            f"direct_uses for leaf {'?' if jx is None else jx} says "
            f"{tracked.get(bad, 0)} occurrence(s) but a recount of the "
            f"wiring gives {recount.get(bad, 0)} — the donation refcount "
            "budget would be wrong",
        )

    # signature determinism: re-derive what record() memoized incrementally
    for i, node in enumerate(nodes):
        if g.keyparts[i] != (node.key, tuple(g.descs[i])):
            _fail(
                "signature",
                "memoized signature part disagrees with the wired graph — "
                "the flush cache would key this program incorrectly",
                i, _op_name(node),
            )
    for j in range(n_leaves):
        if g.leaf_avals[j] != lazy_mod._leaf_sig(leaves[j]):
            _fail(
                "signature",
                f"memoized aval for leaf {j} disagrees with the live leaf "
                f"({g.leaf_avals[j]!r} vs {lazy_mod._leaf_sig(leaves[j])!r})",
            )


def _verify_donation(g, donate_ix: Sequence[int]) -> None:
    """The donation mask must only name leaves that are provably dead after
    this flush. Re-runs the frame-isolated refcount test from the live
    tables; a donated leaf that is still user-referenced (or that is not a
    real device buffer) fails here instead of being destroyed under the
    holder."""
    import jax

    from ..core import lazy as lazy_mod

    leaves = g.leaves
    for j in donate_ix:
        if not (0 <= j < len(leaves)):
            _fail("donation", f"donated leaf index {j} of {len(leaves)}")
        x = leaves[j]
        if not isinstance(x, jax.Array):
            _fail("donation", f"donated leaf {j} is not a jax.Array ({type(x).__name__})")
        try:
            if x.is_deleted():
                _fail("donation", f"donated leaf {j} is already deleted")
        except AttributeError:
            pass
        # a donated leaf that a pending node ALSO consumes is fine (one
        # executable, XLA schedules the read before the alias) — but its
        # only remaining owners must be the graph's own input lists, which
        # the frame-isolated refcount recheck below proves
        x = None
    if donate_ix:
        recheck = lazy_mod._donation_mask(
            leaves, {id(leaves[j]) for j in donate_ix}, g.direct_uses
        )
        stale = set(donate_ix) - set(recheck)
        if stale:
            j = sorted(stale)[0]
            _fail(
                "donation",
                f"leaf {j} is marked for donation but something outside the "
                "pending graph still references it (refcount above the "
                "graph-only budget) — donating would corrupt the live alias",
            )


def _verify_deferred(deferred) -> None:
    """The async runtime's deferred NaN-scan / census queue: each entry is
    ``(span, payload, census, results)`` with payload either None
    (census-only) or the 6-tuple the deferred ``_nan_check`` replays."""
    if not deferred:
        return
    for k, entry in enumerate(deferred):
        if not (isinstance(entry, tuple) and len(entry) == 4):
            _fail(
                "deferred",
                f"queued entry {k} is not a (span, payload, census, results) "
                f"tuple ({type(entry).__name__})",
            )
        payload = entry[1]
        if payload is None:
            continue
        if not (isinstance(payload, tuple) and len(payload) == 6):
            _fail(
                "deferred",
                f"entry {k} carries a malformed NaN-scan payload "
                f"(len {len(payload) if isinstance(payload, tuple) else '?'}, "
                "want 6: keys/fns/live/results/leaves/descs)",
            )
        keys, fns, live, results, _leaves, descs = payload
        if not (len(keys) == len(fns) == len(descs)):
            _fail(
                "deferred",
                f"entry {k}: {len(keys)} op keys vs {len(fns)} fns vs "
                f"{len(descs)} wiring rows",
            )
        if results is not None and len(live) != len(results):
            _fail(
                "deferred",
                f"entry {k}: {len(live)} live slots vs {len(results)} results",
            )


def verify_before_dispatch(g, donate_ix: Sequence[int] = (),
                           deferred=None) -> None:
    """The full pre-dispatch pass ``_flush_impl`` runs under
    ``FLAGS_lazy_verify``: structural graph invariants, donation-mask
    soundness for THIS flush, and deferred-queue bookkeeping. Bumps the
    ``lazy_verify_passes`` counter so the zero-cost tripwire can assert the
    disabled path never reaches here."""
    from ..core.dispatch import _prof

    verify_graph(g)
    _verify_donation(g, donate_ix)
    _verify_deferred(deferred)
    _prof().counter_inc("lazy_verify_passes")
