"""``python -m paddle_tpu.analysis`` — run every pillar, exit non-zero on
any unsuppressed finding.

Order: the two static pillars (linter, lock checker) over the package tree,
then a runtime self-check of the lazy-graph verifier — a live graph must
verify clean AND a deliberately corrupted copy must raise, so a silently
broken verifier (the worst failure mode of a checker) also fails the run.

Flags::

    python -m paddle_tpu.analysis [--root DIR] [--no-baseline] [--no-selfcheck]
"""
from __future__ import annotations

import argparse
import sys


def _verifier_selfcheck() -> int:
    """0 on success. Builds a real pending graph, verifies it, then plants a
    wiring corruption and requires the structured error."""
    import numpy as np

    from ..core import lazy
    from .verify_graph import GraphInvariantError, verify_before_dispatch

    import jax.numpy as jnp

    lazy.flush()  # start from a clean epoch on this thread
    a = jnp.asarray(np.arange(8.0, dtype=np.float32))
    (x,), _ = lazy.record("selfcheck_add", jnp.add, [a, a])
    (y,), _ = lazy.record("selfcheck_mul", jnp.multiply, [x, a])
    g = lazy._state.graph
    try:
        verify_before_dispatch(g, (), None)
    except GraphInvariantError as e:
        print(f"verifier self-check FAILED: clean graph rejected: {e}")
        return 1
    # plant a forward reference (node 0 reading node 1's output = a cycle)
    good = g.descs[0]
    g.descs[0] = (("n", 1, 0),) + tuple(good[1:])
    try:
        verify_before_dispatch(g, (), None)
        print("verifier self-check FAILED: seeded cycle not detected")
        return 1
    except GraphInvariantError:
        pass
    finally:
        g.descs[0] = good
        del x, y
        lazy._state.graph = None  # drop the probe graph, no dispatch needed
    return 0


def main(argv=None) -> int:
    from . import baseline_path, package_root, run_all

    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.analysis")
    ap.add_argument("--root", default=None, help="package dir to analyze")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--no-selfcheck", action="store_true",
                    help="skip the runtime verifier self-check (no jax import)")
    args = ap.parse_args(argv)

    root = args.root or package_root()
    findings = run_all(root, baseline="" if args.no_baseline else None)
    for f in findings:
        print(f)
    rc = 0
    if findings:
        print(f"\n{len(findings)} unsuppressed finding(s) "
              f"(suppress inline with '# lint: ok(<rule>)' or baseline with "
              "a justification in paddle_tpu/analysis/baseline.txt)")
        rc = 1
    else:
        print(f"analysis clean over {root}")
    if not args.no_selfcheck:
        src = _verifier_selfcheck()
        if src == 0:
            print("lazy-graph verifier self-check OK "
                  "(clean graph accepted, seeded cycle rejected)")
        rc = rc or src
    return rc


if __name__ == "__main__":
    sys.exit(main())
