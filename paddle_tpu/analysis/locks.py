"""Lock-discipline checker over ``# guarded_by: <lock>`` annotations.

The async runtime's shared mutable state — the flush-executable cache, the
watchdog's guard/progress tables, the flight recorder's dump pointer — is
touched from worker threads (prefetcher, bg-compile, monitor, heartbeat).
The convention: a shared-mutable attribute declares its lock at its
initialization site::

    _guards: Dict[int, ...] = {}   # guarded_by: _lock

and this AST pass verifies every MUTATION of an annotated name —
reassignment, augmented assignment, ``del``, subscript store, or a call to
a known mutating method (``append``/``pop``/``update``/``clear``/...) — is
lexically inside ``with <lock>:`` (any receiver spelling with the same
terminal name matches: ``_lock``, ``self._lock``, ``cls._lock``) or inside
a function decorated ``@requires_lock("<lock>")`` (whose callers then hold
the lock; the decorator asserts it at runtime under
``FLAGS_thread_checks``). Reads are not checked — the discipline targets
torn writes and lost updates, and read-mostly paths (progress tables,
last-dump pointers) are deliberately lock-free.

Exemptions: the annotated initialization statement itself, other module
top-level statements (import time is single-threaded), and ``__init__``
bodies for ``self.<attr>`` annotations (the object is not yet shared).

Findings use rule ``lock-discipline`` and share the linter's suppression
(``# lint: ok(lock-discipline)``) and baseline grammar.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .lint import Finding, _suppressed_lines, iter_py_files

__all__ = ["check_lock_discipline", "check_source", "collect_annotations"]

_GUARDED = re.compile(r"#\s*guarded_by:\s*([A-Za-z_][\w.]*)")

# method names that mutate the common containers (dict/list/set/deque)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort", "reverse", "rotate", "move_to_end",
}


def collect_annotations(source: str) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
    """(owner, name) -> (lock, line, kind) for every ``# guarded_by:``
    annotation. The annotated name is the assignment target on the same
    line: a module global (``_guards = {}``, owner ``""``, kind
    ``"global"``) or an instance attribute (``self._x = {}`` → owner = the
    enclosing class qualname, kind ``"attr"``). Keying attributes by their
    class keeps two classes' same-named attributes (each with its own lock)
    from colliding."""
    out: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return out
    by_line: Dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _GUARDED.search(line)
        if m:
            by_line[i] = m.group(1)
    if not by_line:
        return out

    class _Collector(ast.NodeVisitor):
        def __init__(self):
            self.classes: List[str] = []

        def visit_ClassDef(self, node):
            self.classes.append(node.name)
            self.generic_visit(node)
            self.classes.pop()

        def _record(self, node, targets):
            lock = by_line.get(node.lineno)
            if lock is None:
                return
            owner = ".".join(self.classes)
            for t in targets:
                if isinstance(t, ast.Name):
                    out[("", t.id)] = (lock, node.lineno, "global")
                elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                        and t.value.id in ("self", "cls"):
                    out[(owner, t.attr)] = (lock, node.lineno, "attr")

        def visit_Assign(self, node):
            self._record(node, node.targets)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            self._record(node, [node.target])
            self.generic_visit(node)

    _Collector().visit(tree)
    return out


def _terminal(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _with_lock_names(node: ast.With) -> Set[str]:
    out: Set[str] = set()
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):  # with lock.acquire_timeout(...) style
            expr = expr.func
        if isinstance(expr, ast.Name):
            out.add(expr.id)
        elif isinstance(expr, ast.Attribute):
            out.add(expr.attr)
    return out


def _requires_locks(node) -> Set[str]:
    """Lock names asserted by ``@requires_lock("...")`` / ``@requires_lock(_lock)``
    decorators on a function."""
    out: Set[str] = set()
    for dec in getattr(node, "decorator_list", ()):
        call = dec if isinstance(dec, ast.Call) else None
        fn = call.func if call else dec
        fname = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if fname != "requires_lock":
            continue
        if call and call.args:
            a = call.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                out.add(_terminal(a.value))
            elif isinstance(a, ast.Name):
                out.add(a.id)
            elif isinstance(a, ast.Attribute):
                out.add(a.attr)
    return out


class _LockChecker(ast.NodeVisitor):
    def __init__(self, relpath: str,
                 annotations: Dict[Tuple[str, str], Tuple[str, int, str]]):
        self.relpath = relpath
        self.ann = annotations
        self.findings: List[Finding] = []
        self._held: List[Set[str]] = [set()]   # lock names in lexical scope
        self._scope: List[str] = []
        self._classes: List[str] = []          # enclosing class chain
        self._func_depth = 0

    def scope(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    # -- scope/context tracking -------------------------------------------
    def visit_With(self, node: ast.With):
        self._held.append(self._held[-1] | _with_lock_names(node))
        self.generic_visit(node)
        self._held.pop()

    def _visit_func(self, node):
        self._scope.append(node.name)
        self._func_depth += 1
        # A function body starts with NO inherited `with` locks — a nested
        # def lexically inside `with _lock:` is a closure that may run LATER
        # on another thread (thread targets, callbacks), when the lock is
        # long released. Only @requires_lock survives into the body: that
        # assumption is re-verified at call time under FLAGS_thread_checks.
        self._held.append(_requires_locks(node))
        self.generic_visit(node)
        self._held.pop()
        self._func_depth -= 1
        self._scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self._classes.append(node.name)
        self.generic_visit(node)
        self._classes.pop()
        self._scope.pop()

    # -- mutation detection --------------------------------------------------
    def _annotated_name(self, node) -> Optional[Tuple[str, str]]:
        """The annotation key when ``node`` denotes an annotated target:
        a bare Name (module global), ``self.<attr>``/``cls.<attr>`` of the
        ENCLOSING class, or a subscript of either. Attribute chains through
        other objects don't match; an attribute annotated by one class never
        matches a same-named attribute of another."""
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Name) and ("", node.id) in self.ann:
            return ("", node.id)
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
                and node.value.id in ("self", "cls"):
            key = (".".join(self._classes), node.attr)
            if key in self.ann:
                return key
        return None

    def _check(self, key: Tuple[str, str], node, action: str):
        lock, ann_line, kind = self.ann[key]
        name = key[1]
        if node.lineno == ann_line:
            return  # the annotated initialization itself
        if self._func_depth == 0:
            return  # module top level: import is single-threaded
        if kind == "attr" and self._scope and self._scope[-1] == "__init__":
            return  # instance state being built before the object escapes
        if _terminal(lock) in self._held[-1]:
            return
        self.findings.append(Finding(
            "lock-discipline", self.relpath, node.lineno, self.scope(),
            f"{action} of {name!r} (guarded_by: {lock}) outside "
            f"`with {lock}:` and not under @requires_lock",
        ))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            name = self._annotated_name(t)
            if name:
                self._check(name, node, "assignment")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        name = self._annotated_name(node.target)
        if name:
            self._check(name, node, "augmented assignment")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            name = self._annotated_name(t)
            if name:
                self._check(name, node, "delete")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            name = self._annotated_name(node.func.value)
            if name:
                self._check(name, node, f".{node.func.attr}() mutation")
        self.generic_visit(node)


def check_source(source: str, relpath: str) -> List[Finding]:
    ann = collect_annotations(source)
    if not ann:
        return []
    tree = ast.parse(source, filename=relpath)
    checker = _LockChecker(relpath, ann)
    checker.visit(tree)
    suppressed = _suppressed_lines(source)
    return [
        f for f in checker.findings
        if "lock-discipline" not in suppressed.get(f.line, ())
    ]


def check_lock_discipline(
    root: str, baseline: Sequence[Tuple[str, str, str]] = ()
) -> List[Finding]:
    """Run the checker over every annotated module under ``root``."""
    findings: List[Finding] = []
    for path in iter_py_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        if "guarded_by:" not in source:
            continue
        try:
            findings.extend(check_source(source, rel))
        except SyntaxError:
            continue  # the linter reports parse errors
    allowed = set(baseline)
    findings = [f for f in findings if f.key() not in allowed]
    findings.sort(key=lambda f: (f.path, f.line))
    return findings
