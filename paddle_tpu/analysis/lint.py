"""Repo-invariant linter (AST-based, zero third-party deps).

Each rule encodes an invariant a past incident or PR established:

* ``host-sync`` — hidden host synchronization in hot paths. ``.numpy()`` /
  ``.item()`` calls and ``np.asarray(x._data)`` inside ``core/``,
  ``distributed/`` and ``optimizer/`` force a device wait that bypasses the
  attributed ``lazy.timed_block`` funnel — invisible dispatch-gap time the
  async runtime (PR 6) exists to eliminate.
* ``compat-shim`` — direct use of a ``jax.*`` name the one-file shim in
  ``core/compat.py`` wraps (``shard_map``, ``export``, ``enable_x64``,
  ``axis_size``). Version drift in these silently dropped three files from
  tier-1 before PR 1 centralized them.
* ``atomic-write`` — a file opened for (over)write, or ``write_bytes`` /
  ``write_text``, in a function that never calls ``os.replace``: a process
  killed mid-write leaves a torn file. Two such torn persistent-cache
  entries produced deterministic segfaults (PR 3, PR 4); every
  cache/checkpoint/store/progress write must be tmp + ``os.replace``.
* ``monotonic-deadline`` — ``time.time()`` feeding deadline/timeout/
  interval arithmetic. Wall clocks jump (NTP, VM migration); a backward
  step turns a 30 s timeout into hours. Deadlines use ``time.monotonic()``;
  wall time is for human-facing timestamps only.
* ``flag-registry`` — a ``FLAGS_*`` name referenced somewhere in the tree
  but never present in ``framework/flags.py`` nor passed to
  ``register_flag``: the typo guard in ``set_flags`` can only reject what
  the registry knows about.
* ``counter-registry`` — mirror of flag-registry for profiler counters:
  every counter bumped anywhere (``counter_inc``/``_counter`` literal
  first args — including conditional-expression branches — and
  ``step_counters()`` dict keys) must appear in
  ``profiler.KNOWN_COUNTERS``, every registered name must be bumped
  somewhere, and every registered name must be documented
  (double-backticked) in the ``profiler.counters()`` docstring. A counter
  that dashboards can't discover (or a doc entry for a counter that no
  longer exists) is silent telemetry rot.
* ``bare-except`` — a bare ``except:`` (or ``except BaseException`` that
  does not re-raise) in retry/commit paths swallows ``KeyboardInterrupt``/
  ``SystemExit`` and can convert a preemption drain into a hang.
* ``oom-handler`` — an ``except`` that can catch an ``XlaRuntimeError``
  (bare, ``BaseException``, ``Exception``, ``RuntimeError``, or the type
  itself) in the DISPATCH-LAYER files of ``core/``/``distributed/``/
  ``serving/`` — the files where compiled executables actually launch —
  must either re-raise or route through the ONE ``fault/memory.py``
  classifier (``is_oom``/``classify``/``note_oom``/``maybe_hbm_oom``/a
  ``_recover_oom``-family helper). A broad handler that silently eats a
  ``RESOURCE_EXHAUSTED`` (e.g. into an unfused eager replay) turns a
  recoverable exhaustion into data-dependent wrong behavior; PR 14 made
  OOM a managed condition and this rule keeps it that way.

Suppression grammar: ``# lint: ok(<rule>)`` on the offending line (or the
line directly above it). Grandfathered findings live in ``baseline.txt`` —
one ``rule<TAB>path<TAB>scope<TAB># justification`` line each, matched on
(rule, file, enclosing function) so they survive line drift.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "lint_package", "lint_source", "load_baseline",
    "iter_py_files", "RULES",
]

RULES = (
    "host-sync", "compat-shim", "atomic-write", "monotonic-deadline",
    "flag-registry", "counter-registry", "bare-except", "oom-handler",
)

# counter-registry anchors: the registry lives in profiler/__init__.py as
# KNOWN_COUNTERS, documented in the counters() docstring; bumps route
# through these callables (literal first args only — the fault/retry.py
# `_counter(name, n)` pass-through and the distributed engine's
# `counter_inc(k, v)` loop over step_counters() are dynamic and resolved
# via their literal sources instead)
_COUNTER_REGISTRY_FILE = "profiler/__init__.py"
_COUNTER_FUNCS = ("counter_inc", "_counter")
_DOC_NAME = re.compile(r"``([A-Za-z0-9_]+)``")

# host-sync applies only to hot-path packages (metric/, hapi/ etc. read
# results by design); paths are package-relative, '/'-normalized
_HOST_SYNC_SCOPE = ("core/", "distributed/", "optimizer/")

# jax names whose only sanctioned home is core/compat.py
_SHIM_ATTRS = {"shard_map", "enable_x64"}
_SHIM_MODULES = {
    "jax.experimental.shard_map", "jax.experimental.export", "jax.export",
}
_DEADLINE_WORD = re.compile(r"deadline|timeout|expire|interval", re.IGNORECASE)
_SUPPRESS = re.compile(r"#\s*lint:\s*ok\(([a-z0-9_,\- ]+)\)")
_WRITE_MODES = ("w", "wb", "w+", "wb+", "x", "xb")
_MUTATING_WRITES = {"write_bytes", "write_text"}
_EXCEPT_SCOPE = ("fault/", "distributed/checkpoint.py", "distributed/coord.py",
                 "distributed/watchdog.py")
# oom-handler applies to the dispatch layer inside core//distributed//
# serving/ — the files where compiled executables launch and an
# XlaRuntimeError(RESOURCE_EXHAUSTED) can actually surface. A broad handler
# elsewhere in those packages has nothing device-dispatching in its try.
_OOM_SCOPE = (
    "core/lazy.py", "core/dispatch.py", "distributed/engine.py",
    "serving/engine.py", "serving/supervisor.py",
)
# exception types a RESOURCE_EXHAUSTED can hide behind
_OOM_TYPES = {"Exception", "BaseException", "RuntimeError", "XlaRuntimeError"}
# fault/memory.py classifier surface (plus the per-layer ladder helpers that
# route through it) — any of these in the handler body satisfies the rule
_OOM_ROUTERS = {
    "is_oom", "classify", "note_oom", "_note_oom", "_oom_recover",
    "_recover_oom", "_on_oom", "maybe_hbm_oom",
}


class Finding:
    """One linter/lock-checker finding. ``scope`` is the enclosing function
    qualname (or ``<module>``) — the stable anchor baseline entries match."""

    __slots__ = ("rule", "path", "line", "scope", "message")

    def __init__(self, rule: str, path: str, line: int, scope: str, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.scope = scope
        self.message = message

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.scope)

    def __repr__(self):
        return f"{self.path}:{self.line}: [{self.rule}] ({self.scope}) {self.message}"


def load_baseline(path: str) -> List[Tuple[str, str, str]]:
    """Parse the baseline file: ``rule<TAB>relpath<TAB>scope<TAB># why``
    per entry; blank lines and ``#`` comment lines ignored. A justification
    comment is REQUIRED — an unexplained entry is itself an error."""
    out: List[Tuple[str, str, str]] = []
    with open(path) as f:
        for ln, raw in enumerate(f, 1):
            line = raw.rstrip("\n")
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            parts = line.split("\t")
            if len(parts) < 4 or not parts[3].lstrip().startswith("#"):
                raise ValueError(
                    f"{path}:{ln}: baseline entry needs "
                    "rule<TAB>path<TAB>scope<TAB># justification"
                )
            if parts[0] not in RULES and not parts[0].startswith("lock-"):
                raise ValueError(f"{path}:{ln}: unknown rule {parts[0]!r}")
            out.append((parts[0], parts[1], parts[2]))
    return out


def iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def _suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """line -> set of rules suppressed there. A marker also covers the NEXT
    line, so it can sit above a long statement."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _SUPPRESS.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(i, set()).update(rules)
            out.setdefault(i + 1, set()).update(rules)
    return out


class _ScopeVisitor(ast.NodeVisitor):
    """Base visitor tracking the enclosing function qualname."""

    def __init__(self):
        self._scope: List[str] = []

    def scope(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def _visit_func(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()


def _dotted(node) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _names_in(node) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
    return out


class _Linter(_ScopeVisitor):
    def __init__(self, relpath: str, tree: ast.AST):
        super().__init__()
        self.relpath = relpath
        self.findings: List[Finding] = []
        self.flag_refs: List[Tuple[int, str, str]] = []  # (line, scope, name)
        self.flag_registered: Set[str] = set()
        self.counter_refs: List[Tuple[int, str, str]] = []  # (line, scope, name)
        self.counter_registered: Dict[str, int] = {}  # name -> line
        self.counter_documented: Set[str] = set()
        if relpath == _COUNTER_REGISTRY_FILE:
            for n in ast.walk(tree):
                if isinstance(n, ast.FunctionDef) and n.name == "counters":
                    doc = ast.get_docstring(n) or ""
                    self.counter_documented |= set(_DOC_NAME.findall(doc))
        # per-function: does it call os.replace (or equivalent rename)?
        self._atomic_funcs = self._collect_atomic_functions(tree)
        self._func_stack: List[ast.AST] = []
        # names assigned from time.time() in the current function
        self._wall_names: List[Set[str]] = [set()]

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _collect_atomic_functions(tree) -> Set[ast.AST]:
        """Function nodes whose body (own statements, not nested defs'
        bodies excluded — a helper closure doing the replace still makes the
        write pattern atomic) contains an ``os.replace``/``os.rename``."""
        atomic: Set[ast.AST] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        dn = _dotted(sub.func)
                        term = dn.rsplit(".", 1)[-1] if dn else None
                        if dn in ("os.replace", "os.rename") or term in (
                            "atomic_open", "atomic_write"
                        ):
                            atomic.add(node)
                            break
        return atomic

    def _emit(self, rule, node, message):
        self.findings.append(
            Finding(rule, self.relpath, node.lineno, self.scope(), message)
        )

    def _in_host_sync_scope(self) -> bool:
        return self.relpath.startswith(_HOST_SYNC_SCOPE)

    def _in_except_scope(self) -> bool:
        return self.relpath.startswith(_EXCEPT_SCOPE)

    # -- scope bookkeeping -------------------------------------------------
    def _visit_func(self, node):
        self._func_stack.append(node)
        self._wall_names.append(set())
        _ScopeVisitor._visit_func(self, node)
        self._wall_names.pop()
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- rules -------------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        dn = _dotted(node.func)

        # host-sync: .numpy()/.item() and np.asarray(x._data) in hot paths
        if self._in_host_sync_scope() and isinstance(node.func, ast.Attribute):
            if node.func.attr in ("numpy", "item") and not node.args and not node.keywords:
                self._emit(
                    "host-sync", node,
                    f".{node.func.attr}() forces a host sync; route readbacks "
                    "through lazy.timed_block (Tensor.numpy) or defer them",
                )
        if (
            self._in_host_sync_scope()
            and dn in ("np.asarray", "numpy.asarray")
            and node.args
            and isinstance(node.args[0], ast.Attribute)
            and node.args[0].attr == "_data"
        ):
            self._emit(
                "host-sync", node,
                "np.asarray(x._data) blocks on the raw buffer, bypassing the "
                "attributed timed_block readback funnel",
            )

        # compat-shim: direct jax.<wrapped name> call/attribute use
        if dn is not None and self.relpath != "core/compat.py":
            if (
                (dn.startswith("jax.") and dn.split(".")[-1] in _SHIM_ATTRS)
                or dn == "jax.export" or dn.startswith("jax.export.")
            ):
                self._emit(
                    "compat-shim", node,
                    f"direct {dn} use; route through core/compat.py (the "
                    "public home of this API moved between jax releases)",
                )
            if dn in ("lax.axis_size", "jax.lax.axis_size"):
                self._emit(
                    "compat-shim", node,
                    "lax.axis_size only exists on newer jax; use "
                    "core.compat.axis_size",
                )

        # atomic-write: open(..., 'w'/'wb') / write_bytes / write_text in a
        # function with no os.replace
        mode = None
        if dn in ("open", "io.open") and len(node.args) >= 2:
            a = node.args[1]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                mode = a.value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        is_write = (
            (dn in ("open", "io.open") and mode in _WRITE_MODES)
            or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_WRITES
            )
        )
        if is_write and not (self._func_stack and self._func_stack[-1] in self._atomic_funcs):
            what = mode and f"open(..., {mode!r})" or f".{node.func.attr}(...)"
            self._emit(
                "atomic-write", node,
                f"{what} with no os.replace in the enclosing function — a "
                "mid-write kill leaves a torn file; write tmp + os.replace",
            )

        # monotonic-deadline: time.time() directly inside deadline math
        if dn == "time.time":
            names = _names_in(self._current_stmt or node)
            if any(_DEADLINE_WORD.search(n) for n in names):
                self._emit(
                    "monotonic-deadline", node,
                    "time.time() in deadline/timeout arithmetic — wall clocks "
                    "jump; use time.monotonic()",
                )

        # flag-registry: collect FLAGS_* string references. Matched on the
        # terminal attribute so chained receivers (`_flags_mod().flag(...)`)
        # are caught too.
        fname = (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else node.func.id if isinstance(node.func, ast.Name) else None
        )
        if fname in ("flag", "register_flag", "get_flags"):
            for a in node.args[:1]:
                if isinstance(a, ast.Constant) and isinstance(a.value, str) \
                        and a.value.startswith("FLAGS_"):
                    if fname == "register_flag":
                        self.flag_registered.add(a.value)
                    else:
                        self.flag_refs.append((node.lineno, self.scope(), a.value))

        # counter-registry: collect counter bump sites. A conditional
        # expression as the name (`counter_inc("a" if c else "b")`) bumps
        # every branch, so every branch is a reference.
        if fname in _COUNTER_FUNCS and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                self.counter_refs.append((node.lineno, self.scope(), a.value))
            elif isinstance(a, ast.IfExp):
                # walk only the VALUE positions (body/orelse, nested
                # conditionals included) — the test expression's string
                # literals are predicates, not counter names
                stack = [a.body, a.orelse]
                while stack:
                    sub = stack.pop()
                    if isinstance(sub, ast.IfExp):
                        stack += [sub.body, sub.orelse]
                    elif isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        self.counter_refs.append(
                            (node.lineno, self.scope(), sub.value))
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import):
        if self.relpath != "core/compat.py":
            for alias in node.names:
                if alias.name in _SHIM_MODULES:
                    self._emit(
                        "compat-shim", node,
                        f"import {alias.name}; route through core/compat.py",
                    )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if self.relpath != "core/compat.py" and node.module:
            wrapped = {"shard_map", "export", "enable_x64"}
            if node.module in _SHIM_MODULES or (
                node.module in ("jax", "jax.experimental")
                and any(a.name in wrapped for a in node.names)
            ):
                self._emit(
                    "compat-shim", node,
                    f"from {node.module} import "
                    f"{', '.join(a.name for a in node.names)}; route through "
                    "core/compat.py",
                )
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        # taint-track `now = time.time()` so a later `now - t0 > timeout`
        # compare in the same function is still caught
        if (
            isinstance(node.value, ast.Call)
            and _dotted(node.value.func) == "time.time"
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._wall_names[-1].add(t.id)
        # counter-registry: the registry itself (profiler KNOWN_COUNTERS)
        if (
            self.relpath == _COUNTER_REGISTRY_FILE
            and any(isinstance(t, ast.Name) and t.id == "KNOWN_COUNTERS"
                    for t in node.targets)
        ):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    self.counter_registered.setdefault(sub.value, sub.lineno)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        names = _names_in(node)
        tainted = names & self._wall_names[-1]
        if tainted and any(_DEADLINE_WORD.search(n) for n in names):
            self._emit(
                "monotonic-deadline", node,
                f"wall-clock value {sorted(tainted)[0]!r} (from time.time()) "
                "compared against a deadline/timeout — use time.monotonic()",
            )
        self.generic_visit(node)

    @staticmethod
    def _oom_catchable(t) -> bool:
        """Could this except-type clause see an XlaRuntimeError?"""
        if t is None:
            return True  # bare except
        if isinstance(t, ast.Tuple):
            return any(_Linter._oom_catchable(x) for x in t.elts)
        dn = _dotted(t)
        return dn is not None and dn.rsplit(".", 1)[-1] in _OOM_TYPES

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if self.relpath in _OOM_SCOPE and self._oom_catchable(node.type):

            def _callee(c):
                dn = _dotted(c.func)
                if dn:
                    return dn.rsplit(".", 1)[-1]
                return c.func.attr if isinstance(c.func, ast.Attribute) else None

            reraises = any(
                isinstance(s, ast.Raise) and s.exc is None
                for s in ast.walk(node)
            )
            routed = any(
                isinstance(s, ast.Call) and _callee(s) in _OOM_ROUTERS
                for s in ast.walk(node)
            )
            if not reraises and not routed:
                self._emit(
                    "oom-handler", node,
                    "broad except in a dispatch-layer file can swallow an "
                    "XlaRuntimeError(RESOURCE_EXHAUSTED); re-raise or route "
                    "through the fault/memory.py classifier (is_oom/"
                    "classify/note_oom)",
                )
        if self._in_except_scope():
            bare = node.type is None
            base = (
                isinstance(node.type, ast.Name) and node.type.id == "BaseException"
            )
            if bare or base:
                reraises = any(
                    isinstance(s, ast.Raise) and s.exc is None
                    for s in ast.walk(node)
                )
                if not reraises:
                    self._emit(
                        "bare-except", node,
                        ("bare except" if bare else "except BaseException") +
                        " without re-raise in a retry/commit path swallows "
                        "KeyboardInterrupt/SystemExit",
                    )
        self.generic_visit(node)

    # flag-registry also needs FLAGS_* dict keys (the registry itself) and
    # env-pickup string literals; collect registrations from flags.py keys
    def visit_Dict(self, node: ast.Dict):
        if self.relpath == "framework/flags.py":
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                        and k.value.startswith("FLAGS_"):
                    self.flag_registered.add(k.value)
        # counter-registry: a `step_counters()` dict is fed verbatim into
        # `counter_inc(k, v)` by the distributed engine — its string keys
        # are counter bumps
        if self._func_stack and getattr(
                self._func_stack[-1], "name", "") == "step_counters":
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    self.counter_refs.append(
                        (k.lineno, self.scope(), k.value))
        self.generic_visit(node)

    # track the current top-level statement for expression-local name scans
    _current_stmt: Optional[ast.stmt] = None

    def visit(self, node):
        if isinstance(node, ast.stmt):
            self._current_stmt = node
        return super().visit(node)


def _analyze(source: str, relpath: str) -> Tuple[List[Finding], "_Linter"]:
    """Run the per-file linter; returns the suppression-filtered findings
    plus the visitor itself (cross-file flag/counter data rides on it)."""
    tree = ast.parse(source, filename=relpath)
    linter = _Linter(relpath, tree)
    linter.visit(tree)
    suppressed = _suppressed_lines(source)
    kept = [
        f for f in linter.findings
        if f.rule not in suppressed.get(f.line, ())
    ]
    return kept, linter


def lint_source(source: str, relpath: str) -> Tuple[List[Finding], List, Set[str]]:
    """Lint one file. Returns (findings, flag_refs, flags_registered) — the
    flag data is resolved cross-file by :func:`lint_package`."""
    kept, linter = _analyze(source, relpath)
    refs = [(relpath, ln, scope, name) for ln, scope, name in linter.flag_refs]
    return kept, refs, linter.flag_registered


def _apply_baseline(findings: Sequence[Finding],
                    baseline: Sequence[Tuple[str, str, str]]) -> List[Finding]:
    allowed = set(baseline)
    return [f for f in findings if f.key() not in allowed]


def lint_package(root: str,
                 baseline: Sequence[Tuple[str, str, str]] = ()) -> List[Finding]:
    """Lint every .py file under ``root`` (a package directory); resolve the
    cross-file flag-registry rule; subtract baseline entries."""
    findings: List[Finding] = []
    all_refs: List[Tuple[str, int, str, str]] = []
    registered: Set[str] = set()
    counter_refs: List[Tuple[str, int, str, str]] = []
    counter_registered: Dict[str, int] = {}
    counter_documented: Set[str] = set()
    for path in iter_py_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            file_findings, linter = _analyze(source, rel)
        except SyntaxError as e:
            findings.append(Finding(
                "parse-error", rel, e.lineno or 0, "<module>",
                f"file does not parse: {e.msg}",
            ))
            continue
        findings.extend(file_findings)
        all_refs.extend(
            (rel, ln, scope, name) for ln, scope, name in linter.flag_refs)
        registered |= linter.flag_registered
        counter_refs.extend(
            (rel, ln, scope, name) for ln, scope, name in linter.counter_refs)
        counter_registered.update(linter.counter_registered)
        counter_documented |= linter.counter_documented
    for rel, ln, scope, name in all_refs:
        if name not in registered:
            findings.append(Finding(
                "flag-registry", rel, ln, scope,
                f"{name} referenced but never registered in framework/flags.py "
                "(set_flags typo-guard cannot protect it)",
            ))
    # counter-registry, three directions: bumped-but-unregistered at the
    # bump site; registered-but-never-bumped and registered-but-undocumented
    # at the registry entry. (The checks only engage when the package under
    # lint actually carries the registry — a synthetic test package without
    # profiler/__init__.py shouldn't fail on its own counter bumps.)
    if counter_registered:
        bumped = {name for _, _, _, name in counter_refs}
        for rel, ln, scope, name in counter_refs:
            if name not in counter_registered:
                findings.append(Finding(
                    "counter-registry", rel, ln, scope,
                    f"counter {name!r} bumped here but missing from "
                    "profiler.KNOWN_COUNTERS (dashboards can't discover it)",
                ))
        for name, ln in sorted(counter_registered.items()):
            if name not in bumped:
                findings.append(Finding(
                    "counter-registry", _COUNTER_REGISTRY_FILE, ln, "<module>",
                    f"counter {name!r} registered in KNOWN_COUNTERS but never "
                    "bumped anywhere (stale registry entry)",
                ))
            if name not in counter_documented:
                findings.append(Finding(
                    "counter-registry", _COUNTER_REGISTRY_FILE, ln, "counters",
                    f"counter {name!r} registered but not documented "
                    "(``double-backticked``) in the counters() docstring",
                ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return _apply_baseline(findings, baseline)
