"""graft-analyze — static & runtime invariant checking for the runtime.

PRs 1-8 accumulated invariants the runtime's correctness silently depends
on: donation masks must match liveness, cache/checkpoint/store writes must
be tmp+``os.replace``-atomic (two torn-cache segfault incidents), raw
``jax.*`` API use must route through ``core/compat.py``, deadlines must use
monotonic clocks, and the async runtime's shared state is touched from
worker threads across a dozen modules. Each of these used to be enforced
only by incident-driven regression tests; this package turns them into
machine-checked rules (the LazyTensor IR-checking discipline,
arXiv:2102.13267) so the next violation is a lint/verify failure instead of
a nondeterministic segfault.

Three pillars:

* :mod:`~paddle_tpu.analysis.verify_graph` — a structural verifier over the
  pending lazy graph, run immediately before dispatch under
  ``FLAGS_lazy_verify`` (default on in tests, off in production at a
  one-flag-probe cost).
* :mod:`~paddle_tpu.analysis.lint` — an AST repo-invariant linter (zero
  third-party deps): hidden host syncs, compat-shim bypasses, non-atomic
  writes, wall-clock deadlines, unregistered ``FLAGS_*``, bare excepts.
  Inline suppression via ``# lint: ok(<rule>)``; grandfathered findings
  live in ``baseline.txt`` with one-line justifications.
* :mod:`~paddle_tpu.analysis.locks` — a lock-discipline checker over
  ``# guarded_by: <lock>`` annotations, plus the opt-in runtime
  ownership-assertion mode in :mod:`~paddle_tpu.analysis.thread_checks`
  (``FLAGS_thread_checks``) that makes races fail deterministically.

``python -m paddle_tpu.analysis`` runs all pillars and exits non-zero on
any unsuppressed finding — wired into tier-1 as a tripwire test.
"""
from __future__ import annotations

import os
from typing import List, Optional

from .lint import Finding, lint_package, load_baseline  # noqa: F401
from .locks import check_lock_discipline  # noqa: F401

__all__ = [
    "Finding", "lint_package", "check_lock_discipline", "run_all",
    "package_root", "baseline_path",
]


def package_root() -> str:
    """The paddle_tpu package directory the analysis runs over."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.txt")


def run_all(root: Optional[str] = None,
            baseline: Optional[str] = None) -> List[Finding]:
    """Run every static pillar over ``root`` (default: the installed
    paddle_tpu package) and return the UNSUPPRESSED findings. The verifier
    pillar is runtime (hooked into the lazy flush) — its self-check lives in
    ``__main__`` and the test suite."""
    root = root or package_root()
    if baseline is None:
        baseline = baseline_path()
    base = load_baseline(baseline) if baseline and os.path.exists(baseline) else []
    findings = lint_package(root, baseline=base)
    findings += check_lock_discipline(root, baseline=base)
    return findings
