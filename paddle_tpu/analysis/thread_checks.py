"""Opt-in runtime ownership assertions (``FLAGS_thread_checks``).

The static lock checker proves LEXICAL discipline: every mutation of an
annotated structure sits inside ``with <lock>:``. It cannot prove dynamic
discipline — a helper called with the lock already held, a structure handed
to a thread it was never meant for. This module closes that gap: with
``FLAGS_thread_checks=1`` (off by default; chaos/async suites turn it on)
annotated structures are wrapped in proxies that make a racy mutation fail
DETERMINISTICALLY at the mutation site, instead of as a corrupted table
three steps later:

* :func:`guarded` — mutations assert the guarding lock is currently held
  (``lock.locked()`` for a ``Lock``, owner check for an ``RLock``);
* :func:`owned` — mutations assert they happen on the structure's owner
  thread (bound at wrap time or first mutation);
* :func:`requires_lock` — the decorator counterpart of the static checker's
  escape hatch: the wrapped function asserts its lock is held on entry.

All three are identity/no-op when the flag is off, so production pays one
flag probe at WRAP time (not per mutation).
"""
from __future__ import annotations

import threading
from typing import Optional

__all__ = [
    "enabled", "guarded", "owned", "requires_lock", "GuardedDict",
    "OwnershipError",
]

# named mutating methods routed through __getattr__; the mutating SPECIAL
# methods (item store/delete, += , |=) are real methods on the proxy below —
# implicit special-method lookup never consults __getattr__
_MUTATORS = (
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort", "reverse", "rotate", "move_to_end",
)


class OwnershipError(AssertionError):
    """A thread mutated a checked structure without holding its lock /
    without being its owner. An AssertionError subclass: this is a bug in
    the calling code, never a recoverable runtime condition."""


def enabled() -> bool:
    from ..framework import flags

    return bool(flags.flag("FLAGS_thread_checks", False))


def _lock_held(lock) -> bool:
    # RLock exposes ownership — guard shared structures with an RLock so an
    # unguarded mutation that merely OVERLAPS another thread's locked region
    # is still caught. A plain Lock only answers "is locked by somebody":
    # every mutation with the lock free still fails deterministically, but a
    # concurrent holder masks the check for that window.
    owned_fn = getattr(lock, "_is_owned", None)
    if owned_fn is not None:
        try:
            return bool(owned_fn())
        except Exception:
            pass
    try:
        return bool(lock.locked())
    except Exception:
        return True  # unknown lock type: don't turn diagnostics into crashes


class _CheckedProxy:
    """Wraps a container; every known-mutating method first runs ``check``.
    Reads pass through untouched. Not a subclass: isinstance checks on the
    wrapped type are intentionally broken under the flag so tests notice
    they're running checked."""

    __slots__ = ("_obj", "_check", "_name")

    def __init__(self, obj, check, name):
        self._obj = obj
        self._check = check
        self._name = name

    def __getattr__(self, attr):
        val = getattr(self._obj, attr)
        if attr in _MUTATORS and callable(val):
            check = self._check

            def checked(*a, _val=val, **k):
                check()
                return _val(*a, **k)

            return checked
        return val

    def __getitem__(self, k):
        return self._obj[k]

    def __setitem__(self, k, v):
        self._check()
        self._obj[k] = v

    def __delitem__(self, k):
        self._check()
        del self._obj[k]

    def __iadd__(self, other):
        self._check()
        self._obj += other
        return self  # the holder's name stays bound to the checked proxy

    def __ior__(self, other):
        self._check()
        self._obj |= other
        return self

    def __contains__(self, k):
        return k in self._obj

    def __iter__(self):
        return iter(self._obj)

    def __len__(self):
        return len(self._obj)

    def __bool__(self):
        return bool(self._obj)

    def __eq__(self, other):
        return self._obj == (other._obj if isinstance(other, _CheckedProxy) else other)

    def __repr__(self):
        return f"checked({self._name}: {self._obj!r})"


GuardedDict = _CheckedProxy  # the common instantiation, re-exported by name


def guarded(obj, lock, name: str = "structure"):
    """Wrap ``obj`` so every mutation asserts ``lock`` is held. Identity
    when ``FLAGS_thread_checks`` is off (and when ``obj`` is already
    wrapped — re-wrapping on reconfigure must not stack proxies)."""
    if not enabled():
        return obj
    if isinstance(obj, _CheckedProxy):
        return obj

    def check():
        if not _lock_held(lock):
            raise OwnershipError(
                f"unguarded mutation of {name} on thread "
                f"{threading.current_thread().name!r}: its guarded_by lock "
                "is not held"
            )

    return _CheckedProxy(obj, check, name)


def owned(obj, name: str = "structure",
          owner: Optional[threading.Thread] = None):
    """Wrap ``obj`` so every mutation asserts it runs on the owner thread
    (default: the thread performing the first mutation). Identity when the
    flag is off."""
    if not enabled():
        return obj
    if isinstance(obj, _CheckedProxy):
        return obj
    box = [owner]

    def check():
        cur = threading.current_thread()
        if box[0] is None:
            box[0] = cur
            return
        if box[0] is not cur:
            raise OwnershipError(
                f"{name} is owned by thread {box[0].name!r} but was mutated "
                f"from {cur.name!r}"
            )

    return _CheckedProxy(obj, check, name)


def unwrap(obj):
    """The raw container behind a checked proxy (identity otherwise)."""
    return obj._obj if isinstance(obj, _CheckedProxy) else obj


def requires_lock(lock, name: Optional[str] = None):
    """Decorator: the static checker accepts mutations inside the decorated
    function as guarded; under ``FLAGS_thread_checks`` the assumption is
    verified on every call. ``lock`` may also be a string naming an
    attribute on the first positional arg (``@requires_lock("_lock")`` on a
    method resolves ``self._lock`` at call time)."""

    def wrap(fn):
        if isinstance(lock, str):
            def wrapped(*a, **k):
                if enabled():
                    lk = getattr(a[0], lock, None) if a else None
                    if lk is None:
                        import sys

                        lk = getattr(sys.modules.get(fn.__module__), lock, None)
                    if lk is not None and not _lock_held(lk):
                        raise OwnershipError(
                            f"{fn.__qualname__} requires {lock} held"
                        )
                return fn(*a, **k)
        else:
            def wrapped(*a, **k):
                if enabled() and not _lock_held(lock):
                    raise OwnershipError(
                        f"{fn.__qualname__} requires "
                        f"{name or 'its lock'} held"
                    )
                return fn(*a, **k)
        wrapped.__name__ = fn.__name__
        wrapped.__qualname__ = fn.__qualname__
        wrapped.__doc__ = fn.__doc__
        wrapped.__wrapped__ = fn
        return wrapped

    return wrap
