"""Eager op dispatch.

TPU-native analogue of the reference's dygraph trace path
(``paddle/fluid/imperative/tracer.cc:170`` TraceOp →
``prepared_operator.cc:129`` kernel select → launch). Here "kernel selection"
is gone — every op is a pure JAX function lowered by XLA — and the trace step
is a ``jax.vjp`` capture that doubles as grad-node creation
(cf. tracer.cc:303 CreateGradOpNode). Non-differentiable paths run through a
per-op ``jax.jit`` cache so repeated eager calls hit compiled executables.

AMP auto-cast hooks into this layer exactly where the reference casts inputs
in the tracer (tracer.cc:207-221).
"""
from __future__ import annotations

import time as _time
import weakref
from typing import Callable, Optional, Sequence

import numpy as np
import jax

from . import lazy as lazy_mod
from .engine import GradNode, grad_enabled
from .tensor import Tensor

# profiler module, bound once at first dispatch (module-level `from .. import`
# would run during partial package init; per-op imports cost the hot path)
_profiler = None


def _prof():
    global _profiler
    if _profiler is None:
        from .. import profiler

        _profiler = profiler
    return _profiler

# AMP hook — set by paddle_tpu.amp.auto_cast; signature (op_name, tensors) -> tensors
_amp_hook: Optional[Callable] = None

# Fault-injection hook — set to the paddle_tpu.fault.inject module by
# inject.arm(), back to None by inject.disarm(). The disarmed hot path pays
# one `is not None` check per op.
_fault_inject = None


def set_amp_hook(hook):
    global _amp_hook
    _amp_hook = hook


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, np.dtype):
        return str(v)
    if isinstance(v, (bool, int, float, complex)):
        return lazy_mod._typed(v)  # 1/1.0/True hash-collide but trace differently
    return v


# Per-(op, attrs) jitted executable cache — the analogue of the reference's
# PreparedOp cache (prepared_operator.cc) + program/executable caching.
# Ops define their fn as a per-call lambda/closure, so the key must be the
# code object + closure/default VALUES, not the function identity — otherwise
# every call is a cache miss and the cache grows without bound.
import collections

_jit_cache: "collections.OrderedDict" = collections.OrderedDict()
_JIT_CACHE_MAX = 4096


_fn_key = lazy_mod._fn_key  # one implementation; key includes kw-only defaults

# Per-call-site key memo: ops define their fn at a fixed source location, and
# for the common closure-free/default-free shape the key is fully determined
# by the code object — skip re-hashing () cells and defaults on every call.
# Closures over attr values still hash their cell contents (values vary).
_code_key_cache: dict = {}


def _fast_fn_key(fn):
    try:
        cells = fn.__closure__
        if not fn.__defaults__ and not fn.__kwdefaults__:
            if cells is None:
                code = fn.__code__
                k = _code_key_cache.get(code)
                if k is None:
                    k = _fn_key(fn)
                    if len(_code_key_cache) > _JIT_CACHE_MAX:
                        _code_key_cache.clear()  # exec/notebook-generated code objects
                    _code_key_cache[code] = k
                elif _profiler is not None and _profiler._enabled:
                    _profiler.counter_inc("dispatch_fastkey_hits")
                return k
            # Call-site memo, scalar-closure shape (the common op lambda
            # `lambda *xs: fn(*xs, attr=v)` closing over attr values): build
            # the key inline, skipping _fn_key's getattr chain + kwdefault
            # sort. MUST stay value-compatible with _fn_key's output —
            # scalars as (typename, value), strings verbatim — so both paths
            # hash a given fn to the same executable-cache entry.
            vals = []
            for c in cells:
                v = c.cell_contents
                t = type(v)
                if t in (bool, int, float, complex):
                    vals.append((t.__name__, v))
                elif t is str:
                    vals.append(v)
                else:
                    return _fn_key(fn)
            return (fn.__code__, tuple(vals), (), ())
    except (AttributeError, ValueError):
        pass
    return _fn_key(fn)


def _attrs_key(attrs):
    """Hashable signature of an op's attrs; () for the no-attr fast path.
    Raises TypeError for unhashable attrs (callers fall back)."""
    if not attrs:
        return ()
    key = tuple(sorted((k, _hashable(v)) for k, v in attrs.items()))
    hash(key)
    return key


def _get_jitted(fn, attrs):
    try:
        key = (_fast_fn_key(fn), _attrs_key(attrs))
        hash(key)
    except TypeError:  # unhashable attr → run eagerly un-jitted
        return lambda *arrays: fn(*arrays, **attrs)
    jf = _jit_cache.get(key)
    if jf is None:
        jf = jax.jit(lambda *arrays: fn(*arrays, **attrs))
        _jit_cache[key] = jf
        if len(_jit_cache) > _JIT_CACHE_MAX:
            _jit_cache.popitem(last=False)
    else:
        _jit_cache.move_to_end(key)
    return jf


def _nonfinite_error(name, idx, arr, origin="eager", hint=False, extra=None):
    """Build the FLAGS_check_nan_inf diagnostic (reference
    nan_inf_utils_detail.cc prints tensor meta + offending values): which
    output, its shape/dtype, how many non-finite elements, and where the
    first one sits."""
    a = np.asarray(arr)
    bad = ~np.isfinite(a)
    cnt = int(bad.sum())
    flat_idx = int(np.flatnonzero(bad.ravel())[0]) if cnt else -1
    first = a.ravel()[flat_idx] if cnt else None
    msg = (
        f"Operator '{name}' output {idx} (shape={tuple(a.shape)}, "
        f"dtype={a.dtype}) contains {cnt} non-finite value(s); first at flat "
        f"index {flat_idx} = {first!r} [{origin}] (FLAGS_check_nan_inf is set)."
    )
    if hint:
        msg += (
            " Set FLAGS_check_nan_inf_per_op=1 to re-run the pending graph "
            "unfused and attribute the first non-finite value to its "
            "producing op."
        )
    # Every non-finite diagnostic (eager, lazy flush, per-op replay) writes a
    # flight-recorder post-mortem BEFORE the raise: the dump's active-span
    # stack names the producing flush span (for a DEFERRED async-mode trip
    # the flush span is already closed, so `extra` carries it instead), and
    # recent spans + counters show what the engine was doing when the value
    # went bad.
    try:
        from ..profiler import flight

        flight.dump(
            "naninf",
            extra={
                "op": name, "output": idx, "origin": origin,
                "nonfinite_count": cnt, "first_flat_index": flat_idx,
                "message": msg, **(extra or {}),
            },
        )
    except Exception:  # lint: ok(oom-handler) — flight-dump guard, nothing dispatches in this try
        pass
    return FloatingPointError(msg)


def _check_nan_inf(name, outs, origin="eager"):
    # FLAGS_check_nan_inf debug scan — the reference checks every op output
    # when the flag is set (operator.cc:1171 → nan_inf_utils_detail.cc).
    # Host-side isfinite forces a device sync per op; that's the documented
    # cost of the debug mode there too.
    import jax.numpy as jnp

    for i, o in enumerate(outs):
        if hasattr(o, "dtype") and jnp.issubdtype(o.dtype, jnp.floating):
            if not bool(jnp.isfinite(o).all()):
                _prof().counter_inc("naninf_trips")
                raise _nonfinite_error(name, i, o, origin=origin)


def eager_call(
    name: str,
    fn: Callable,
    tensor_args: Sequence[Tensor],
    attrs: Optional[dict] = None,
    differentiable: bool = True,
    nondiff_outputs: Sequence[int] = (),
    fn_key=None,
):
    """Run one op eagerly; record a GradNode if any input needs grad.

    ``fn(*arrays, **attrs)`` must be a pure function of JAX arrays returning
    an array or a tuple of arrays. ``nondiff_outputs`` marks integer/bool
    output positions excluded from the vjp capture.
    """
    p = _prof()
    try:
        if p._enabled:
            _t0 = _time.perf_counter_ns()
            try:
                res = _eager_call_impl(
                    name, fn, tensor_args, attrs, differentiable,
                    nondiff_outputs, fn_key,
                )
            finally:
                p._record("op::" + name, _t0)
        else:
            res = _eager_call_impl(
                name, fn, tensor_args, attrs, differentiable, nondiff_outputs, fn_key
            )
    except Exception as e:
        # a RESOURCE_EXHAUSTED on the per-op path is classified (counter +
        # flight context) before it propagates — there is no per-op retry
        # rung; the flush/engine ladders own recovery (fault/memory.py)
        _note_oom(e, "eager:" + name)
        raise
    if _fault_inject is not None and _fault_inject.should_fire("tensor.nan", op=name):
        _fault_inject.poison_first_nan(res)
    return res


def _note_oom(e: BaseException, where: str) -> None:
    """Route a possible device-memory exhaustion through the ONE classifier
    (fault/memory.py). Import is lazy and only on the exception path — the
    unconfigured hot loop never touches the module (inert tripwire)."""
    from ..fault import memory as _mem

    if _mem.is_oom(e):
        _mem.note_oom(where, e)


def _eager_call_impl(
    name: str,
    fn: Callable,
    tensor_args: Sequence[Tensor],
    attrs: Optional[dict] = None,
    differentiable: bool = True,
    nondiff_outputs: Sequence[int] = (),
    fn_key=None,
):
    attrs = attrs or {}
    if _amp_hook is not None:
        tensor_args = _amp_hook(name, tensor_args)
    arrays = tuple(t._data for t in tensor_args)
    need_grad = (
        differentiable
        and grad_enabled()
        and any(not t.stop_gradient for t in tensor_args)
    )

    from ..framework import flags as _flags

    check_naninf = _flags.flag("FLAGS_check_nan_inf", False)

    # Lazy batching path: queue the op; execution happens in one XLA
    # computation at the next materialization point. Bypassed under jit
    # tracing (tracer inputs) and for unhashable attrs (no stable
    # executable-cache key). FLAGS_check_nan_inf does NOT bypass: the guard
    # runs as a post-flush scan (lazy.py), so the fused step keeps its
    # fusion and still raises within the same step the NaN is produced.
    has_tracer = any(isinstance(a, jax.core.Tracer) for a in arrays)
    if not has_tracer and lazy_mod.lazy_enabled():
        try:
            attrs_key = _attrs_key(attrs)
        except TypeError:
            attrs_key = None
        if attrs_key is not None:
            return _lazy_eager_call(
                name, fn, tensor_args, arrays, attrs, attrs_key,
                need_grad, nondiff_outputs, fn_key=fn_key,
            )
    if any(lazy_mod.is_lazy(a) for a in arrays):
        # per-op path (tracing / debug / unhashable attrs): jit args must be
        # real buffers, so pending lazy values materialize here
        arrays = tuple(lazy_mod.concrete(a) for a in arrays)

    if not need_grad:
        outs = _get_jitted(fn, attrs)(*arrays)
        single = not isinstance(outs, (tuple, list))
        if check_naninf:
            _check_nan_inf(name, (outs,) if single else outs)
        outs_t = [Tensor(o, stop_gradient=True) for o in ((outs,) if single else outs)]
        return outs_t[0] if single else outs_t

    # Differentiate ONLY wrt inputs that need grad (stop_gradient inputs are
    # closed over as constants). Skips dead grad work and avoids an XLA TPU
    # pathology: one program computing a conv's d/dinput AND d/dweight
    # compiles ~10-100x slower than either alone.
    need_idx = tuple(i for i, t in enumerate(tensor_args) if not t.stop_gradient)
    diff_arrays = tuple(arrays[i] for i in need_idx)

    def _over_diff(base_fn):
        def f(*dxs):
            full = list(arrays)
            for j, i in enumerate(need_idx):
                full[i] = dxs[j]
            return base_fn(*full)

        return f

    if nondiff_outputs:
        nondiff = set(nondiff_outputs)

        # has_aux carries the nondiff outputs out of one forward execution
        # (no double compute); we need the output count first — probe cheaply
        # with eval_shape (no FLOPs).
        probe = jax.eval_shape(lambda *xs: fn(*xs, **attrs), *arrays)
        n_out = len(probe) if isinstance(probe, (tuple, list)) else 1
        diff_idx = [i for i in range(n_out) if i not in nondiff]

        def split_fn(*xs):
            res = fn(*xs, **attrs)
            res = res if isinstance(res, (tuple, list)) else (res,)
            return tuple(res[i] for i in diff_idx), tuple(res[i] for i in sorted(nondiff))

        diff_outs, raw_vjp, aux = jax.vjp(_over_diff(split_fn), *diff_arrays, has_aux=True)
        outs = [None] * n_out
        for j, i in enumerate(diff_idx):
            outs[i] = diff_outs[j]
        for j, i in enumerate(sorted(nondiff)):
            outs[i] = aux[j]
        node_out_idx = {i: j for j, i in enumerate(diff_idx)}
        multi = True
        diff_list = list(diff_outs)
    else:
        # jax.vjp natively handles tuple outputs: cotangent structure matches.
        outs, raw_vjp = jax.vjp(_over_diff(lambda *xs: fn(*xs, **attrs)), *diff_arrays)
        multi = isinstance(outs, (tuple, list))
        outs = list(outs) if multi else [outs]
        node_out_idx = {i: i for i in range(len(outs))}
        diff_list = outs

    def vjp_fn(cts, _raw=raw_vjp, _n=len(arrays), _idx=need_idx):
        gs = _raw(cts)
        if not isinstance(gs, tuple):
            gs = (gs,)
        full = [None] * _n
        for j, i in enumerate(_idx):
            full[i] = gs[j]
        return tuple(full)

    routes = []
    for t in tensor_args:
        if t.stop_gradient:
            routes.append(None)
        elif t._grad_node is not None:
            routes.append(("node", t._grad_node, t._out_index))
        else:
            routes.append(("leaf", t))

    out_avals = [(tuple(o.shape), o.dtype) for o in diff_list]
    node = GradNode(name, vjp_fn, routes, out_avals, multi=multi)
    # Replay info for higher-order grads (create_graph): backward is re-run as
    # a recorded op over the ORIGINAL input tensors so d(grad)/d(input) exists.
    if nondiff_outputs:
        # replay must produce ONLY the differentiable outputs (cotangent
        # structure matches diff_outs): reuse split_fn and drop the aux part
        diff_fn = lambda *xs: split_fn(*xs)[0]
    else:
        diff_fn = lambda *xs: fn(*xs, **attrs)
    node.replay = (diff_fn, list(tensor_args), multi)

    if check_naninf:
        _check_nan_inf(name, outs)
    outs_t = []
    refs = [None] * len(out_avals)
    for i, o in enumerate(outs):
        if i in node_out_idx:
            t = Tensor(o, stop_gradient=False)
            t._grad_node = node
            t._out_index = node_out_idx[i]
            refs[node_out_idx[i]] = weakref.ref(t)
        else:
            t = Tensor(o, stop_gradient=True)
        outs_t.append(t)
    node.out_tensors = refs
    if len(outs_t) == 1 and not multi:
        return outs_t[0]
    return outs_t


def _lazy_eager_call(
    name, fn, tensor_args, arrays, attrs, attrs_key, need_grad, nondiff_outputs,
    fn_key=None,
):
    """Record the op into the lazy graph instead of executing it; autograd
    defers jax.vjp into the graph too (vjp composes under tracing), so a
    whole backward()+optimizer.step()+next-forward chain flushes as ONE
    compiled XLA computation."""
    key = ((fn_key if fn_key is not None else _fast_fn_key(fn)), attrs_key)
    fwd = lambda *xs: fn(*xs, **attrs)

    outs, single = lazy_mod.record(name, fwd, list(arrays), key=key)

    if not need_grad:
        outs_t = [Tensor(o, stop_gradient=True) for o in outs]
        return outs_t[0] if single else outs_t

    n_out = len(outs)
    nondiff = set(nondiff_outputs or ())
    diff_idx = [i for i in range(n_out) if i not in nondiff]
    if nondiff:
        def diff_fn(*xs, _idx=tuple(diff_idx)):
            res = fn(*xs, **attrs)
            res = res if isinstance(res, (tuple, list)) else (res,)
            return tuple(res[i] for i in _idx)

        vjp_multi = True
    else:
        diff_fn = fwd
        vjp_multi = not single

    n_in = len(arrays)
    # Differentiate ONLY wrt inputs that need grad. Besides skipping dead
    # work, this avoids an XLA TPU pathology where a conv that computes
    # d/dinput and d/dweight in one program compiles ~10-100x slower than
    # either alone (data inputs are stop_gradient, so the common case is
    # weight-only).
    need_idx = tuple(i for i, t in enumerate(tensor_args) if not t.stop_gradient)
    vjp_key = ("vjp", key, vjp_multi, n_in, tuple(sorted(nondiff)), need_idx)

    def deferred_vjp(cts):
        cts_list = list(cts) if vjp_multi else [cts]

        def bwd(*flat):
            xs = flat[:n_in]
            c = flat[n_in:]

            def f(*diff_xs):
                full = list(xs)
                for j, i in enumerate(need_idx):
                    full[i] = diff_xs[j]
                return diff_fn(*full)

            _, vjp = jax.vjp(f, *(xs[i] for i in need_idx))
            return vjp(tuple(c) if vjp_multi else c[0])

        outs_b, _ = lazy_mod.record(
            "vjp_" + name, bwd, list(arrays) + cts_list, key=vjp_key
        )
        grads = [None] * n_in
        for j, i in enumerate(need_idx):
            grads[i] = outs_b[j]
        return tuple(grads)

    routes = []
    for t in tensor_args:
        if t.stop_gradient:
            routes.append(None)
        elif t._grad_node is not None:
            routes.append(("node", t._grad_node, t._out_index))
        else:
            routes.append(("leaf", t))

    out_avals = [(tuple(outs[i].shape), outs[i].dtype) for i in diff_idx]
    node = GradNode(name, deferred_vjp, routes, out_avals, multi=vjp_multi)
    node.replay = (diff_fn, list(tensor_args), vjp_multi)
    node.replay_key = ("lz", key, vjp_multi, tuple(sorted(nondiff)))
    node.replay_arrays = list(arrays)  # forward-time input values

    node_out_idx = {i: j for j, i in enumerate(diff_idx)}
    outs_t = []
    refs = [None] * len(diff_idx)
    for i, o in enumerate(outs):
        if i in node_out_idx:
            t = Tensor(o, stop_gradient=False)
            t._grad_node = node
            t._out_index = node_out_idx[i]
            refs[node_out_idx[i]] = weakref.ref(t)
        else:
            t = Tensor(o, stop_gradient=True)
        outs_t.append(t)
    node.out_tensors = refs
    if len(outs_t) == 1 and single:
        return outs_t[0]
    return outs_t


def as_tensor(x, dtype=None):
    """Coerce scalars / numpy arrays / Tensors to Tensor (no copy when Tensor)."""
    if isinstance(x, Tensor):
        return x
    return Tensor(x, dtype=dtype)


def unary(name, fn, x, **attrs):
    return eager_call(name, fn, [as_tensor(x)], attrs)


def binary(name, fn, x, y, **attrs):
    return eager_call(name, fn, [as_tensor(x), as_tensor(y)], attrs)
