"""Eager reverse-mode autograd engine.

TPU-native analogue of the reference's dygraph autograd:
``paddle/fluid/imperative/basic_engine.h:31`` (BasicEngine: ready-queue over
grad nodes with dependency counting) and ``gradient_accumulator.h:28``
(multi-consumer gradient summation). Instead of registered grad ops, each
forward op captures a ``jax.vjp`` closure at trace time; backward replays the
closures in reverse topological order. ``paddle.grad`` -style partial grads
(reference ``partial_grad_engine.cc``) are supported via cotangent capture,
and ``create_graph=True`` re-records the backward as tape ops over the
original inputs so higher-order gradients work.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

# --------------------------------------------------------------------------
# Grad-enabled state (reference: tracer has_grad flag / paddle.no_grad)
# --------------------------------------------------------------------------
_grad_state = threading.local()


def grad_enabled() -> bool:
    return getattr(_grad_state, "enabled", True)


def set_grad_enabled(mode: bool) -> None:
    _grad_state.enabled = bool(mode)


class no_grad:
    """Context manager + decorator disabling autograd recording."""

    def __enter__(self):
        self._prev = grad_enabled()
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = grad_enabled()
        set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


# --------------------------------------------------------------------------
# Graph nodes
# --------------------------------------------------------------------------
class GradNode:
    """One recorded op: holds the vjp closure and routing to its inputs.

    ``input_routes[i]`` describes where the i-th input cotangent flows:
      - ``("leaf", tensor)``      : accumulate into tensor.grad
      - ``("node", node, index)`` : accumulate into upstream node's output ct
      - ``None``                  : grad discarded (stop_gradient input)
    """

    __slots__ = (
        "name",
        "vjp_fn",
        "input_routes",
        "out_avals",
        "out_tensors",
        "post_hooks",
        "multi",
        "replay",
        "replay_key",
        "replay_arrays",
    )

    def __init__(self, name: str, vjp_fn: Callable, input_routes, out_avals, multi=False):
        self.name = name
        self.vjp_fn = vjp_fn
        self.input_routes = input_routes
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.out_tensors = None  # weakrefs set by dispatch for capture
        self.post_hooks = []
        self.multi = multi  # vjp expects a tuple cotangent
        self.replay = None  # (diff_fn, input_tensors, multi) for create_graph
        self.replay_key = None  # stable identity of replay[0] (tape-bwd cache)
        self.replay_arrays = None  # input VALUES captured at forward time


# --------------------------------------------------------------------------
# Tape-level backward (lazy mode fast path)
# --------------------------------------------------------------------------
class _TapeFallback(Exception):
    pass


def _tape_backward(roots, grad_tensors, retain_graph):
    """Single-vjp backward: compose every recorded op's forward (GradNode
    .replay) into ONE function of the grad-requiring leaves and record ONE
    ``jax.vjp`` node over it. This reproduces exactly the program structure
    of a hand-written ``jax.value_and_grad`` step — one instance of each
    forward op inside the vjp — which XLA compiles orders of magnitude
    faster than a chain of per-op vjp subprograms (a TPU compiler pathology:
    modules with many separately-derived conv grads explode compile time).

    Returns {} on success, None to fall back to the per-node engine (hooks,
    PyLayer-style custom vjp without replay info, capture, create_graph).
    """
    from . import lazy as lazy_mod
    from .tensor import Tensor

    if any(isinstance(t._data, jax.core.Tracer) for t in roots):
        return None

    def _check(gn):
        if gn.replay is None or gn.vjp_fn is None or gn.post_hooks:
            raise _TapeFallback
        if gn.out_tensors:
            for r in gn.out_tensors:
                t = r() if callable(r) else None
                if t is not None and t._backward_hooks:
                    raise _TapeFallback

    def _children(gn):
        return [r[1] for r in gn.input_routes if r is not None and r[0] == "node"]

    # iterative post-order DFS (deep chains must not hit the Python
    # recursion limit — the per-node engine this path replaces is iterative)
    nodes, state = [], {}
    try:
        for t in roots:
            gn = t._grad_node
            if gn is None or id(gn) in state:
                continue
            _check(gn)
            state[id(gn)] = 0
            stack = [(gn, iter(_children(gn)))]
            while stack:
                node, it = stack[-1]
                advanced = False
                for child in it:
                    if id(child) not in state:
                        _check(child)
                        state[id(child)] = 0
                        stack.append((child, iter(_children(child))))
                        advanced = True
                        break
                if not advanced:
                    nodes.append(node)
                    stack.pop()
    except _TapeFallback:
        return None
    if not nodes:
        return None

    node_ix = {id(n): i for i, n in enumerate(nodes)}
    diff_leaves, const_inputs = [], []
    leaf_ix, const_ix = {}, {}
    descs, sig = [], []
    from .dispatch import _fn_key

    leaf_values = []
    for n in nodes:
        diff_fn, in_tensors, _multi = n.replay
        # gradients must be taken at the values CAPTURED at forward time, not
        # at the tensors' current _data (a _set_data between forward and
        # backward must not change the result — vjp-closure semantics)
        arrs = n.replay_arrays
        for k, t in enumerate(in_tensors):
            a = arrs[k] if arrs is not None else t._data
            dn_kind = n.input_routes[k]
            if dn_kind is None:
                # key on (tensor, captured array): a tensor mutated via
                # _set_data between two forward uses captured two distinct
                # arrays, and each use must replay its own value
                j = const_ix.get((id(t), id(a)))
                if j is None:
                    j = len(const_inputs)
                    const_ix[(id(t), id(a))] = j
                    const_inputs.append(a)
            elif dn_kind[0] == "leaf":
                t2 = dn_kind[1]
                j = leaf_ix.get(id(t2))
                if j is None:
                    j = len(diff_leaves)
                    leaf_ix[id(t2)] = j
                    diff_leaves.append(t2)
                    leaf_values.append(a)
                elif leaf_values[j] is not a:
                    # same differentiable leaf captured with two different
                    # values (mutated mid-iteration): a single-value vjp
                    # replay would be wrong — fall back to per-node engine
                    return None
        dn = []
        for k, (t, route) in enumerate(zip(in_tensors, n.input_routes)):
            if route is None:
                a = arrs[k] if arrs is not None else t._data
                dn.append(("c", const_ix[(id(t), id(a))]))
            elif route[0] == "node":
                dn.append(("n", node_ix[id(route[1])], route[2]))
            else:
                dn.append(("l", leaf_ix[id(route[1])]))
        descs.append(tuple(dn))
        rk = n.replay_key
        if rk is None:
            try:
                rk = _fn_key(diff_fn)
                hash(rk)
            except Exception:
                return None  # unstable identity would recompile per step
        sig.append((n.name, rk, tuple(dn)))
    if not diff_leaves:
        return None

    # root refs + cotangent seeds
    root_refs, cts = [], []
    for t, g in zip(roots, grad_tensors):
        if t._grad_node is None:
            continue  # leaf root: seeded by caller path below
        root_refs.append(("n", node_ix[id(t._grad_node)], t._out_index))
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires grad_tensors"
                )
            cts.append(
                lazy_mod.lazy_full(tuple(t._data.shape), t._data.dtype, 1.0, name="grad_seed")
            )
        else:
            cts.append(g._data if isinstance(g, Tensor) else jnp.asarray(g))

    replays = [n.replay[0] for n in nodes]
    nL, nC = len(diff_leaves), len(const_inputs)
    root_refs_t = tuple(root_refs)

    def tape_bwd(*flat):
        lv_outer = flat[:nL]
        consts_v = flat[nL : nL + nC]
        cts_v = flat[nL + nC :]

        def fwd_fn(*lv):
            env = [None] * len(replays)
            for i, f in enumerate(replays):
                args = []
                for d in descs[i]:
                    if d[0] == "l":
                        args.append(lv[d[1]])
                    elif d[0] == "c":
                        args.append(consts_v[d[1]])
                    else:
                        args.append(env[d[1]][d[2]])
                o = f(*args)
                env[i] = tuple(o) if isinstance(o, (tuple, list)) else (o,)
            return tuple(env[i][j] for (_, i, j) in root_refs_t)

        primals, vjp = jax.vjp(fwd_fn, *lv_outer)
        # returning the primals too lets the caller rewire root tensors onto
        # THIS node, so the separately-recorded forward chain goes dead and
        # XLA sees each forward op exactly once (value_and_grad structure)
        return tuple(vjp(tuple(cts_v))) + tuple(primals)

    try:
        outs_all, _ = lazy_mod.record(
            "tape_backward",
            tape_bwd,
            leaf_values + const_inputs + cts,
            key=("tape", tuple(sig), root_refs_t),
        )
    except Exception:
        return None  # non-traceable replay fn → per-node engine
    grads_out = outs_all[:nL]
    primal_out = outs_all[nL:]

    # rewire roots onto the tape primals (frees the fwd chain for DCE when
    # nothing else holds its intermediates)
    j = 0
    for t in roots:
        if t._grad_node is None:
            continue
        if isinstance(t._data, lazy_mod.LazyArray) and t._data._concrete is None:
            t._data = primal_out[j]
        j += 1

    # free graphs (match "backward twice" semantics of the per-node engine);
    # replay tensors are dropped so forward intermediates can die
    if not retain_graph:
        for n in nodes:
            n.vjp_fn = None
            n.replay = None
            n.replay_arrays = None
            n.out_tensors = None

    # leaf accumulation (+ leaf hooks, same semantics as the per-node path)
    for t, g in zip(diff_leaves, grads_out):
        hook_g = g
        for hook in t._backward_hooks:
            out = hook(Tensor(hook_g) if not isinstance(hook_g, Tensor) else hook_g)
            if out is not None:
                hook_g = out._data if isinstance(out, Tensor) else out
        g_arr = hook_g._data if isinstance(hook_g, Tensor) else hook_g
        if t.grad is None:
            t.grad = Tensor(g_arr, stop_gradient=True)
        else:
            # accumulation rebinds the grad buffer through the graph: the
            # displaced buffer is donatable once nothing else references it
            old = t.grad._data
            t.grad._data = lazy_mod.maybe_lazy_binary(
                jnp.add, old, g_arr, name="grad_acc"
            )
            lazy_mod.note_rebound(old)

    # leaf roots seed directly
    for t, g in zip(roots, grad_tensors):
        if t._grad_node is not None or t.stop_gradient:
            continue
        seed = (
            g._data if isinstance(g, Tensor)
            else (jnp.asarray(g) if g is not None
                  else lazy_mod.lazy_full(tuple(t._data.shape), t._data.dtype, 1.0, name="grad_seed"))
        )
        if t.grad is None:
            t.grad = Tensor(seed, stop_gradient=True)
        else:
            old = t.grad._data
            t.grad._data = lazy_mod.maybe_lazy_binary(jnp.add, old, seed, name="grad_acc")
            lazy_mod.note_rebound(old)
    return {}


# --------------------------------------------------------------------------
# Engine
# --------------------------------------------------------------------------
def run_backward(
    tensors: Sequence[Any],
    grad_tensors: Optional[Sequence[Any]] = None,
    retain_graph: bool = False,
    capture: Optional[dict] = None,
    accumulate_leaves: bool = True,
    create_graph: bool = False,
):
    """Execute reverse pass from ``tensors`` (the roots).

    ``capture`` maps ``id(tensor) -> tensor`` for paddle.grad-style queries;
    returns ``{id: grad}`` for captured tensors (arrays, or Tensors when
    ``create_graph``).

    Mirrors BasicEngine::Execute (reference basic_engine.cc): init ready queue
    from root nodes, dependency-count every reachable node, pop/run/route.
    """
    from .tensor import Tensor

    roots = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(roots)

    captured: dict = {}
    capture = capture or {}

    if not create_graph and not capture and accumulate_leaves:
        from . import lazy as _lz_mod

        if _lz_mod.lazy_enabled():
            res = _tape_backward(roots, grad_tensors, retain_graph)
            if res is not None:
                return res

    if create_graph:
        from .dispatch import eager_call

        def _acc(dst, g):
            if dst is None:
                return g
            return eager_call("grad_acc", jnp.add, [dst, g])

        def _zeros(shape, dtype):
            return Tensor(jnp.zeros(shape, dtype))

        def _wrap(g, ref_t):
            if isinstance(g, Tensor):
                return g
            return Tensor(jnp.asarray(g, dtype=ref_t._data.dtype))
    else:
        from . import lazy as lazy_mod

        def _acc(dst, g):
            a = g._data if isinstance(g, Tensor) else g
            if dst is None:
                return a
            d = dst._data if isinstance(dst, Tensor) else dst
            return lazy_mod.maybe_lazy_binary(jnp.add, d, a, name="grad_acc")

        def _zeros(shape, dtype):
            return lazy_mod.lazy_full(shape, dtype, 0.0, name="grad_zeros")

        def _wrap(g, ref_t):
            if isinstance(g, Tensor):
                return g._data
            if lazy_mod.is_lazy(g):
                return g.astype(ref_t._data.dtype)
            return jnp.asarray(g, dtype=ref_t._data.dtype)

    # Seed cotangents. pending[node][out_idx] = accumulated cotangent.
    pending: dict = {}
    leaf_grads: dict = {}  # id(tensor) -> (tensor, grad)

    def seed_leaf(t, g):
        if accumulate_leaves and not t.stop_gradient:
            key = id(t)
            prev = leaf_grads.get(key, (t, None))[1]
            leaf_grads[key] = (t, _acc(prev, g))
        if id(t) in capture:
            captured[id(t)] = _acc(captured.get(id(t)), g)

    root_nodes = []
    from . import lazy as _lz

    for t, g in zip(roots, grad_tensors):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires grad_tensors"
                )
            seed = _lz.lazy_full(
                tuple(t._data.shape), t._data.dtype, 1.0, name="grad_seed"
            ) if not create_graph else jnp.ones(t._data.shape, dtype=t._data.dtype)
            g = _wrap(seed, t)
        else:
            g = _wrap(g, t)
        node = t._grad_node
        if node is None:
            seed_leaf(t, g)
            continue
        pmap = pending.setdefault(id(node), {})
        idx = t._out_index
        pmap[idx] = _acc(pmap.get(idx), g)
        root_nodes.append(node)
        # NB: no capture here — a node-produced root is captured exactly once
        # when its producing node is processed (out_tensors scan), which sees
        # this seed in the pending cotangents.

    # Reachability + dependency counting (consumer edges per node).
    deps: dict = {}
    node_by_id: dict = {}
    seen = set()
    stack = list(root_nodes)
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        node_by_id[id(node)] = node
        for route in node.input_routes:
            if route is not None and route[0] == "node":
                parent = route[1]
                deps[id(parent)] = deps.get(id(parent), 0) + 1
                stack.append(parent)

    queue = [n for n in dict.fromkeys(id(n) for n in root_nodes) if deps.get(n, 0) == 0]
    for n in root_nodes:
        node_by_id[id(n)] = n
    queue = [node_by_id[i] for i in queue]
    processed = set()
    while queue:
        node = queue.pop()
        if id(node) in processed:
            continue
        processed.add(id(node))
        cts_map = pending.pop(id(node), {})
        cts = tuple(
            cts_map.get(i)
            if cts_map.get(i) is not None
            else _zeros(shape, dtype)
            for i, (shape, dtype) in enumerate(node.out_avals)
        )
        # Non-leaf tensor hooks (Tensor.register_hook) transform the cotangent
        # flowing through the tensor — the reference invokes hooks on any
        # autograd-tracked tensor, not just leaves.
        if node.out_tensors is not None:
            cts = list(cts)
            for i, ref in enumerate(node.out_tensors):
                t = ref() if callable(ref) else None
                if t is not None and t._backward_hooks:
                    hook_g = cts[i]
                    for hook in t._backward_hooks:
                        arg = hook_g if isinstance(hook_g, Tensor) else Tensor(hook_g)
                        out = hook(arg)
                        if out is not None:
                            hook_g = out if create_graph else (
                                out._data if isinstance(out, Tensor) else out
                            )
                    cts[i] = hook_g
            cts = tuple(cts)
        # Capture cotangents of intermediate tensors produced by this node.
        if node.out_tensors is not None:
            for i, ref in enumerate(node.out_tensors):
                t = ref() if callable(ref) else None
                if t is not None and id(t) in capture:
                    captured[id(t)] = _acc(captured.get(id(t)), cts[i])

        if create_graph and node.replay is not None:
            diff_fn, inputs_t, multi = node.replay
            n_in = len(inputs_t)

            def replay_fn(*all_args, n_in=n_in, multi=multi, diff_fn=diff_fn):
                xs = all_args[:n_in]
                cts_a = all_args[n_in:]
                _, vjp_fn = jax.vjp(diff_fn, *xs)
                return vjp_fn(tuple(cts_a) if multi else cts_a[0])

            from .dispatch import eager_call

            out = eager_call("grad_" + node.name, replay_fn, list(inputs_t) + list(cts))
            in_grads = out if isinstance(out, (list, tuple)) else [out]
        else:
            if node.vjp_fn is None:
                raise RuntimeError(
                    f"Trying to backward through the graph a second time (node "
                    f"'{node.name}' was already freed). Specify retain_graph=True "
                    f"on the first backward call if you need to backward twice."
                )
            in_grads = node.vjp_fn(
                tuple(c._data if hasattr(c, "_data") else c for c in cts)
                if node.multi
                else (cts[0]._data if hasattr(cts[0], "_data") else cts[0])
            )
            if not isinstance(in_grads, tuple):
                in_grads = (in_grads,)
        for hook in node.post_hooks:
            hook()
        if not retain_graph and not create_graph:
            node.vjp_fn = None  # free residuals eagerly (reference GC parity)
        for route, g in zip(node.input_routes, in_grads):
            if route is None or g is None:
                continue
            kind = route[0]
            if kind == "leaf":
                seed_leaf(route[1], g)
            else:
                _, parent, idx = route
                pmap = pending.setdefault(id(parent), {})
                pmap[idx] = _acc(pmap.get(idx), g)
                deps[id(parent)] -= 1
                if deps[id(parent)] == 0:
                    queue.append(parent)

    for t, g in leaf_grads.values():
        hook_g = g
        for hook in t._backward_hooks:
            out = hook(Tensor(hook_g) if not isinstance(hook_g, Tensor) else hook_g)
            if out is not None:
                hook_g = out._data if isinstance(out, Tensor) else out
        g_arr = hook_g._data if isinstance(hook_g, Tensor) else hook_g
        if t.grad is None:
            t.grad = Tensor(g_arr, stop_gradient=True)
        else:
            from . import lazy as lazy_mod

            old = t.grad._data
            t.grad._data = lazy_mod.maybe_lazy_binary(
                jnp.add, old, g_arr, name="grad_acc"
            )
            lazy_mod.note_rebound(old)

    return captured
