"""Cross-version JAX API shims.

The public homes of ``shard_map`` and ``export`` moved between jax releases:

* ``shard_map``: ``jax.experimental.shard_map.shard_map`` (<= 0.4.x, kwarg
  ``check_rep``) became ``jax.shard_map`` (>= 0.5, kwarg ``check_vma``).
* ``export``: ``jax.experimental.export`` (<= 0.4.2x) became ``jax.export``
  (a lazily-imported submodule — plain attribute access on ``jax`` raises
  AttributeError until something imports it).

Every in-repo and in-test use goes through this module so a jax upgrade is a
one-file change (SURVEY §4: version-drift collection errors silently dropped
three files from tier-1).
"""
from __future__ import annotations

import os

import jax

__all__ = [
    "shard_map", "shard_map_check_kwargs", "jax_export", "axis_size",
    "enable_persistent_compilation_cache",
]

try:  # jax >= 0.5: stable API, replication check renamed to check_vma
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _CHECK_KW = "check_vma"
except ImportError:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

try:  # jax >= 0.5: promoted out of experimental
    from jax import enable_x64  # type: ignore[attr-defined]  # noqa: F401
except ImportError:
    from jax.experimental import enable_x64  # noqa: F401


def shard_map(f, *args, **kwargs):
    """``jax.shard_map`` resolved across versions; accepts either spelling of
    the replication-check kwarg (``check_vma``/``check_rep``) and translates
    to whatever this jax understands."""
    for alias in ("check_vma", "check_rep"):
        if alias in kwargs and alias != _CHECK_KW:
            kwargs[_CHECK_KW] = kwargs.pop(alias)
    return _shard_map(f, *args, **kwargs)


def shard_map_check_kwargs(value=False):
    """Kwargs dict disabling (or enabling) the replication check, spelled for
    this jax version: ``{"check_vma": value}`` or ``{"check_rep": value}``."""
    return {_CHECK_KW: value}


def axis_size(axis: str) -> int:
    """Size of a bound manual mesh axis; raises (NameError) when ``axis`` is
    not bound. ``lax.axis_size`` only exists on newer jax — the classic
    spelling is ``psum(1, axis)``, which constant-folds to the axis size
    inside shard_map/pmap and raises outside one."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    return lax.psum(1, axis)


def jax_export():
    """The export module (``jax.export`` on >= 0.4.30, else
    ``jax.experimental.export``). Importing it also binds the ``jax.export``
    attribute, so legacy ``jax.export.deserialize`` call sites work after any
    paddle_tpu import."""
    try:
        import jax.export as m  # submodule import works even when the lazy
        return m  # attribute on `jax` hasn't been materialized
    except ImportError:
        from jax.experimental import export as m

        return m


def enable_persistent_compilation_cache():
    """Point JAX's persistent compilation cache at a paddle_tpu-owned dir so
    re-runs warm-start compiles (the flush-executable signatures are stable
    across processes). Controlled by ``FLAGS_xla_persistent_cache`` (default
    on) and ``FLAGS_xla_persistent_cache_dir``. Returns the dir or None."""
    from ..framework import flags as _flags

    if not _flags.flag("FLAGS_xla_persistent_cache", True):
        return None
    # Respect a cache the host application already configured (env var or
    # jax.config.update before importing paddle_tpu) — the compilation cache
    # is process-global and hijacking it would cold-start their workloads.
    existing = getattr(jax.config, "jax_compilation_cache_dir", None)
    if existing:
        return existing
    d = _flags.flag("FLAGS_xla_persistent_cache_dir") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu", "xla"
    )
    try:
        os.makedirs(d, exist_ok=True)
        _atomic_cache_writes()
        # jax's default threshold (1s) is tuned for serving-sized programs;
        # a train step's flush executable compiles faster than that on CPU
        # yet is exactly what a warm restart wants back. Set the threshold
        # BEFORE the dir: if either option is missing on this jax, nothing
        # is half-activated (a threshold without a dir is inert).
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(_flags.flag("FLAGS_xla_persistent_cache_min_compile_secs", 0.5)),
        )
        jax.config.update("jax_compilation_cache_dir", d)
        return d
    except Exception:
        return None


_atomic_writes_patched = False


def _atomic_cache_writes():
    """Make the persistent-cache entry write ATOMIC on jax versions whose
    ``LRUCache.put`` uses a bare ``write_bytes`` (jax<=0.4.x): a process
    killed mid-write (the common fate of driver-timed-out benches, SIGKILL)
    leaves a truncated serialized executable, and every later process that
    deserializes it crashes — observed as a deterministic segfault in a
    single test until the cache dir is cleared. tmp-file + ``os.replace``
    makes a torn entry impossible; readers either see nothing or a full
    write. No-op when the jax version has no patchable LRUCache."""
    global _atomic_writes_patched
    if _atomic_writes_patched:
        return
    try:
        from jax._src import lru_cache as _lru

        orig_put = _lru.LRUCache.put
        suffix = getattr(_lru, "_CACHE_SUFFIX", ".bin")

        def atomic_put(self, key, val):
            # Pre-write the payload file atomically; the original put then
            # sees it existing and skips its own (torn-write-prone)
            # write_bytes while still doing the lock/atime bookkeeping.
            # Thread/process-safe: no global state, and a concurrent
            # os.replace of the same entry just wins with identical bytes.
            # (When LRU eviction is explicitly enabled, a pre-written entry
            # escapes the eviction size accounting — acceptable: this repo
            # runs the cache unbounded, and a slightly-over-budget cache
            # beats a segfaulting one.)
            if key:
                try:
                    import time as _time

                    path = self.path / f"{key}{suffix}"
                    if not path.exists():
                        # atime sidecar FIRST: orig_put early-returns on an
                        # existing payload without writing it, and eviction
                        # read_bytes()-es every entry's atime
                        atime = self.path / f"{key}{getattr(_lru, '_ATIME_SUFFIX', '.atime')}"
                        atime.write_bytes(_time.time_ns().to_bytes(8, "little"))
                        tmp = path.with_name(f"{path.name}.tmp{os.getpid()}")
                        tmp.write_bytes(val)
                        os.replace(tmp, path)
                except OSError:
                    pass  # fall through: orig_put raises or handles it
            return orig_put(self, key, val)

        _lru.LRUCache.put = atomic_put
        _atomic_writes_patched = True
    except Exception:
        pass
