"""Eager Tensor.

TPU-native analogue of the reference's dygraph ``VarBase``
(``paddle/fluid/imperative/layer.h:66``): a named, autograd-tracked handle over
a device buffer. Here the buffer is a ``jax.Array`` (PJRT-owned HBM), autograd
metadata is a ``GradNode`` reference (cf. reference ``grad_node_info.h``), and
methods are attached by the op library at import time — mirroring the
reference's ``varbase_patch_methods.py`` monkey-patch design.
"""
from __future__ import annotations

import weakref
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes
from . import place as place_mod
from .engine import run_backward, no_grad
from .lazy import LazyArray, note_rebound, timed_block as lazy_timed_block

_tensor_count = 0


def _next_name(prefix="eager_tmp"):
    global _tensor_count
    _tensor_count += 1
    return f"{prefix}_{_tensor_count}"


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "name",
        "persistable",
        "_backward_hooks",
        # distributed layout annotations (GSPMD PartitionSpecs)
        "pspec",
        "opt_state_pspec",
        "grad_pspec",
        "__weakref__",
    )

    def __init__(self, data, dtype=None, place=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        dt = dtypes.convert_dtype(dtype) if dtype is not None else None
        if isinstance(data, (jax.Array, LazyArray)):
            arr = data if dt is None else data.astype(dt)
        else:
            np_arr = np.asarray(data)
            if dt is None and np_arr.dtype == np.float64:
                dt = dtypes.get_default_dtype()  # paddle default-dtype semantics
            arr = jnp.asarray(np_arr, dtype=dt)
        if place is not None:
            arr = jax.device_put(arr, place.jax_device())
        self._data = arr
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self.name = name or _next_name()
        self.persistable = False
        self._backward_hooks = []
        self.pspec = None
        self.opt_state_pspec = None
        self.grad_pspec = None

    # -- metadata ---------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        try:
            dev = next(iter(self._data.devices()))
            return place_mod.Place(dev.platform, dev.id)
        except Exception:
            return place_mod.current_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    # -- host interop -----------------------------------------------------
    def numpy(self):
        d = self._data
        if isinstance(d, LazyArray):
            d = d._value()
        # attributed host wait (async runtime): the time spent here waiting
        # for the device is the dispatch gap, not an anonymous np.asarray
        return np.asarray(lazy_timed_block(d))

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is ambiguous"
            )
        if isinstance(self._data, jax.core.Tracer):
            raise TypeError(
                "bool() on a traced Tensor: data-dependent Python control flow "
                "inside jit/to_static needs conversion — use tensor-assigning "
                "`if`/`while` bodies (converted to lax.cond/while_loop by "
                "to_static) or paddle.static.nn.cond/while_loop; `return` "
                "inside a tensor-dependent branch is not convertible"
            )
        return bool(self.item())

    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __repr__(self):
        return (
            f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
            f"place={self.place}, stop_gradient={self.stop_gradient},\n"
            f"       {np.array2string(self.numpy(), prefix='       ')})"
        )

    # -- autograd ---------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        self._backward_hooks.append(hook)

        class _Removable:
            def remove(self_inner):
                if hook in self._backward_hooks:
                    self._backward_hooks.remove(hook)

        return _Removable()

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    @property
    def grad_fn(self):
        return self._grad_node

    # -- in-place / value management (optimizer fast path) ----------------
    def _set_data(self, arr):
        """Replace the underlying buffer (used by optimizers & loaders).
        The displaced buffer becomes a donation candidate for the pending
        lazy flush — if it only feeds the queued computation (the optimizer
        rebind pattern), XLA gets to update it in place."""
        old = self._data
        if old is not arr:
            note_rebound(old)
        self._data = arr

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        arr = jnp.asarray(value, dtype=self._data.dtype)
        if tuple(arr.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._data.shape}"
            )
        note_rebound(self._data)
        self._data = arr

    def copy_(self, other):
        self.set_value(other)
        return self

    def pin_memory(self):
        return self

    def cpu(self):
        return Tensor(
            jax.device_put(self._data, jax.devices("cpu")[0]),
            stop_gradient=self.stop_gradient,
        )

    def to(self, *args, **kwargs):
        dtype = None
        place = None
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, place_mod.Place):
                place = a
            elif isinstance(a, str) and (":" in a or a in ("cpu", "tpu", "gpu")):
                place = _parse_place(a)
            else:
                dtype = a
        arr = self._data
        if dtype is not None:
            arr = arr.astype(dtypes.convert_dtype(dtype))
        if place is not None:
            arr = jax.device_put(arr, place.jax_device())
        return Tensor(arr, stop_gradient=self.stop_gradient)

    # NumPy-style protocol hooks so jnp.asarray(tensor) works.
    def __jax_array__(self):
        return self._data


def _parse_place(device: str):
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    return place_mod.Place({"xla": "tpu", "cuda": "gpu"}.get(name, name), idx)


class Parameter(Tensor):
    """Trainable tensor (reference: ``framework.Parameter`` /
    ``VarBase`` with persistable=True, stop_gradient=False)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(
            data, dtype=dtype, stop_gradient=not trainable, name=name or _next_name("param")
        )
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
