"""Global RNG state.

Analogue of the reference's generator (``paddle/fluid/framework/generator.cc``,
``paddle.seed``). JAX PRNG is functional, so the "global generator" is a key
that is split on every random op. When tracing a program (jit/to_static), the
tracer installs a traced key provider so randomness becomes a program input
rather than a baked-in constant — this is what makes dropout work under jit
(cf. reference RNG-state control for parallel layers,
``fleet/meta_parallel/parallel_layers/random.py:32``).
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()


def _get():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
        _state.provider = None
    return _state


def seed(s: int):
    st = _get()
    st.key = jax.random.PRNGKey(int(s))
    return st.key


def next_key():
    """Return a fresh subkey. Inside a trace, defers to the installed provider."""
    st = _get()
    if getattr(st, "provider", None) is not None:
        return st.provider()
    st.key, sub = jax.random.split(st.key)
    return sub


class traced_keys:
    """Install a traced key provider during program capture."""

    def __init__(self, base_key):
        self.base_key = base_key
        self.count = 0

    def __enter__(self):
        st = _get()
        self._prev = getattr(st, "provider", None)

        def provider():
            sub = jax.random.fold_in(self.base_key, self.count)
            self.count += 1
            return sub

        st.provider = provider
        return self

    def __exit__(self, *exc):
        _get().provider = self._prev
        return False


def get_rng_state():
    return _get().key


def set_rng_state(key):
    _get().key = key


class ProgramRNG:
    """Checkpointable view of the global RNG stream: put ``program_rng`` in
    a checkpoint tree (``{"model": m, "opt": o, "rng": program_rng}``) and a
    resumed run continues the SAME key-split sequence — together with the
    DataLoader's ``state_dict`` this is what makes an interrupted run replay
    bit-identical steps (sample-exact resume)."""

    def state_dict(self):
        import numpy as np

        return {"key": np.asarray(jax.random.key_data(_get().key))}

    def set_state_dict(self, sd):
        import jax.numpy as jnp

        key = sd["key"]
        _get().key = jnp.asarray(key, dtype=jnp.uint32)


program_rng = ProgramRNG()
