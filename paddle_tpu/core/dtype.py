"""Dtype system.

TPU-native analogue of the reference's ``paddle/phi/common/data_type.h`` /
``python/paddle/fluid/core.VarDesc.VarType`` dtype enums: instead of a protobuf
enum we alias numpy/JAX dtypes directly, keeping paddle-style names
(``paddle.float32`` etc.) so user code reads identically.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# Canonical dtype objects (numpy dtypes; JAX accepts them everywhere).
bool_ = np.dtype("bool")
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = jnp.bfloat16.dtype  # ml_dtypes bfloat16 — first-class on TPU (MXU-native)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_NAME_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGER = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}


def convert_dtype(dtype):
    """Normalize any user-supplied dtype spec to a numpy/ml_dtypes dtype.

    Mirrors the reference's ``convert_dtype``
    (``python/paddle/fluid/data_feeder.py``) but without the VarType enum hop.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = dtype.replace("paddle.", "")
        if name not in _NAME_TO_DTYPE:
            raise TypeError(f"Unsupported dtype string: {dtype!r}")
        return _NAME_TO_DTYPE[name]
    try:
        return np.dtype(dtype)
    except TypeError:
        pass
    if hasattr(dtype, "dtype"):
        return np.dtype(dtype.dtype)
    raise TypeError(f"Unsupported dtype: {dtype!r}")


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOATING


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in _INTEGER


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in _COMPLEX


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    for name, v in _NAME_TO_DTYPE.items():
        if v == d:
            return name
    return str(d)


# Default dtype handling (paddle.get_default_dtype / set_default_dtype).
_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError("set_default_dtype only accepts floating dtypes")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype
