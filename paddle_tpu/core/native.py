"""ctypes bindings to the C++ runtime (runtime_cpp/libpaddle_tpu_runtime.so).

The reference's native runtime pieces we keep native: the feed-path blocking
queue (operators/reader/blocking_queue.h), TCPStore rendezvous
(distributed/store/tcp_store.cc), host event recorder
(platform/profiler/host_event_recorder.h) and the host staging allocator
(memory/allocation/*). Built on demand with `make` (g++); every consumer has
a pure-Python fallback so the framework works before the first build.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_RUNTIME_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "runtime_cpp")
_SO = os.path.join(_RUNTIME_DIR, "libpaddle_tpu_runtime.so")

_lib = None
_lock = threading.Lock()

# True when the loaded .so carries the profiler span ring
# (trace.cc ptt_span_record/ptt_span_drain); stale builds predate it.
HAS_SPANS = False

# True when the loaded .so carries the host-embedding PS kernels
# (embed.cc pte_unique/pte_gather_f32/...); stale builds predate them and
# the host-embedding table falls back to pure numpy.
HAS_EMBED = False


def _build():
    subprocess.run(["make", "-C", _RUNTIME_DIR], check=True, capture_output=True)


def _stale() -> bool:
    """True when any runtime source is newer than the built .so."""
    try:
        so_m = os.path.getmtime(_SO)
        for name in os.listdir(_RUNTIME_DIR):
            if name.endswith((".cc", ".h")) and os.path.getmtime(
                    os.path.join(_RUNTIME_DIR, name)) > so_m:
                return True
    except OSError:
        return False
    return False


def lib():
    """Load (building if needed) the native runtime; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        try:
            if not os.path.exists(_SO) or _stale():
                # make's own mtime check keeps the rebuild a no-op when
                # nothing changed; calling it whenever a source is newer
                # means an upgraded checkout can't load a stale .so that
                # lacks newly added symbols
                _build()
            L = ctypes.CDLL(_SO)
        except Exception:
            return None
        # queue
        L.ptq_create.restype = ctypes.c_void_p
        L.ptq_create.argtypes = [ctypes.c_int64]
        L.ptq_push.restype = ctypes.c_int
        L.ptq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        L.ptq_pop_size.restype = ctypes.c_int64
        L.ptq_pop_size.argtypes = [ctypes.c_void_p]
        L.ptq_pop_into.restype = ctypes.c_int64
        L.ptq_pop_into.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        L.ptq_close.argtypes = [ctypes.c_void_p]
        L.ptq_size.restype = ctypes.c_int64
        L.ptq_size.argtypes = [ctypes.c_void_p]
        L.ptq_destroy.argtypes = [ctypes.c_void_p]
        # store
        L.pts_server_create.restype = ctypes.c_void_p
        L.pts_server_create.argtypes = [ctypes.c_int]
        L.pts_server_destroy.argtypes = [ctypes.c_void_p]
        L.pts_client_create.restype = ctypes.c_void_p
        L.pts_client_create.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        L.pts_client_destroy.argtypes = [ctypes.c_void_p]
        L.pts_request.restype = ctypes.c_int
        L.pts_request.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        # trace
        L.ptt_create.restype = ctypes.c_void_p
        L.ptt_create.argtypes = [ctypes.c_int64]
        L.ptt_destroy.argtypes = [ctypes.c_void_p]
        L.ptt_intern.restype = ctypes.c_uint32
        L.ptt_intern.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.ptt_now_ns.restype = ctypes.c_uint64
        L.ptt_record.argtypes = [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint64, ctypes.c_uint64]
        L.ptt_drain.restype = ctypes.c_int64
        L.ptt_drain.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        L.ptt_name.restype = ctypes.c_char_p
        L.ptt_name.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        L.ptt_reset.argtypes = [ctypes.c_void_p]
        # trace span ring (absent from pre-span builds of the .so)
        global HAS_SPANS
        try:
            L.ptt_span_record.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64,
            ]
            L.ptt_span_drain.restype = ctypes.c_int64
            L.ptt_span_drain.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ]
            HAS_SPANS = True
        except AttributeError:
            HAS_SPANS = False
        # host-embedding PS kernels (absent from pre-embed builds)
        global HAS_EMBED
        try:
            L.pte_unique.restype = ctypes.c_int64
            L.pte_unique.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64,
            ]
            L.pte_gather_f32.restype = ctypes.c_int
            L.pte_gather_f32.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int64,
            ]
            L.pte_sgd_f32.restype = ctypes.c_int
            L.pte_sgd_f32.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_float, ctypes.c_int64,
            ]
            L.pte_adagrad_f32.restype = ctypes.c_int
            L.pte_adagrad_f32.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            L.pte_merge_f32.restype = ctypes.c_int64
            L.pte_merge_f32.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64,
            ]
            HAS_EMBED = True
        except AttributeError:
            HAS_EMBED = False
        # arena
        L.pta_create.restype = ctypes.c_void_p
        L.pta_create.argtypes = [ctypes.c_int64]
        L.pta_destroy.argtypes = [ctypes.c_void_p]
        L.pta_alloc.restype = ctypes.c_void_p
        L.pta_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        L.pta_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        L.pta_bytes.restype = ctypes.c_int64
        L.pta_bytes.argtypes = [ctypes.c_void_p]
        L.pta_reused.restype = ctypes.c_int64
        L.pta_reused.argtypes = [ctypes.c_void_p]
        _lib = L
        return _lib


class NativeQueue:
    """Bounded blocking byte-buffer queue backed by C++ (GIL-free copies)."""

    def __init__(self, capacity: int):
        L = lib()
        if L is None:
            raise RuntimeError("native runtime unavailable")
        self._L = L
        self._q = L.ptq_create(capacity)

    def push(self, data: bytes) -> bool:
        return self._L.ptq_push(self._q, data, len(data)) == 0

    def pop(self):
        n = self._L.ptq_pop_size(self._q)
        if n <= 0:
            return None
        buf = ctypes.create_string_buffer(int(n))
        got = self._L.ptq_pop_into(self._q, buf, n)
        if got <= 0:
            return None
        return buf.raw[: int(got)]

    def close(self):
        self._L.ptq_close(self._q)

    def __len__(self):
        return int(self._L.ptq_size(self._q))

    def __del__(self):
        try:
            self._L.ptq_destroy(self._q)
        except Exception:
            pass


class TCPStore:
    """KV store for rendezvous (reference distributed/store/tcp_store.h)."""

    SET, GET, ADD, WAIT, DELETE = 0, 1, 2, 3, 4

    def __init__(self, host="127.0.0.1", port=23456, is_master=False, timeout=30):
        L = lib()
        if L is None:
            raise RuntimeError("native runtime unavailable")
        self._L = L
        self._server = None
        if is_master:
            self._server = L.pts_server_create(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
        self._client = L.pts_client_create(host.encode(), port, int(timeout * 1000))
        if not self._client:
            raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")

    def _req(self, op, key, val=b"", max_bytes=None):
        # the C side drains the full reply off the socket before the copy-out
        # bounds check, so an undersized buffer LOSES the value (-2, not
        # retryable) — callers expecting large replies must size up front
        out = ctypes.create_string_buffer(max(1 << 20, int(max_bytes or 0)))
        out_len = ctypes.c_int64(0)
        status = self._L.pts_request(
            self._client, op, key.encode(), val, len(val), out, len(out), ctypes.byref(out_len)
        )
        if status < 0:
            raise RuntimeError("TCPStore request failed")
        return status, out.raw[: out_len.value]

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        self._req(self.SET, key, value)

    def get(self, key, max_bytes=None):
        status, val = self._req(self.GET, key, max_bytes=max_bytes)
        return val if status == 0 else None

    def add(self, key, amount=1):
        import struct

        _, val = self._req(self.ADD, key, struct.pack("<q", amount))
        return struct.unpack("<q", val)[0]

    def wait(self, key, max_bytes=None):
        status, val = self._req(self.WAIT, key, max_bytes=max_bytes)
        if status != 0:
            raise RuntimeError(f"TCPStore wait({key}) interrupted")
        return val

    def delete_key(self, key):
        self._req(self.DELETE, key)

    def close(self):
        if self._client:
            self._L.pts_client_destroy(self._client)
            self._client = None
        if self._server:
            self._L.pts_server_destroy(self._server)
            self._server = None
