"""Device/place abstraction.

TPU-native analogue of the reference's ``Place`` hierarchy
(``paddle/phi/common/place.h``) and ``paddle.device.set_device``
(``python/paddle/device/__init__.py``). A Place wraps a PJRT device handle
(`jax.Device`); there is no per-device context pool — XLA owns streams.
"""
from __future__ import annotations

import threading

import jax


class Place:
    """A logical device. ``device_type`` is 'cpu' | 'tpu' | 'gpu'."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    # -- PJRT handle ------------------------------------------------------
    def jax_device(self):
        devs = [d for d in jax.devices() if d.platform == self.device_type]
        if not devs:
            # Fall back to the default backend (e.g. asking for tpu on a
            # CPU-only test host): semantics match reference CPU fallback
            # (paddle/fluid/framework/operator.cc:1187-1234 phi CPU fallback).
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPlace(Place):  # accepted for API parity; maps to gpu backend
    def __init__(self, device_id: int = 0):
        super().__init__("gpu", device_id)


_state = threading.local()


def _default_place() -> Place:
    plat = jax.default_backend()
    if plat == "tpu":
        return TPUPlace(0)
    if plat == "gpu":
        return CUDAPlace(0)
    return CPUPlace()


def set_device(device: str) -> Place:
    """paddle.device.set_device('tpu:0' | 'cpu' | 'gpu:1')."""
    if isinstance(device, Place):
        _state.place = device
        return device
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = {"xla": "tpu"}.get(name, name)
    if name == "cpu":
        place = CPUPlace()
    elif name == "tpu":
        place = TPUPlace(idx)
    elif name in ("gpu", "cuda"):
        place = CUDAPlace(idx)
    else:
        raise ValueError(f"Unknown device {device!r}")
    _state.place = place
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.device_id}"


def current_place() -> Place:
    if not hasattr(_state, "place"):
        _state.place = _default_place()
    return _state.place


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


class CUDAPinnedPlace(CPUPlace):
    """Pinned host memory place (reference platform/place.h). On this
    runtime host staging is the arena allocator's job; the class exists for
    API parity and behaves as host memory."""


class _UnavailablePlace:
    """Reference device places with no backing hardware here (IPU/MLU/NPU/
    XPU/custom). Constructing one fails loudly instead of silently running
    on the wrong device."""

    def __init__(self, *a, **k):
        raise RuntimeError(
            f"{type(self).__name__} hardware is not available in this "
            "TPU-native build; use CPUPlace() or TPUPlace()")


class IPUPlace(_UnavailablePlace):
    pass


class MLUPlace(_UnavailablePlace):
    pass


class NPUPlace(_UnavailablePlace):
    pass


class XPUPlace(_UnavailablePlace):
    pass


class CustomPlace(_UnavailablePlace):
    pass
