"""Lazy eager-op batching (LazyTensor engine) with an async runtime.

TPU-native answer to the reference's per-op dispatch engineering
(``paddle/fluid/imperative/tracer.cc:170`` hot loop +
``prepared_operator.cc:129`` PreparedOp caching): instead of shaving the cost
of ONE op launch, eager ops are queued into a growing expression graph and
executed as a SINGLE XLA computation at materialization points
(``.numpy()``/``.item()``/print/host control flow). In steady state a train
loop flushes once per iteration — backward(i) + optimizer-update(i) +
forward(i+1) fuse into one cached executable, giving eager code compiled-step
throughput (SURVEY §7 hard part (a): LazyTensor-style lazy batching).

Design:
  * ``LazyArray`` — placeholder carrying only an aval (shape/dtype). Tensors
    hold these in ``_data`` exactly like a ``jax.Array``; any host access
    (``__array__``, unknown attribute) forces a flush.
  * ``record(name, fn, inputs)`` — append one node; output avals come from a
    cached ``jax.eval_shape`` probe, so shape/dtype errors still surface at
    the op call site like eager mode. The wiring descriptors, leaf table and
    signature parts are built HERE, incrementally — the flush no longer walks
    the whole graph again, so per-step host work on cache hits is one
    liveness sweep plus a dict probe.
  * ``flush()`` — replay the pending nodes inside ``jax.jit``. The executable
    cache is keyed on the graph *signature* (per-node fn identity incl.
    closure values, input wiring, leaf avals, liveness mask, donation mask),
    so the second identical iteration reuses the compiled step.
  * autograd defers ``jax.vjp`` into the graph (vjp composes under tracing),
    so backward is recorded, not executed, until the next materialization.

Async runtime (``FLAGS_lazy_async``, default ON — arXiv:2102.13267's point:
overlap host graph construction with device execution):

  * the flush returns as soon as the fused executable is DISPATCHED; results
    land in ``LazyArray._concrete`` as unblocked ``jax.Array`` futures, and
    the host traces step k+1 while the device executes step k. Host waits are
    instrumented: ``timed_block`` (called by ``Tensor.numpy()`` and
    ``LazyArray.__array__``) emits a ``block`` span and feeds the
    ``lazy_block_ns`` counter — the dispatch-gap metric in bench.py.
  * the FLAGS_check_nan_inf scan and the telemetry memory census move off the
    critical path: they are enqueued against the dispatched arrays and run at
    the next flush, the next materialization, or :func:`sync` — the trip
    surfaces at most one step late, with the producing ``lazy_flush`` span
    attribution preserved in the flight-recorder dump. Donation stays
    suppressed while the guard is armed (pre-step state survives, PR 2).
  * ``FLAGS_lazy_bg_compile`` (opt-in): an executable-cache miss compiles on
    a background thread while the current step completes via the un-jitted
    replay, so new-shape warmup no longer stalls the loop. Opt-in because the
    unfused replay can differ from the fused executable by ~1 ulp, and WHEN
    the compiled executable is picked up depends on compile latency — loops
    that pin bitwise reproducibility across runs must leave it off.
  * ``FLAGS_lazy_async=0`` restores the fully synchronous PR-2 behavior:
    in-flush NaN scan, in-flush census, no block instrumentation.

Correctness fallback: if jitted replay fails, nodes run eagerly one-by-one.

Known cost trade-off: materializing the loss BEFORE backward() (print/log
every step) splits the iteration into two executables, and the tape backward
re-derives the forward inside its vjp — i.e. forward FLOPs run twice, like
``jax.value_and_grad`` after a separate forward eval. Loops that materialize
after ``opt.step()`` (or only every N steps) pay nothing.
"""
from __future__ import annotations

import collections
import sys
import threading
import time
import warnings
import weakref
from typing import Any, Callable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "LazyArray", "record", "flush", "sync", "lazy_enabled", "set_lazy_mode",
    "lazy_guard", "is_lazy", "maybe_lazy_binary", "lazy_full",
    "note_rebound", "timed_block", "evict_cold",
]

_state = threading.local()
_DEFAULT_ENABLED = True  # flipped off per-thread via set_lazy_mode(False)

# Stability-sentinel drain tap (fault/sentinel.py): invoked at the same
# boundaries as the deferred NaN/Inf drain so the sentinel's per-step fused
# scalar readback rides the existing deferred-check path instead of adding
# sync points of its own. None while no sentinel is active — the disabled
# path is this one attribute probe per flush (tier-1 inert tripwire).
_stability_tap = None

# Flush when the pending graph reaches this many nodes even without a
# materialization point (a loop that never prints would otherwise grow the
# graph unboundedly). Boundaries then land at consistent offsets across
# identical iterations, so the signature cache still hits.
_MAX_PENDING = 2048


def lazy_enabled() -> bool:
    return getattr(_state, "enabled", _DEFAULT_ENABLED)


def set_lazy_mode(enabled: bool) -> None:
    """Turn lazy eager batching on/off for this thread (flushes first)."""
    flush()
    _state.enabled = bool(enabled)


class lazy_guard:
    """Context manager: ``with lazy_guard(False): ...`` for per-op dispatch."""

    def __init__(self, enabled: bool = True):
        self._want = bool(enabled)

    def __enter__(self):
        self._prev = lazy_enabled()
        set_lazy_mode(self._want)
        return self

    def __exit__(self, *exc):
        set_lazy_mode(self._prev)
        return False


def is_lazy(x) -> bool:
    return isinstance(x, LazyArray)


def concrete(x):
    """Materialize a LazyArray to its jax.Array (identity for anything else).
    External consumers (orbax, dlpack, ctypes buffers) need real buffers."""
    return x._value() if isinstance(x, LazyArray) else x


class _Node:
    __slots__ = ("key", "fn", "inputs", "n_out", "out_refs", "gix", "graph")

    def __init__(self, key, fn, inputs, n_out):
        self.key = key
        self.fn = fn
        self.inputs = inputs  # LazyArray | jax.Array | np scalar
        self.n_out = n_out
        self.out_refs = None  # list of weakrefs to output LazyArrays
        self.gix = 0  # index in its graph's node list (wiring descriptor)
        self.graph = None  # owning _Graph while pending; None once flushed


class LazyArray:
    """Placeholder for a pending node output. Metadata (shape/dtype) is free;
    everything else materializes the whole pending graph."""

    __slots__ = ("_node", "_idx", "aval", "_concrete", "__weakref__")

    def __init__(self, node, idx, aval):
        self._node = node
        self._idx = idx
        self.aval = aval
        self._concrete = None

    # -- free metadata ----------------------------------------------------
    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    def astype(self, dt):
        dt = np.dtype(dt) if not hasattr(dt, "dtype") else dt
        if np.dtype(dt) == np.dtype(self.dtype):
            return self
        (out,), _ = record(
            "astype", lambda x: x.astype(dt), [self], key=("lazy_astype", str(dt))
        )
        return out

    # -- materialization --------------------------------------------------
    def _value(self):
        if self._concrete is None:
            flush()
        if self._concrete is None:  # node died before flush (shouldn't happen)
            raise RuntimeError("LazyArray was never materialized")
        # a deferred NaN/Inf check against THIS flush must surface here, at
        # the materialization point, not one step later
        _drain_deferred()
        return self._concrete

    def __jax_array__(self):
        return self._value()

    def __array__(self, dtype=None):
        a = np.asarray(timed_block(self._value()))
        return a.astype(dtype) if dtype is not None else a

    def __getattr__(self, name):
        # private attrs never delegate (hasattr probes must stay cheap and
        # must not force a flush)
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._value(), name)

    def __repr__(self):
        st = "pending" if self._concrete is None else "ready"
        return f"LazyArray(shape={tuple(self.shape)}, dtype={self.dtype}, {st})"

    def __len__(self):
        if not self.aval.shape:
            raise TypeError("len() of a 0-d array")
        return self.aval.shape[0]

    def __iter__(self):
        return iter(self._value())

    def __bool__(self):
        return bool(self._value())

    def __float__(self):
        return float(self._value())

    def __int__(self):
        return int(self._value())

    def __format__(self, spec):
        # the wait is attributed like every other readback (block span +
        # lazy_block_ns) — an f-string on a pending loss is a host sync too
        v = timed_block(self._value())
        return format(np.asarray(v) if self.ndim else v.item(), spec)  # lint: ok(host-sync)

    @staticmethod
    def _rev(fn):
        def rev(a, b):
            return fn(b, a)

        rev.__name__ = "r_" + fn.__name__
        return rev

    def __getitem__(self, idx):
        # stay lazy for static indices (ints/slices): a stray `lazy[0]` in a
        # library must not split the fused iteration into two executables
        try:
            hash(idx)
        except TypeError:
            return self._value()[idx]
        (out,), _ = record(
            "lazy_getitem", lambda a: a[idx], [self],
            key=("lazy_getitem", str(idx)),
        )
        return out

    # arithmetic stays LAZY (recorded into the pending graph) — raw operator
    # use on a LazyArray must not force a full flush of the iteration
    def _binop(self, other, op, name):
        if _no_tracer(other):
            return maybe_lazy_binary(op, self, other, name=name)
        return op(self._value(), other)

    def __add__(self, o):
        return self._binop(o, jnp.add, "lazy_add")

    def __radd__(self, o):
        return self._binop(o, self._rev(jnp.add), "lazy_radd")

    def __sub__(self, o):
        return self._binop(o, jnp.subtract, "lazy_sub")

    def __rsub__(self, o):
        return self._binop(o, self._rev(jnp.subtract), "lazy_rsub")

    def __mul__(self, o):
        return self._binop(o, jnp.multiply, "lazy_mul")

    def __rmul__(self, o):
        return self._binop(o, self._rev(jnp.multiply), "lazy_rmul")

    def __truediv__(self, o):
        return self._binop(o, jnp.divide, "lazy_div")

    def __rtruediv__(self, o):
        return self._binop(o, self._rev(jnp.divide), "lazy_rdiv")

    def __neg__(self):
        (out,), _ = record("lazy_neg", jnp.negative, [self], key=("lazy_neg",))
        return out

    def __matmul__(self, o):
        return self._binop(o, jnp.matmul, "lazy_matmul")

    def __pow__(self, o):
        return self._binop(o, jnp.power, "lazy_pow")

    def __lt__(self, o):
        return self._value() < o

    def __le__(self, o):
        return self._value() <= o

    def __gt__(self, o):
        return self._value() > o

    def __ge__(self, o):
        return self._value() >= o


class _Graph:
    """One pending-graph epoch. The trace structures the old flush used to
    rebuild per step — wiring descriptors, the deduped leaf table, donation
    refcount bookkeeping, signature parts — are maintained INCREMENTALLY by
    ``record``, so a cache-hit flush only sweeps output liveness."""

    __slots__ = (
        "nodes", "leaves", "leaf_pos", "leaf_avals", "direct_uses",
        "descs", "keyparts",
    )

    def __init__(self):
        self.nodes: List[_Node] = []
        self.leaves: list = []  # deduped external inputs, in first-use order
        self.leaf_pos: dict = {}  # id(leaf) -> index in `leaves`
        self.leaf_avals: list = []  # per-leaf (shape, dtype, kind) sig parts
        self.direct_uses: dict = {}  # id(leaf) -> occurrences in node inputs
        self.descs: list = []  # per-node wiring descriptor tuples
        self.keyparts: list = []  # per-node (node.key, descs) signature parts


def _graph() -> _Graph:
    g = getattr(_state, "graph", None)
    if g is None:
        g = _Graph()
        _state.graph = g
    return g


# -- donation candidates -----------------------------------------------------
# Buffers whose holder rebound them THROUGH the pending graph (a Tensor's
# _data replaced by a flush output, an optimizer moment replaced by its
# update, a grad buffer replaced by its accumulation). These are the
# dead-after-flush candidates the liveness pass in _flush_impl may pass as
# donate_argnums. Ids only — holding a reference here would defeat the
# refcount deadness test that guards against user-held aliases.
_DONATE_IDS_MAX = 65536


def note_rebound(old):
    """Record that ``old`` (a jax.Array, or a LazyArray wrapping one) was
    replaced by a pending-graph output in whatever slot held it. No-op when
    nothing is queued — candidacy only means anything for buffers feeding the
    pending graph."""
    g = getattr(_state, "graph", None)
    if g is None or not g.nodes:
        return
    if isinstance(old, LazyArray):
        old = old._concrete
    if old is None or not isinstance(old, jax.Array):
        return
    s = getattr(_state, "donate_ids", None)
    if s is None:
        s = set()
        _state.donate_ids = s
    if len(s) < _DONATE_IDS_MAX:
        s.add(id(old))


def _false():
    return False


_donation_warnings_filtered = False


def _ignore_donation_warnings():
    """XLA may decline an aliasing hint (layout/sharding mismatch) and jax
    warns per unusable donation — correct but noisy once per train step.
    Installed ONCE: catch_warnings around every flush would copy/restore the
    process-global filter list on the hot path (and isn't thread-safe).
    Action "once" (not "ignore"): the filter is process-global and jax emits
    the SAME text for a user's own jit(donate_argnums=...) — one surviving
    diagnostic per warn-site keeps their misconfiguration visible while
    killing the per-step repeat."""
    global _donation_warnings_filtered
    if not _donation_warnings_filtered:
        warnings.filterwarnings(
            "once", message=r"Some donated buffers were not usable"
        )
        _donation_warnings_filtered = True


def _donation_mask(leaves, cand, direct_uses):
    """Leaf positions provably dead after this flush: marked as rebound AND
    the only strong references left are the pending graph's own input lists.
    Runs in its own frame so the caller's loop variables can't inflate the
    refcount of the leaf under test. A leaf still reachable through a live
    LazyArray is protected automatically: that LazyArray's ``_concrete``
    reference inflates the refcount past the graph-only budget."""
    out = []
    for j in range(len(leaves)):
        x = leaves[j]
        i = id(x)
        if (
            i not in cand
            or not isinstance(x, jax.Array)
            or isinstance(x, jax.core.Tracer)
        ):
            x = None
            continue
        # Refcount at this point for a dead buffer: one per occurrence in a
        # node's input list, plus the graph `leaves` list, the loop binding
        # `x`, and getrefcount's own argument. Anything above that is a live
        # Tensor / user alias / residual capture — donation would corrupt it.
        if sys.getrefcount(x) == direct_uses.get(i, 0) + 3:
            out.append(j)
        x = None
    return tuple(out)


# -- aval probing (cached) ---------------------------------------------------
_aval_cache: dict = {}
_AVAL_CACHE_MAX = 8192
_sds_cache: dict = {}  # (shape, dtype) -> ShapeDtypeStruct (records are hot)


def _aval_of(x):
    if isinstance(x, LazyArray):
        return x.aval  # already a ShapeDtypeStruct from the probe
    if isinstance(x, jax.Array):
        k = (x.shape, x.dtype)
        s = _sds_cache.get(k)
        if s is None:
            if len(_sds_cache) > _AVAL_CACHE_MAX:
                _sds_cache.clear()
            s = _sds_cache[k] = jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return s
    a = np.asarray(x)
    return jax.ShapeDtypeStruct(a.shape, a.dtype)


def _leaf_sig(x):
    """Per-leaf signature component: shape/dtype (+ python-scalar typing —
    a plain float traces weakly typed, an np.float32 doesn't). Folding these
    into the flush signature keeps one cache entry per real trace, which the
    AOT background-compile path requires (a compiled executable, unlike
    jax.jit, cannot silently re-trace on a dtype change)."""
    if isinstance(x, jax.Array):
        return (x.shape, x.dtype)
    if isinstance(x, (bool, int, float, complex)):
        return type(x).__name__
    a = np.asarray(x)
    return (a.shape, a.dtype)


def _probe(key, fn, in_avals):
    ck = (key, tuple((a.shape, a.dtype) for a in in_avals))
    try:
        hash(ck)
    except TypeError:
        ck = None
    if ck is not None:
        hit = _aval_cache.get(ck)
        if hit is not None:
            return hit
    out = jax.eval_shape(fn, *in_avals)
    single = not isinstance(out, (tuple, list))
    avals = (out,) if single else tuple(out)
    res = (avals, single)
    if ck is not None:
        if len(_aval_cache) > _AVAL_CACHE_MAX:
            _aval_cache.clear()
        _aval_cache[ck] = res
    return res


def _typed(v):
    """Tag scalars with their type: 1, 1.0 and True are == and hash-equal in
    Python, but produce different traced programs (int64 vs float64 vs bool
    constants) — an untyped key silently serves the wrong executable."""
    if isinstance(v, (bool, int, float, complex)):
        return (type(v).__name__, v)
    if isinstance(v, tuple):
        return tuple(_typed(x) for x in v)
    return v


def _fn_key(fn):
    """Stable identity for a function: code object + closure/default VALUES.
    Shared by dispatch.py (per-op jit cache) and this module (flush
    signature); keyword-only defaults are part of the key."""
    try:
        cells = tuple(
            _typed(c.cell_contents) for c in (getattr(fn, "__closure__", None) or ())
        )
        defaults = tuple(_typed(v) for v in (getattr(fn, "__defaults__", None) or ()))
        kwdefaults = tuple(
            sorted((k, _typed(v)) for k, v in (getattr(fn, "__kwdefaults__", None) or {}).items())
        )
        code = getattr(fn, "__code__", None)
        key = (code, cells, defaults, kwdefaults) if code is not None else fn
        hash(key)
        return key
    except (TypeError, ValueError, AttributeError):
        return fn


def record(name, fn, inputs, key=None):
    """Append one op to the pending graph.

    ``fn(*arrays)`` must be pure over JAX arrays. Returns
    ``(outputs: list[LazyArray], single: bool)``. ``key`` identifies fn for
    the executable cache; when None it is derived from fn's code + closure
    values (correct as long as the closure holds only hashables).

    The wiring descriptor, leaf-table entries and signature part for the node
    are built here — incremental tracing — so ``flush`` does not re-walk the
    graph (tentpole of the async runtime: host work per cache-hit step is a
    liveness sweep + executable-cache probe + dispatch).
    """
    g = _graph()
    leaf_pos = g.leaf_pos
    leaves = g.leaves
    ins = []
    descs = []
    # Leaf-table/direct_uses mutations are staged and committed only after
    # _probe succeeds: a caught shape/dtype error from eval_shape must leave
    # the pending graph exactly as it was (an orphan leaf would perturb the
    # flush signature and overcount direct_uses, breaking the donation mask).
    new_leaves = []  # (x, leaf_sig) in reservation order
    new_pos = {}
    du_bump = {}
    for x in inputs:
        if isinstance(x, LazyArray):
            if x._concrete is None:
                n = x._node
                if n.graph is not g:
                    raise RuntimeError(
                        "lazy graph invariant violated: input from a "
                        "flushed-but-unmaterialized node"
                    )
                ins.append(x)
                descs.append(("n", n.gix, x._idx))
                continue
            x = x._concrete
        j = leaf_pos.get(id(x))
        if j is None:
            j = new_pos.get(id(x))
            if j is None:
                j = len(leaves) + len(new_leaves)
                new_pos[id(x)] = j
                new_leaves.append((x, _leaf_sig(x)))
        du_bump[id(x)] = du_bump.get(id(x), 0) + 1
        ins.append(x)
        descs.append(("l", j))
    in_avals = [_aval_of(x) for x in ins]
    k = key if key is not None else _fn_key(fn)
    avals, single = _probe((name, k), fn, in_avals)
    for x, sig in new_leaves:
        leaf_pos[id(x)] = len(leaves)
        leaves.append(x)
        g.leaf_avals.append(sig)
    du = g.direct_uses
    for ident, c in du_bump.items():
        du[ident] = du.get(ident, 0) + c
    node = _Node((name, k), fn, ins, len(avals))
    node.gix = len(g.nodes)
    node.graph = g
    outs = [LazyArray(node, i, a) for i, a in enumerate(avals)]
    node.out_refs = [weakref.ref(o) for o in outs]
    g.nodes.append(node)
    descs = tuple(descs)
    g.descs.append(descs)
    g.keyparts.append((node.key, descs))
    if len(g.nodes) >= _MAX_PENDING:
        flush()
    return outs, single


# -- flush -------------------------------------------------------------------
# The executable cache is shared by every thread running lazy mode (graphs
# are thread-local, compiled steps are not) — an OrderedDict's reorder/evict
# is not atomic, so probes and inserts serialize on _cache_lock (one
# uncontended acquire per flush; the lock is NOT held across trace/compile).
_cache_lock = threading.Lock()
_flush_cache: "collections.OrderedDict" = collections.OrderedDict()  # guarded_by: _cache_lock
_FLUSH_CACHE_MAX = 128


def evict_cold(keep: int = 4) -> int:
    """Drop cold executable-cache entries, keeping the ``keep`` most
    recently used — the lazy runtime's pressure-relief rung
    (fault/memory.free_pressure): a compiled program pins its constants and
    workspace, so under RESOURCE_EXHAUSTED the cold tail is the cheapest
    memory to give back (an evicted signature merely recompiles if it ever
    comes back). Returns the number evicted."""
    n = 0
    with _cache_lock:
        while len(_flush_cache) > max(int(keep), 0):
            _flush_cache.popitem(last=False)
            n += 1
    return n


def _interp(fns, wiring, leaf_vals, on_node=None):
    """The one interpreter for the graph wiring descriptors
    (``("l", leaf_ix)`` / ``("n", node_ix, out_ix)``): used traced inside the
    jitted replay AND eagerly by the per-op nan checker — one format, one
    reader. Returns the per-node output env; ``on_node(i, outs)`` observes
    each node as it lands."""
    env: list = [None] * len(fns)
    for i, f in enumerate(fns):
        args = [
            leaf_vals[d[1]] if d[0] == "l" else env[d[1]][d[2]]
            for d in wiring[i]
        ]
        o = f(*args)
        env[i] = tuple(o) if isinstance(o, (tuple, list)) else (o,)
        if on_node is not None:
            on_node(i, env[i])
    return env


# span-tracer module, bound once at first flush (same pattern as
# dispatch._prof — flush runs once per iteration, not per op, so the span is
# cheap; the flight recorder keeps it even with the profiler closed)
_spans_mod = None


def _spans():
    global _spans_mod
    if _spans_mod is None:
        from ..profiler import spans

        _spans_mod = spans
    return _spans_mod


def _flags_mod():
    from ..framework import flags

    return flags


def pending_summary() -> dict:
    """Post-mortem view of this thread's pending graph (flight recorder):
    node count and the tail of op names awaiting execution."""
    g = getattr(_state, "graph", None)
    nodes = g.nodes if g is not None else []
    return {
        "pending_nodes": len(nodes),
        "tail_ops": [n.key[0] for n in nodes[-8:]],
        # census-only entries (payload None) carry no NaN/Inf scan — a dump
        # must not claim a check was pending when only a census was
        "deferred_checks": sum(
            1 for e in (getattr(_state, "deferred", ()) or ()) if e[1] is not None
        ),
    }


# -- async runtime: host-wait instrumentation & deferred post-flush work -----
def _timed_block(x, where: str):
    """Block until ``x`` is ready under a ``block`` span, feeding the
    dispatch-gap counters (``lazy_blocks`` / ``lazy_block_ns``). This is the
    ONLY sanctioned way the runtime waits on the device — the tier-1
    tripwire asserts no ``block`` span ever appears inside ``lazy_flush``."""
    from .dispatch import _prof
    from ..distributed import watchdog as _watchdog

    t0 = time.perf_counter_ns()
    with _spans().span("block", where=where):
        # deadline on the host sync: a peer rank that died mid-step leaves
        # this wait blocked forever in multi-controller runs — the watchdog
        # (FLAGS_collective_timeout_s>0) converts that into an attributed
        # resumable exit. A flag probe when disabled.
        with _watchdog.guard(f"block:{where}"):
            jax.block_until_ready(x)
    p = _prof()
    p.counter_inc("lazy_blocks")
    p.counter_inc("lazy_block_ns", time.perf_counter_ns() - t0)
    return x


def timed_block(x, where: str = "readback"):
    """Public wrapper used at host readback sites (``Tensor.numpy()``,
    ``LazyArray.__array__``, metric updates): waits for an in-flight
    ``jax.Array`` (or a sequence of them) with the wait ATTRIBUTED (block
    span + lazy_block_ns), so host idle time between device steps is
    measurable instead of hiding inside ``np.asarray``. Identity for ready
    arrays, non-arrays, tracers, and when ``FLAGS_lazy_async`` is off (the
    old behavior blocked silently)."""
    if isinstance(x, (list, tuple)):
        arrs = [
            a for a in x
            if isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer)
        ]
        if not arrs or not _flags_mod().flag("FLAGS_lazy_async", True):
            return x
        try:
            if all(a.is_ready() for a in arrs):
                return x
        except Exception:  # lint: ok(oom-handler) — readiness probe, nothing dispatches in this try
            pass
        _timed_block(arrs, where)
        return x
    if not isinstance(x, jax.Array) or isinstance(x, jax.core.Tracer):
        return x
    if not _flags_mod().flag("FLAGS_lazy_async", True):
        return x
    try:
        if x.is_ready():  # committed futures skip the span entirely
            return x
    except Exception:  # lint: ok(oom-handler) — readiness probe, nothing dispatches in this try
        pass
    return _timed_block(x, where)


def _enqueue_deferred(sp, check_payload, census, results):
    d = getattr(_state, "deferred", None)
    if d is None:
        d = []
        _state.deferred = d
    d.append((sp, check_payload, census, results))
    # verify at ENQUEUE time: flush() drains this queue before the next
    # _flush_impl runs, so a pre-dispatch check there would only ever see an
    # empty queue — here is the one point a malformed entry can exist
    if _flags_mod().flag("FLAGS_lazy_verify", False):
        from ..analysis.verify_graph import _verify_deferred

        _verify_deferred(d)


def _drain_deferred():
    """Run the post-flush work deferred off the critical path: the memory
    census (attrs attached to the PRODUCING lazy_flush span post-hoc) and
    the NaN/Inf scan — which blocks on the dispatched arrays under a
    ``block`` span and raises with the producing-span attribution intact.
    Called at flush entry, at every materialization point, and by sync()."""
    d = getattr(_state, "deferred", None)
    if not d:
        return
    entries = list(d)
    del d[:]  # reentrancy/raise-safe: one trip drops the batch
    spans_mod = _spans()
    for sp, payload, census, results in entries:
        if census:
            from .dispatch import _prof

            mem = _prof().memory_census()
            attrs = dict(
                live_bytes=mem["live_bytes"],
                live_arrays=mem["live_arrays"],
                peak_live_bytes=mem["peak_live_bytes"],
                delta_bytes=mem["last_delta_bytes"],
            )
            if sp is not None:
                spans_mod.update_attrs(sp, **attrs)
        if payload is not None:
            with spans_mod.span(
                "lazy_deferred_check",
                producing_span=(sp.span_id if sp is not None else 0),
            ):
                _timed_block(results, "deferred_naninf")
                _nan_check(*payload, deferred=True, producing=sp)


def sync():
    """Synchronization barrier for the async runtime: dispatch everything
    pending, surface any deferred NaN/Inf trip, and block (attributed) until
    the device finished the last dispatched step. With ``FLAGS_lazy_async=0``
    every flush already behaves like this."""
    flush()
    _drain_deferred()
    tap = _stability_tap
    if tap is not None:
        tap()
    inflight = getattr(_state, "inflight", None)
    if inflight:
        _state.inflight = None
        _timed_block(inflight, "sync")


# -- background compilation ---------------------------------------------------
class _BgCompile:
    """One background compile of a flush signature: ``jax.jit(replay)
    .lower(*leaves).compile()`` on a daemon worker thread while the training
    loop keeps stepping through the un-jitted replay. Lowering from the live
    leaves (not synthetic avals) captures exact shapes/dtypes/weak-types; the
    thread's reference to them dies with the compile."""

    __slots__ = ("ready", "value", "error", "_thread")

    def __init__(self, replay, donate_ix, leaves):
        self.ready = False
        self.value = None
        self.error = None

        def work(leaves=leaves):
            try:
                jf = (
                    jax.jit(replay, donate_argnums=donate_ix)
                    if donate_ix
                    else jax.jit(replay)
                )
                self.value = jf.lower(*leaves).compile()
            except Exception as e:  # surfaced as a sync-compile fallback
                from ..fault import memory as _mem

                if _mem.is_oom(e):  # compile-time RESOURCE_EXHAUSTED counts
                    _mem.note_oom("lazy_bg_compile", e)
                self.error = e
            finally:
                self.ready = True  # publish AFTER value/error (GIL ordering)

        self._thread = threading.Thread(
            target=work, daemon=True, name="lazy-bg-compile"
        )
        self._thread.start()


def flush():
    """Execute all pending nodes as one jitted XLA computation and write the
    results back into the live LazyArrays. With ``FLAGS_lazy_async`` (default)
    the host returns as soon as the executable is dispatched — the results in
    ``LazyArray._concrete`` are unblocked futures."""
    if getattr(_state, "flushing", False):
        return
    # deferred work from the PREVIOUS flush surfaces before new work is
    # dispatched — a deferred NaN trip is ≤1 step late, never dropped
    _drain_deferred()
    tap = _stability_tap
    if tap is not None:
        tap()  # non-blocking readiness sweep; never raises, never flushes
    g = getattr(_state, "graph", None)
    if g is None or not g.nodes:
        return
    _state.flushing = True
    try:
        _state.graph = None  # fresh epoch for anything recorded during flush
        # the sync() handle on the previous step's results must die BEFORE
        # the donation mask runs — a held results list would inflate the
        # refcount of every rebound buffer and defeat in-place updates
        _state.inflight = None
        with _spans().span("lazy_flush", nodes=len(g.nodes)) as sp:
            _flush_impl(g, sp)
    finally:
        _state.flushing = False


def _flush_impl(g: _Graph, sp=None):
    nodes = g.nodes
    leaves = g.leaves
    descs_all = g.descs

    # The wiring/signature was built incrementally by record(); the only
    # flush-time trace work left is the output-liveness sweep.
    with _spans().span("trace", nodes=len(nodes)) as trace_span:
        alive_parts = tuple(
            tuple(r() is not None for r in n.out_refs) for n in nodes
        )
        trace_span.set(leaves=len(leaves))

    # Liveness pass: donate leaves that were rebound through this graph and
    # that nothing outside the graph still references. The mask is part of
    # the executable signature, so a cache hit always replays with the same
    # donation layout it was compiled with. Donation is SUPPRESSED while
    # FLAGS_check_nan_inf is set: a donated buffer is destroyed by the flush,
    # and on a NaN trip the pre-step state must survive for inspection (and
    # for the per-op unfused replay).
    _flags = _flags_mod()

    check_nan = bool(_flags.flag("FLAGS_check_nan_inf", False))
    async_on = bool(_flags.flag("FLAGS_lazy_async", True))
    # HBM preflight admission (fault/memory.py): "off" (default) costs this
    # one probe — fault.memory is never imported, no census runs, the
    # executable compiles through the plain jax.jit path (inert tripwire)
    admission = _flags.flag("FLAGS_hbm_admission", "off")
    donate_ix: tuple = ()
    cand = getattr(_state, "donate_ids", None)
    if cand and _flags.flag("FLAGS_lazy_donate", True):
        if check_nan:
            from .dispatch import _prof as _prof_fn

            _prof_fn().counter_inc("naninf_donation_suppressed")
            if sp is not None:
                sp.set(donation="suppressed_naninf")
        else:
            with _spans().span("donate", candidates=len(cand)) as dsp:
                donate_ix = _donation_mask(leaves, cand, g.direct_uses)
                dsp.set(donated=len(donate_ix))
    # snapshot for the preflight-rejection path: a rejected dispatch must
    # put the donation intent back, or the retry flush would re-key (and
    # recompile) WITHOUT donation — a bigger footprint exactly when memory
    # is tightest
    cand_snapshot = set(cand) if cand else None
    if cand:
        cand.clear()

    # Graph IR verifier (analysis/verify_graph.py): re-derive the wiring /
    # leaf table / donation mask / signature from ground truth and cross-
    # check the record-time memoization, BEFORE anything is dispatched or
    # cached. Off by default — this probe is the entire disabled-path cost.
    if _flags.flag("FLAGS_lazy_verify", False):
        from ..analysis.verify_graph import verify_before_dispatch

        # deferred entries are verified where they are enqueued (see
        # _enqueue_deferred) — by this point flush() has already drained them
        verify_before_dispatch(g, donate_ix)

    try:
        sig = (tuple(g.keyparts), alive_parts, tuple(g.leaf_avals), donate_ix)
        hash(sig)
    except TypeError:
        sig = None

    from .dispatch import _prof

    prof = _prof()
    prof.counter_inc("lazy_flushes")

    with _cache_lock:
        entry = _flush_cache.get(sig) if sig is not None else None
        if entry is not None:
            _flush_cache.move_to_end(sig)
    cache_hit = entry is not None
    if sp is not None:
        # the executable-cache key: stable within a process (str hashing is
        # seeded per-process), enough to correlate hit/miss spans in a trace
        sp.set(
            cache="hit" if cache_hit else "miss",
            cache_key=(f"{hash(sig) & 0xFFFFFFFFFFFFFFFF:016x}" if sig is not None else None),
        )
    precompiled = False
    if entry is None:
        fns = [n2.fn for n2 in nodes]
        wiring = descs_all
        live = [
            (i, j)
            for i, n2 in enumerate(nodes)
            for j in range(n2.n_out)
            if n2.out_refs[j]() is not None
        ]

        def replay(*leaf_vals):
            env = _interp(fns, wiring, leaf_vals)
            return [env[i][j] for (i, j) in live]

        if (
            async_on
            and sig is not None
            and _flags.flag("FLAGS_lazy_bg_compile", False)
        ):
            # compile off-thread; THIS step (and any same-signature step
            # until the compile lands) completes via the un-jitted replay
            # (no memory prediction until the pickup — admission skips it)
            task = _BgCompile(replay, donate_ix, list(leaves))
            entry = [None, live, replay, donate_ix, task, None]
            prof.counter_inc("lazy_bg_compiles")
        elif admission != "off":
            # admission needs the executable's memory_analysis BEFORE the
            # first dispatch: compile ahead-of-time (the bg-compile pickup
            # shape — entry[0] is an AOT Compiled, the aot fallback rung
            # re-traces on aval drift) and key the prediction like the
            # executable cache
            from ..fault import memory as _hbm

            jf = (
                jax.jit(replay, donate_argnums=donate_ix)
                if donate_ix
                else jax.jit(replay)
            )
            with _spans().span("compile", cache="miss", admission=admission) as csp:
                compiled = jf.lower(*leaves).compile()
                mem = _hbm.analyze_compiled(
                    compiled,
                    key=(f"{hash(sig) & 0xFFFFFFFFFFFFFFFF:016x}"
                         if sig is not None else None),
                )
                if mem is not None:
                    csp.set(
                        hbm_exec_peak_bytes=mem["peak_bytes"],
                        hbm_temp_bytes=mem["temp_bytes"],
                        hbm_output_bytes=mem["output_bytes"],
                        hbm_alias_bytes=mem["alias_bytes"],
                    )
            entry = [compiled, live, replay, donate_ix, None, mem]
            precompiled = True
        else:
            jitted = (
                jax.jit(replay, donate_argnums=donate_ix)
                if donate_ix
                else jax.jit(replay)
            )
            # list, not tuple: the donation-error fallback swaps in a
            # non-donating executable under the same signature
            entry = [jitted, live, replay, donate_ix, None, None]
        if sig is not None:
            with _cache_lock:
                _flush_cache[sig] = entry
                if len(_flush_cache) > _FLUSH_CACHE_MAX:
                    _flush_cache.popitem(last=False)
    else:
        prof.counter_inc("lazy_cache_hits")

    jitted, live, replay, don, task = entry[:5]
    mem_pred = entry[5] if len(entry) > 5 else None
    donated_bytes = (
        sum(int(getattr(leaves[j], "nbytes", 0)) for j in don) if don else 0
    )
    if sp is not None and don:
        sp.set(donated_buffers=len(don), donated_bytes=donated_bytes)
    if jitted is None and task is not None:
        # background compile in flight: pick it up if finished, else keep
        # stepping through the replay fallback
        if task.ready:
            if task.error is None:
                jitted = entry[0] = task.value
                entry[4] = None
                prof.counter_inc("lazy_bg_pickups")
                if sp is not None:
                    sp.set(bg_compile="picked_up")
            else:
                # bg compile failed — compile synchronously under this
                # signature; a persistent error then surfaces on execution
                jitted = entry[0] = (
                    jax.jit(replay, donate_argnums=don) if don else jax.jit(replay)
                )
                entry[4] = None
                prof.counter_inc("lazy_bg_compile_failures")
                if sp is not None:
                    sp.set(bg_compile="failed", bg_error=type(task.error).__name__)
    if (
        admission != "off"
        and mem_pred is None
        and task is None
        and jitted is not None
        and hasattr(jitted, "lower")
    ):
        # cache entry predates the admission flag flip (or was built by the
        # plain path): upgrade it IN PLACE once — lower+compile the same
        # jitted (donation mask already baked in; the persistent compilation
        # cache makes this warm) and capture its memory analysis
        from ..fault import memory as _hbm

        try:
            with _spans().span("compile", cache="upgrade", admission=admission) as csp:
                compiled = jitted.lower(*leaves).compile()
                mem_pred = _hbm.analyze_compiled(
                    compiled,
                    key=(f"{hash(sig) & 0xFFFFFFFFFFFFFFFF:016x}"
                         if sig is not None else None),
                )
                if mem_pred is not None:
                    csp.set(hbm_exec_peak_bytes=mem_pred["peak_bytes"])
            entry[0] = jitted = compiled
            if len(entry) > 5:
                entry[5] = mem_pred
            precompiled = True
        except Exception as e:
            if _hbm.is_oom(e):  # even the upgrade compile can exhaust HBM
                _hbm.note_oom("lazy_flush.compile", e)
                raise
            mem_pred = None  # no prediction; admission admits, dispatch as-is

    # a bg-compile pickup leaves an AOT Compiled in entry[0]; unlike jax.jit
    # it cannot re-trace, so execution failures get an extra fallback rung
    aot = jitted is not None and not hasattr(jitted, "lower")

    if admission != "off" and jitted is not None:
        # predicted peak + live census vs the device budget, BEFORE the
        # device is touched. An enforce rejection reinstates the pending
        # epoch: nothing was dispatched, so the caller can free memory or
        # raise the budget and simply flush again.
        from ..fault import memory as _hbm

        try:
            _hbm.preflight(
                mem_pred, "lazy_flush", span=sp, donated_bytes=donated_bytes
            )
        except Exception:
            cur = getattr(_state, "graph", None)
            if cur is None or not cur.nodes:
                _state.graph = g
            if cand_snapshot:
                # restore the donation intent too: the retry flush then
                # re-derives the SAME donation mask → same signature →
                # cache hit on this already-compiled (donating) executable
                s = getattr(_state, "donate_ids", None)
                if s is None:
                    s = set()
                    _state.donate_ids = s
                s.update(cand_snapshot)
            raise

    results = None
    if jitted is None:
        # replay-while-compiling: one eager pass, correct but unfused
        prof.counter_inc("lazy_bg_replays")
        if sp is not None:
            sp.set(bg_compile="pending")
        with _spans().span("execute", cache="miss", fallback="bg_compiling"):
            results = replay(*leaves)
    else:
        try:
            if don:
                _ignore_donation_warnings()
            from .dispatch import _fault_inject as _finj

            if _finj is not None:
                # hbm.oom chaos: the synthesized RESOURCE_EXHAUSTED raises
                # from inside this try, so the recovery ladder below handles
                # it exactly like a real device OOM
                _finj.maybe_hbm_oom("lazy_flush")
            # a miss pays trace+compile inside this first invocation (unless
            # admission already compiled ahead-of-time); a hit is a pure
            # executable launch — with the async runtime the host RETURNS at
            # dispatch ("dispatch" span), only the sync kill-switch path
            # keeps the old "execute" attribution
            span_name = (
                "compile"
                if not cache_hit and not precompiled
                else ("dispatch" if async_on else "execute")
            )
            with _spans().span(
                span_name, cache="hit" if cache_hit else "miss"
            ):
                results = jitted(*leaves)
            if don:
                prof.counter_inc("lazy_donated_buffers", len(don))
        except Exception as e:
            from ..fault import memory as _hbm

            if _hbm.is_oom(e):
                # RESOURCE_EXHAUSTED: classify → free pressure → retry once
                # → structured halt. NEVER the eager-replay fallback — an
                # unfused replay of an OOM'd graph would OOM harder on a
                # real device (and silently un-fuse on CPU tests).
                results = _oom_recover(e, entry, leaves, sp, prof)
            else:
                donated_dead = any(
                    getattr(l, "is_deleted", _false)()
                    for l in leaves
                    if isinstance(l, jax.Array)
                )
                if aot and not donated_dead:
                    # AOT executables (bg-compile pickups / admission
                    # precompiles) don't re-trace on an input-aval drift the
                    # way jax.jit does — swap in the polymorphic jit under
                    # the same signature and retry
                    prof.counter_inc("lazy_bg_aot_fallbacks")
                    if sp is not None:
                        sp.set(fallback="aot_retrace")
                    jitted = entry[0] = (
                        jax.jit(replay, donate_argnums=don) if don else jax.jit(replay)
                    )
                    try:
                        with _spans().span("compile", cache="miss", fallback="aot_retrace"):
                            results = jitted(*leaves)
                        if don:
                            prof.counter_inc("lazy_donated_buffers", len(don))
                    except Exception as e2:
                        if _hbm.is_oom(e2):
                            results = _oom_recover(e2, entry, leaves, sp, prof)
                        else:
                            results = _fallback_execute(
                                entry, leaves, replay, don, donated_dead, sp, prof
                            )
                else:
                    results = _fallback_execute(
                        entry, leaves, replay, don, donated_dead, sp, prof
                    )

    for (i, j), val in zip(live, results):
        o = nodes[i].out_refs[j]()
        if o is not None:
            o._concrete = val
    _state.inflight = results  # sync() blocks on the last dispatched step

    mem_active = prof._memory_active()
    if async_on and (check_nan or mem_active):
        # post-flush scans move OFF the critical path: enqueued against the
        # dispatched arrays, they run at the next flush / materialization /
        # sync() — the host returns now, overlapping step k+1's trace with
        # step k's device execution
        payload = None
        if check_nan:
            payload = (
                [n2.key[0] for n2 in nodes],
                [n2.fn for n2 in nodes],
                live,
                results,
                leaves,
                descs_all,
            )
            prof.counter_inc("lazy_deferred_checks")
        _enqueue_deferred(sp, payload, mem_active, results)
    else:
        # Memory accounting (profiler profile_memory / FLAGS_profile_memory):
        # live-buffer census at the flush boundary — the point where donated
        # inputs are gone and outputs exist, so the delta IS the step's real
        # memory effect and the peak gauge tracks the high-water mark.
        if mem_active:
            mem = prof.memory_census()
            if sp is not None:
                sp.set(
                    live_bytes=mem["live_bytes"],
                    live_arrays=mem["live_arrays"],
                    peak_live_bytes=mem["peak_live_bytes"],
                    delta_bytes=mem["last_delta_bytes"],
                )
        # FLAGS_check_nan_inf with the async runtime OFF: scan the flush
        # outputs synchronously AFTER the writeback (the materialized state
        # stays inspectable — donation was suppressed above, so pre-step
        # buffers survive too) and raise within the same step.
        if check_nan:
            _nan_check(
                [n2.key[0] for n2 in nodes],
                [n2.fn for n2 in nodes],
                live, results, leaves, descs_all,
            )

    # Release the graph's buffer references: without this, a live LazyArray
    # output (e.g. a held loss) would pin every input buffer of its whole
    # step through node.inputs until the handle died.
    for n2 in nodes:
        n2.inputs = ()
        n2.graph = None


def _fallback_execute(entry, leaves, replay, don, donated_dead, sp, prof):
    """Donation-rejection / eager fallbacks shared by the jit and AOT paths
    (semantics unchanged from the synchronous runtime)."""
    if don and not donated_dead:
        # XLA rejected the donation (or the donating executable failed
        # before invalidating inputs): permanently fall back to a
        # non-donating executable under this signature
        prof.counter_inc("lazy_donation_fallbacks")
        if sp is not None:
            sp.set(fallback="donation_rejected")
        jitted = jax.jit(replay)
        entry[0] = jitted
        entry[3] = ()
        try:
            with _spans().span("compile", cache="miss", fallback="donation_rejected"):
                return jitted(*leaves)
        except Exception as e:
            from ..fault import memory as _mem

            if _mem.is_oom(e):
                # never eat an exhaustion into an unfused replay — it would
                # OOM harder on a real device and silently un-fuse on CPU
                raise
            if sp is not None:
                sp.set(fallback="eager_replay")
            with _spans().span("execute", fallback="eager_replay"):
                return replay(*[jnp.asarray(v) for v in leaves])
    elif donated_dead:
        # inputs were invalidated mid-execution; eager replay impossible
        raise
    else:
        # fallback: run un-jitted (still one pass, concrete ops)
        if sp is not None:
            sp.set(fallback="eager_replay")
        with _spans().span("execute", fallback="eager_replay"):
            return replay(*[jnp.asarray(v) for v in leaves])


def _oom_recover(exc, entry, leaves, sp, prof):
    """Flush-level OOM recovery ladder (fault/memory.py): classify the
    RESOURCE_EXHAUSTED, free pressure (evict cold executables, refresh the
    census, shrink serving pools), retry the SAME executable once, and halt
    with a structured :class:`~paddle_tpu.fault.memory.HbmExhausted` plus a
    flight post-mortem (census + per-executable attributions + attempts)
    when the retry fails too. The microbatch-degrade rung lives one layer
    up, in the engine's train step — the flush has no batch axis to split."""
    from ..fault import memory as _hbm

    attempts = [{"action": "classify", **_hbm.note_oom("lazy_flush", exc)}]
    if sp is not None:
        sp.set(hbm_oom=type(exc).__name__)
    donated_dead = any(
        getattr(l, "is_deleted", _false)()
        for l in leaves
        if isinstance(l, jax.Array)
    )
    if donated_dead:
        # the failed launch already invalidated donated inputs — nothing to
        # retry with; the checkpoint/sentinel layer owns recovery from here
        attempts.append({"action": "retry", "ok": False,
                         "why": "donated inputs invalidated"})
        path = _hbm.post_mortem("lazy_flush", attempts, exc)
        raise _hbm.HbmExhausted("lazy_flush", attempts, path) from exc
    attempts.append({"action": "free_pressure",
                     **_hbm.free_pressure("lazy_flush")})
    try:
        with _spans().span("execute", retry="hbm_oom"):
            from .dispatch import _fault_inject as _finj

            if _finj is not None:
                # consult again: a persistent injected fault (from=) must
                # defeat the retry the way sustained real pressure would
                _finj.maybe_hbm_oom("lazy_flush")
            results = entry[0](*leaves)
    except Exception as e2:
        if not _hbm.is_oom(e2):
            raise
        attempts.append({"action": "retry", "ok": False})
        path = _hbm.post_mortem("lazy_flush", attempts, e2)
        raise _hbm.HbmExhausted("lazy_flush", attempts, path) from e2
    prof.counter_inc("hbm_oom_recoveries")
    attempts.append({"action": "retry", "ok": True})
    if sp is not None:
        sp.set(hbm_oom_recovered=True)
    return results


def _nan_check(keys, fns, live, results, leaves, descs_all,
               deferred=False, producing=None):
    """Post-flush nan/inf scan (reference operator.cc:1171 semantics adapted
    to fused execution). Default mode scans the LIVE flush outputs — a NaN
    in an intermediate that was fused away AND masked out of every live
    output is invisible (the price of keeping fusion). Opt-in
    FLAGS_check_nan_inf_per_op re-runs the graph UNFUSED on every flush and
    checks EVERY node output — full reference parity (dead intermediates
    included) at the reference's documented debug cost (~2x compute).

    In deferred mode (async runtime) the same scan runs against the retained
    arrays at the NEXT flush/materialization/sync; ``producing`` is the
    closed ``lazy_flush`` span of the step that built these values, threaded
    into the flight-recorder dump so the post-mortem still names it."""
    from .dispatch import _nonfinite_error, _prof

    origin_sfx = " (deferred)" if deferred else ""
    extra = None
    if producing is not None:
        extra = {"producing_span": producing.to_dict()}
    if _flags_mod().flag("FLAGS_check_nan_inf_per_op", False):
        # Unfused replay: same wiring, eager ops, every node output checked,
        # first offender attributed to its producing op.
        def check_node(i2, outs):
            for j2, out in enumerate(outs):
                if hasattr(out, "dtype") and jnp.issubdtype(out.dtype, jnp.floating):
                    if not bool(jnp.isfinite(out).all()):
                        _prof().counter_inc("naninf_trips")
                        raise _nonfinite_error(
                            keys[i2], j2, out,
                            origin="lazy per-op replay" + origin_sfx,
                            extra=extra,
                        )

        _interp(fns, descs_all, leaves, on_node=check_node)
        return
    for (i, j), val in zip(live, results):
        if hasattr(val, "dtype") and jnp.issubdtype(val.dtype, jnp.floating):
            if not bool(jnp.isfinite(val).all()):
                _prof().counter_inc("naninf_trips")
                raise _nonfinite_error(
                    keys[i], j, val, origin="lazy flush" + origin_sfx,
                    hint=True, extra=extra,
                )


# -- helpers for the autograd engine ----------------------------------------
def _no_tracer(*xs):
    return not any(isinstance(x, jax.core.Tracer) for x in xs)


def maybe_lazy_binary(fn, a, b, name="lazy_bin"):
    """jnp-style binary op that stays lazy when lazy mode is on (or when an
    operand is already lazy); used by gradient accumulation."""
    if (lazy_enabled() or is_lazy(a) or is_lazy(b)) and _no_tracer(a, b):
        (out,), _ = record(name, fn, [a, b], key=(name, getattr(fn, "__name__", "fn")))
        return out
    return fn(concrete(a), concrete(b))


def lazy_full(shape, dtype, value, name="lazy_full"):
    """Constant creation that embeds into the flushed graph (no host→device
    transfer per call) when lazy mode is on."""
    shape = tuple(shape)
    if lazy_enabled():
        (out,), _ = record(
            name,
            lambda: jnp.full(shape, value, dtype=dtype),
            [],
            key=(name, shape, str(np.dtype(dtype)), float(value)),
        )
        return out
    return jnp.full(shape, value, dtype=dtype)
