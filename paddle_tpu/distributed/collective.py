"""Collective communication API.

Parity: reference ``python/paddle/distributed/collective.py`` wrapping the
C++ collective ops (``paddle/fluid/operators/collective/`` — c_allreduce_sum,
c_allgather, alltoall, send_v2/recv_v2 …, SURVEY.md §2.4).

TPU-native: a collective is an HLO op on a mesh axis. Called inside a
``shard_map``/``pjit`` trace, these lower to ``lax.psum``/``all_gather``/
``all_to_all``/``ppermute`` on ICI. Called eagerly on a single controller,
they are the single-participant identity (world_size given by
``jax.process_count()``) — matching the reference's 1-rank behavior. The
reference's ring-id/comm-stream machinery has no equivalent because XLA's
latency-hiding scheduler owns overlap.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communicator = a mesh axis (reference: NCCL ring / ProcessGroup)."""

    _next_id = 0

    def __init__(self, axis_name: Optional[str] = None, ranks=None, nranks=None):
        Group._next_id += 1
        self.id = Group._next_id
        self.axis_name = axis_name
        self.ranks = ranks or []
        self._nranks = nranks

    @property
    def nranks(self):
        if self._nranks is not None:
            return self._nranks
        if self.axis_name:
            from .mesh import mesh_axis_size

            return mesh_axis_size(self.axis_name)
        return max(len(self.ranks), 1)

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def process_group(self):
        return self


_default_group = None
_groups = {}


def new_group(ranks=None, backend=None, axis_name=None, timeout=None):
    g = Group(axis_name=axis_name, ranks=ranks)
    _groups[g.id] = g
    return g


def get_group(gid=0):
    if gid == 0:
        return _get_default_group()
    return _groups.get(gid)


def _get_default_group():
    global _default_group
    if _default_group is None:
        _default_group = Group(axis_name=None, nranks=jax.process_count())
    return _default_group


def _is_traced(arr) -> bool:
    return isinstance(arr, jax.core.Tracer)


def _axis_bound(axis: str) -> bool:
    """True only inside a shard_map/pmap scope where ``axis`` is a manual
    axis. Under plain jit/GSPMD this is False — the partitioner owns comms
    there and explicit collectives must be identities."""
    from ..core.compat import axis_size

    try:
        axis_size(axis)
        return True
    except Exception:
        return False


def _axis(group):
    if group is not None and group.axis_name:
        return group.axis_name
    return None


def _manual(t, group):
    axis = _axis(group)
    if axis is None or not _is_traced(t._data):
        return None
    return axis if _axis_bound(axis) else None


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True, use_calc_stream=False):
    t = as_tensor(tensor)
    axis = _axis(group)
    if _is_traced(t._data) and axis is not None and _axis_bound(axis):
        fns = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax, ReduceOp.MIN: lax.pmin}
        if op == ReduceOp.AVG:
            out = lax.pmean(t._data, axis)
        elif op == ReduceOp.PROD:
            out = jnp.exp(lax.psum(jnp.log(t._data), axis))
        else:
            out = fns[op](t._data, axis)
        result = Tensor(out, stop_gradient=t.stop_gradient)
        if isinstance(tensor, Tensor):
            tensor._data = result._data
        return result
    # eager single-participant: identity
    return t


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    t = as_tensor(tensor)
    axis = _axis(group)
    if _is_traced(t._data) and axis is not None and _axis_bound(axis):
        gathered = lax.all_gather(t._data, axis)
        n = gathered.shape[0]
        if isinstance(tensor_list, list):
            tensor_list.extend(Tensor(gathered[i]) for i in range(n))
            return
        return Tensor(gathered)
    if isinstance(tensor_list, list):
        tensor_list.append(t)
        return
    return t


def all_gather_into_tensor(out, tensor, group=None, sync_op=True, concat_axis=0):
    t = as_tensor(tensor)
    axis = _axis(group)
    if _is_traced(t._data) and axis is not None and _axis_bound(axis):
        g = lax.all_gather(t._data, axis)
        arr = jnp.concatenate([g[i] for i in range(g.shape[0])], axis=concat_axis)
        return Tensor(arr)
    return t


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    # On TPU a reduce-to-root is an all-reduce; root selection is free under SPMD.
    return all_reduce(tensor, op, group, sync_op)


def reduce_scatter(tensor, tensor_list_or_input, op=ReduceOp.SUM, group=None, sync_op=True):
    inp = as_tensor(tensor_list_or_input if not isinstance(tensor_list_or_input, list) else tensor_list_or_input[0])
    axis = _axis(group)
    if _is_traced(inp._data) and axis is not None and _axis_bound(axis):
        out = lax.psum_scatter(inp._data, axis, scatter_dimension=0, tiled=True)
        if isinstance(tensor, Tensor):
            tensor._data = out
        return Tensor(out)
    return inp


def broadcast(tensor, src=0, group=None, sync_op=True):
    t = as_tensor(tensor)
    axis = _axis(group)
    if _is_traced(t._data) and axis is not None and _axis_bound(axis):
        idx = lax.axis_index(axis)
        src_val = lax.all_gather(t._data, axis)[src]
        if isinstance(tensor, Tensor):
            tensor._data = src_val
        return Tensor(src_val)
    return t


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    t = as_tensor(tensor)
    axis = _axis(group)
    if _is_traced(t._data) and axis is not None and _axis_bound(axis) and tensor_list is not None:
        stacked = jnp.stack([as_tensor(x)._data for x in tensor_list])
        idx = lax.axis_index(axis)
        out = stacked[idx]
        if isinstance(tensor, Tensor):
            tensor._data = out
        return Tensor(out)
    return t


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """Reference: alltoall op (MoE global routing building block)."""
    axis = _axis(group)
    if isinstance(in_tensor_list, list):
        x = jnp.stack([as_tensor(t)._data for t in in_tensor_list])
    else:
        x = as_tensor(in_tensor_list)._data
    if _is_traced(x) and axis is not None and _axis_bound(axis):
        out = lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=False)
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(Tensor(out[i]) for i in range(out.shape[0]))
            return
        return Tensor(out)
    if isinstance(out_tensor_list, list):
        out_tensor_list.extend(Tensor(x[i]) for i in range(x.shape[0]))
        return
    return Tensor(x)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    out_tensor_list = [] if out_tensor_list is None else out_tensor_list
    all_to_all(out_tensor_list, in_tensor_list, group, sync_op)
    return out_tensor_list


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None, out_split_sizes=None, group=None, sync_op=True):
    t = as_tensor(in_tensor)
    axis = _axis(group)
    if _is_traced(t._data) and axis is not None and _axis_bound(axis):
        out = lax.all_to_all(t._data, axis, split_axis=0, concat_axis=0, tiled=True)
        if isinstance(out_tensor, Tensor):
            out_tensor._data = out
        return Tensor(out)
    return t


def p2p_permute(tensor, perm, axis_name):
    """Point-to-point transfer inside shard_map: ``perm`` is a list of
    (src, dst) pairs over ``axis_name`` — the XLA collective-permute that
    replaces the reference's send_v2/recv_v2 NCCL ops
    (paddle/fluid/operators/collective/send_v2_op.cc). Ranks not named as a
    dst receive zeros, matching collective-permute semantics."""
    t = as_tensor(tensor)
    out = lax.ppermute(t._data, axis_name, perm)
    return Tensor(out)


def _p2p_unsupported(name):
    raise NotImplementedError(
        f"paddle_tpu.distributed.{name}: host-level eager p2p has no XLA "
        "equivalent on TPU — p2p is compiler-scheduled. Use p2p_permute "
        "inside shard_map (pipeline schedules do this; see "
        "fleet/meta_parallel/pipeline_parallel.py), or all_gather/broadcast "
        "for host-visible exchange."
    )


def send(tensor, dst=0, group=None, sync_op=True):
    _p2p_unsupported("send")


def recv(tensor, src=0, group=None, sync_op=True):
    _p2p_unsupported("recv")


def isend(tensor, dst=0, group=None):
    _p2p_unsupported("isend")


def irecv(tensor, src=0, group=None):
    _p2p_unsupported("irecv")


def barrier(group=None):
    # the watchdog guard is a float compare when FLAGS_collective_timeout_s
    # is 0 (no thread, no sync); armed, an effects barrier that never
    # returns — a dead peer in a multi-controller world — trips the deadline
    # and exits resumably instead of hanging the job forever
    from . import watchdog

    with watchdog.guard("barrier"):
        jax.effects_barrier()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and not _is_traced(tensor._data):
        from . import watchdog

        with watchdog.guard("wait"):
            tensor._data.block_until_ready()


def split(x, num_partitions, axis=0, group=None):
    from ..ops.manipulation import split as _split

    return _split(x, num_partitions, axis)


# -- quantized collectives (EQuARX, arXiv:2506.17615) ------------------------
# Blockwise-scaled int8 compression around the DP gradient collectives: each
# `block`-element tile carries one f32 scale (amax/127), so the wire payload
# drops ~4x vs f32 (1 byte/elem + 4/block scale bytes). These are ARRAY-level
# primitives meant to run inside a shard_map trace over a mesh axis; the
# bucket layer (fleet/grad_buckets.py) guarantees flat inputs whose length
# divides evenly into nranks shards of whole blocks.

def blockwise_quantize(flat, block=128):
    """flat (m,) float -> (q int8 (m/block, block), scale f32 (m/block, 1)).
    m must be a multiple of block."""
    xb = flat.astype(jnp.float32).reshape(-1, block)
    scale = jnp.maximum(jnp.max(jnp.abs(xb), axis=1, keepdims=True), 1e-30) / 127.0
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    return q, scale


def blockwise_dequantize(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).reshape(-1).astype(dtype)


def quantized_psum_scatter_mean(flat, axis_name, nranks, block=128):
    """Quantized reduce-scatter-mean of ``flat`` (padded,) over ``axis_name``.

    Each rank splits its local bucket into ``nranks`` shards, compresses
    every shard blockwise to int8, and all-to-alls so rank i collects all
    ranks' version of shard i; dequantize + sum + /n gives the mean shard in
    f32. Returns ``(shard (padded/n,) f32, err (padded,) f32)`` where ``err``
    is the LOCAL compression residual (x - dequant(quant(x))) — the
    error-feedback accumulator adds it to the next step's gradient so the
    suppressed mass is eventually transmitted.
    """
    s = flat.shape[0] // nranks
    # shards are whole blocks (buckets are padded to nranks*block), so the
    # flat blockwise quantization reshapes losslessly into per-shard tiles
    q, scale = blockwise_quantize(flat, block)
    err = flat.astype(jnp.float32) - blockwise_dequantize(q, scale)
    qt = lax.all_to_all(q.reshape(nranks, s // block, block), axis_name, 0, 0)
    st = lax.all_to_all(scale.reshape(nranks, s // block, 1), axis_name, 0, 0)
    shard = jnp.sum(qt.astype(jnp.float32) * st, axis=0).reshape(-1) / nranks
    return shard, err


def quantized_all_reduce_mean(flat, axis_name, nranks, block=128):
    """Quantized all-reduce-mean: quantized reduce-scatter, then the reduced
    shard is re-quantized and all-gathered (both wire phases int8+scales).
    Returns ``(mean (padded,) f32, err (padded,) f32)``; ``err`` covers the
    reduce-scatter phase (the dominant term — the gather phase's error is
    identical on every replica so the model stays consistent)."""
    shard, err = quantized_psum_scatter_mean(flat, axis_name, nranks, block)
    q2, s2 = blockwise_quantize(shard, block)
    qg = lax.all_gather(q2.reshape(-1), axis_name, tiled=True)
    sg = lax.all_gather(s2.reshape(-1), axis_name, tiled=True)
    out = blockwise_dequantize(qg.reshape(-1, block), sg.reshape(-1, 1))
    return out, err


# -- mp helper prims (reference collective.py:790,876,924,1032) --------------
def _c_identity(tensor, group=None):
    """Forward identity; backward all-reduce (column-parallel input)."""
    t = as_tensor(tensor)
    axis = _axis(group)
    if not (_is_traced(t._data) and axis is not None and _axis_bound(axis)):
        return t

    @jax.custom_vjp
    def ident(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, ct):
        return (lax.psum(ct, axis),)

    ident.defvjp(fwd, bwd)
    return eager_call("c_identity", ident, [t])


def _mp_allreduce(tensor, group=None):
    """Forward all-reduce; backward identity (row-parallel output)."""
    t = as_tensor(tensor)
    axis = _axis(group)
    if not (_is_traced(t._data) and axis is not None and _axis_bound(axis)):
        return t

    @jax.custom_vjp
    def ar(x):
        return lax.psum(x, axis)

    def fwd(x):
        return lax.psum(x, axis), None

    def bwd(_, ct):
        return (ct,)

    ar.defvjp(fwd, bwd)
    return eager_call("mp_allreduce", ar, [t])


def _c_split(tensor, group=None):
    """Split along last dim, keep this rank's shard (fwd); all-gather (bwd)."""
    t = as_tensor(tensor)
    axis = _axis(group)
    if not (_is_traced(t._data) and axis is not None and _axis_bound(axis)):
        return t
    n = group.nranks

    def fn(x):
        idx = lax.axis_index(axis)
        size = x.shape[-1] // n
        return lax.dynamic_slice_in_dim(x, idx * size, size, axis=x.ndim - 1)

    return eager_call("c_split", fn, [t])


def _c_concat(tensor, group=None):
    """All-gather along last dim (column-parallel output gather)."""
    t = as_tensor(tensor)
    axis = _axis(group)
    if not (_is_traced(t._data) and axis is not None and _axis_bound(axis)):
        return t

    def fn(x):
        return lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)

    return eager_call("c_concat", fn, [t])


def _c_softmax_with_cross_entropy(logits, label, group=None, ignore_index=-100):
    """Vocab-sharded softmax CE (reference collective.py:1032 +
    c_softmax_with_cross_entropy_op.cu): logits sharded on the class dim
    across the mp axis; per-rank partial max/sum are all-reduced."""
    lg, lb = as_tensor(logits), as_tensor(label)
    axis = _axis(group)
    if not (_is_traced(lg._data) and axis is not None and _axis_bound(axis)):
        from ..nn.functional.loss import cross_entropy

        return cross_entropy(lg, lb, reduction="none", ignore_index=ignore_index)
    n = group.nranks

    def fn(x, lab):
        # x: (..., V/n) local shard of logits
        local_max = jnp.max(x, axis=-1, keepdims=True)
        gmax = lax.pmax(local_max, axis)
        ex = jnp.exp(x - gmax)
        local_sum = jnp.sum(ex, axis=-1, keepdims=True)
        gsum = lax.psum(local_sum, axis)
        logp = x - gmax - jnp.log(gsum)
        vshard = x.shape[-1]
        ridx = lax.axis_index(axis)
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == x.ndim:
            lab_i = jnp.squeeze(lab_i, -1)
        local_lab = lab_i - ridx * vshard
        in_range = (local_lab >= 0) & (local_lab < vshard)
        safe = jnp.clip(local_lab, 0, vshard - 1)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        loss_local = jnp.where(in_range, -picked, 0.0)
        return lax.psum(loss_local, axis)

    return eager_call("c_softmax_with_cross_entropy", fn, [lg, lb])
