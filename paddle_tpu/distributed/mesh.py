"""Device mesh management.

TPU-native replacement for the reference's communicator-group machinery
(``HybridCommunicateGroup`` topology ``fleet/base/topology.py:36,117``, NCCL
ring ids ``platform/collective_helper.h:71``): one ``jax.sharding.Mesh``
whose named axes (dp/pp/tp/sp/ep…) ARE the communicator groups — XLA lowers
per-axis collectives onto ICI rings automatically.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_global_mesh: Optional[Mesh] = None


def build_mesh(axis_names: Sequence[str], shape: Sequence[int], devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def set_global_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh


def global_mesh() -> Mesh:
    global _global_mesh
    if _global_mesh is None:
        devs = jax.devices()
        _global_mesh = Mesh(np.asarray(devs), ("dp",))
    return _global_mesh


def shard_map_compat():
    """(shard_map, check_kwargs) across jax versions — delegates to the
    one-file shim in ``core/compat.py`` (the stable ``jax.shard_map`` takes
    ``check_vma``; the older experimental API takes ``check_rep``)."""
    from ..core.compat import shard_map, shard_map_check_kwargs

    return shard_map, shard_map_check_kwargs(False)


def mesh_axis_size(axis: str) -> int:
    m = global_mesh()
    return m.shape.get(axis, 1) if hasattr(m.shape, "get") else dict(zip(m.axis_names, m.devices.shape)).get(axis, 1)


def named_sharding(*spec) -> NamedSharding:
    return NamedSharding(global_mesh(), PartitionSpec(*spec))
