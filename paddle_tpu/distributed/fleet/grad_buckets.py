"""Gradient bucketing for data-parallel sync.

Parity: the reference C++ Reducer (``paddle/fluid/imperative/reducer.cc`` —
``Group`` buffers: dtype-homogeneous, ``comm_buffer_size``-capped, filled in
REVERSE registration order so the first bucket to fill is the last layer's,
whose backward finishes first) and the sharding-stage grad storages
(``fleet/meta_parallel/sharding/group_sharded_storage.py``).

TPU-native role: coalesce per-param gradients into a handful of large flat
arrays so the DP sync is a few big collectives instead of hundreds of small
ones. Buckets are emitted in reverse-backward order, so inside the one fused
train-step executable XLA's latency-hiding scheduler can overlap each
bucket's reduce-scatter/all-reduce with the backward compute of earlier
layers that hasn't run yet. The plan's ``signature`` is hashable and folds
into executable cache keys (lazy-flush signature, engine jit identity), so a
fixed model keeps hitting the warm compiled step.

The same flat layout drives the ZeRO-1 sharded weight update
("Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training", arXiv:2004.13336): every bucket is padded to a multiple of
``nranks * block`` elements, so a bucket splits evenly into per-replica
shards AND every shard splits evenly into quantization blocks (EQuARX,
arXiv:2506.17615).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

# Default bucket cap: the reference DataParallel's comm_buffer_size=25 (MB).
DEFAULT_BUCKET_BYTES = 25 * 1024 * 1024
# Quantization/shard granularity: one v5e lane tile. Buckets are padded to a
# multiple of nranks*block so shards and blocks always divide evenly.
DEFAULT_BLOCK = 128


class Bucket:
    """One fused sync unit: a contiguous run of params (reverse-backward
    order) sharing dtype and per-param optimizer attributes."""

    __slots__ = ("indices", "shapes", "sizes", "offsets", "dtype", "size",
                 "padded", "wds", "plr")

    def __init__(self, indices, shapes, sizes, offsets, dtype, size, padded,
                 wds, plr):
        self.indices = tuple(indices)    # positions into the plan's param list
        self.shapes = tuple(shapes)
        self.sizes = tuple(sizes)
        self.offsets = tuple(offsets)    # offset of each param in the flat view
        self.dtype = np.dtype(dtype)
        self.size = int(size)            # live elements (sum of sizes)
        self.padded = int(padded)        # flat length incl. padding
        self.wds = tuple(float(w) for w in wds)  # per-param decay gates
        self.plr = float(plr)            # homogeneous per-param lr multiplier

    @property
    def itemsize(self):
        return self.dtype.itemsize

    @property
    def wd_scale(self):
        """Scalar decay gate when homogeneous across the bucket, else None
        (use ``BucketPlan.wd_vector`` for the per-element gate)."""
        return self.wds[0] if len(set(self.wds)) <= 1 else None

    def key(self):
        return (self.indices, str(self.dtype), self.padded, self.wds, self.plr)


class BucketPlan:
    """Static bucket geometry for a fixed parameter list.

    ``nranks`` is the DP world the buckets will be reduce-scattered over
    (1 = pure bucketing, no shard constraint beyond block alignment).
    """

    def __init__(self, buckets: Sequence[Bucket], nranks: int, block: int):
        self.buckets = list(buckets)
        self.nranks = int(nranks)
        self.block = int(block)
        self.signature = (self.nranks, self.block,
                          tuple(b.key() for b in self.buckets))

    def __len__(self):
        return len(self.buckets)

    # -- flat view ---------------------------------------------------------
    def flatten(self, bucket: Bucket, arrays):
        """Concatenate the bucket's arrays (reverse-backward order) into one
        padded 1-D array of the bucket dtype."""
        parts = [jnp.reshape(a, (-1,)).astype(bucket.dtype) for a in arrays]
        pad = bucket.padded - bucket.size
        if pad:
            parts.append(jnp.zeros((pad,), bucket.dtype))
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def unflatten(self, bucket: Bucket, flat):
        """Slice a flat bucket back into per-param arrays (plan order)."""
        return [
            jnp.reshape(flat[off:off + sz], shape)
            for off, sz, shape in zip(bucket.offsets, bucket.sizes, bucket.shapes)
        ]

    def shard_size(self, bucket: Bucket) -> int:
        return bucket.padded // self.nranks

    def wd_vector(self, bucket: Bucket):
        """Per-element decay gate for a mixed-wd bucket (e.g. AdamW with
        ``apply_decay_param_fun`` excluding biases): the elementwise rules
        broadcast it in place of the scalar ``wd_scale``. None when the
        bucket is homogeneous. Padding lanes get 1.0 (their updates are
        never read back)."""
        if bucket.wd_scale is not None:
            return None
        parts = [np.full((sz,), w, np.float32)
                 for sz, w in zip(bucket.sizes, bucket.wds)]
        parts.append(np.ones((bucket.padded - bucket.size,), np.float32))
        return jnp.asarray(np.concatenate(parts))

    # -- analytic wire accounting -----------------------------------------
    # Per-replica payload bytes entering the DP gradient-sync collectives for
    # ONE step. ``reduce_scatter`` counts one pass over the bucket,
    # ``all_reduce`` two (the reduce-scatter + all-gather phases of a ring).
    # Quantized buckets ship int8 payload + one f32 scale per block.
    def sync_bytes(self, mode: str = "reduce_scatter", quantized: bool = False) -> int:
        phases = 2 if mode == "all_reduce" else 1
        total = 0
        for b in self.buckets:
            if quantized:
                payload = b.padded * 1 + (b.padded // self.block) * 4
            else:
                payload = b.padded * b.itemsize
            total += payload * phases
        return total

    def gather_bytes(self) -> int:
        """Per-replica bytes of the ZeRO-1 updated-param all-gather (full
        precision — weights are not quantized)."""
        return sum(b.padded * b.itemsize for b in self.buckets)


def build_bucket_plan(
    params,
    nranks: int = 1,
    bucket_bytes: Optional[int] = None,
    block: int = DEFAULT_BLOCK,
    wd_of: Optional[Callable] = None,
    plr_of: Optional[Callable] = None,
) -> BucketPlan:
    """Build a plan over ``params`` (objects exposing ``shape``/``dtype``
    via their array, i.e. paddle Tensors or jax arrays).

    Buckets are formed by walking params in REVERSE registration order
    (last layer first — its gradient materializes first in backward) and
    splitting whenever dtype / wd gate / lr multiplier changes or the byte
    cap fills, mirroring reducer.cc's group assembly.
    """
    bucket_bytes = int(bucket_bytes or DEFAULT_BUCKET_BYTES)
    nranks = max(int(nranks), 1)
    align = nranks * int(block)

    metas = []  # (orig_index, shape, size, dtype, wd, plr) in reverse order
    n = len(list(params))
    for rev_pos, p in enumerate(reversed(list(params))):
        arr = getattr(p, "_data", p)
        shape = tuple(int(s) for s in arr.shape)
        size = int(np.prod(shape)) if shape else 1
        dt = np.dtype(arr.dtype)
        wd = float(wd_of(p)) if wd_of is not None else 1.0
        plr = float(plr_of(p)) if plr_of is not None else 1.0
        metas.append((n - 1 - rev_pos, shape, size, dt, wd, plr))

    buckets: List[Bucket] = []
    cur: list = []
    cur_bytes = 0
    cur_key = None

    def close():
        nonlocal cur, cur_bytes
        if not cur:
            return
        indices = [m[0] for m in cur]
        shapes = [m[1] for m in cur]
        sizes = [m[2] for m in cur]
        offsets = list(np.cumsum([0] + sizes[:-1]).astype(int)) if sizes else []
        size = int(sum(sizes))
        padded = int(-(-size // align) * align)
        dt, plr = cur[0][3], cur[0][5]
        wds = [m[4] for m in cur]
        buckets.append(Bucket(indices, shapes, sizes, offsets, dt, size,
                              padded, wds, plr))
        cur, cur_bytes = [], 0

    for m in metas:
        _, _, size, dt, wd, plr = m
        key = (dt, plr)  # wd may vary inside a bucket (per-element gate)
        nbytes = size * dt.itemsize
        if cur and (key != cur_key or cur_bytes + nbytes > bucket_bytes):
            close()
        cur_key = key
        cur.append(m)
        cur_bytes += nbytes
    close()
    return BucketPlan(buckets, nranks, block)


__all__ = ["Bucket", "BucketPlan", "build_bucket_plan",
           "DEFAULT_BUCKET_BYTES", "DEFAULT_BLOCK"]
