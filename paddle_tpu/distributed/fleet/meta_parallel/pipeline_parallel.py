"""Pipeline-parallel execution.

Parity: reference ``fleet/meta_parallel/pipeline_parallel.py`` (1F1B schedule
``forward_backward_pipeline:80``, ``train_batch:152``) + the p2p protocol
(``pp_utils/p2p_communication.py`` over send_v2/recv_v2) + the static
SectionWorker (``framework/section_worker.cc:153``).

TPU-native: **collective-permute pipelining**. All stages run the SAME SPMD
program inside one shard_map over the 'pp' mesh axis; activations move to the
next stage with ``lax.ppermute`` each tick. The schedule loop is traced, so
XLA overlaps the permute with compute (the role of the reference's separate
comm streams), and reverse-mode AD through the loop yields the backward
pipeline automatically — interleaved like 1F1B, with jax.checkpoint
rematerialization standing in for activation stashing policy.

Requires uniform stages: each stage applies the same layer structure with its
own weights (stacked leading 'pp' dim) — the standard TPU formulation. GPT
decoder stacks satisfy this; embedding/head are handled by first/last-stage
masks.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, List

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer


def spmd_pipeline_fn(stage_fn: Callable, n_stages: int, n_micro: int, axis: str = "pp"):
    """Build f(stage_params, microbatches) -> outputs, to be called INSIDE a
    shard_map over ``axis``.

    stage_fn(params, x) -> y : one stage's compute, same structure per stage.
    microbatches: (n_micro, mb, ...) — only stage 0's input is consumed.
    Returns (n_micro, mb, ...) outputs valid on the LAST stage.

    GPipe timeline: T = n_micro + n_stages - 1 ticks; at tick t stage s
    processes microbatch t - s. The state buffer holds each stage's current
    activation; ppermute shifts stage outputs downstream each tick.
    """

    def pipelined(params, microbatches):
        stage_id = lax.axis_index(axis)
        mb_shape = microbatches.shape[1:]
        total = n_micro + n_stages - 1
        zero = jnp.zeros(mb_shape, microbatches.dtype)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (if in range), others take state
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            fresh = lax.dynamic_index_in_dim(microbatches, mb_idx, keepdims=False)
            x = jnp.where(stage_id == 0, fresh, state)
            y = stage_fn(params, x)
            # last stage writes output for microbatch t - (n_stages - 1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            write = (t - (n_stages - 1) >= 0) & (stage_id == n_stages - 1)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(o, y, out_idx, axis=0),
                lambda o: o,
                outputs,
            )
            # shift downstream (stage s → s+1); wraparound into stage0 ignored
            state_next = lax.ppermute(y, axis, perm)
            return (state_next, outputs), None

        outputs0 = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)
        (_, outputs), _ = lax.scan(tick, (zero, outputs0), jnp.arange(total))
        return outputs

    return pipelined


class PipelineTrainStep:
    """Compiled pipelined train step over non-uniform stages.

    The whole GPipe timeline (n_micro + n_stages - 1 ticks) is ONE traced
    ``lax.scan`` inside a ``shard_map`` over the 'pp' mesh axis; at each tick
    every stage runs its OWN segment via ``lax.switch(stage_id, ...)`` —
    embedding on stage 0, loss head on the last stage (the reference's
    first/last-stage special cases, pipeline_parallel.py:152 `_forward_step` /
    `pp_layers.py` loss_fn) — and hands its activation downstream with
    ``lax.ppermute``. Reverse-mode AD through the scan reverses the permutes,
    yielding the backward pipeline; ``jax.checkpoint`` around each stage call
    bounds activation memory the way 1F1B's eager stashing discipline does.
    Per-microbatch losses are mask-accumulated on the last stage and psum'd so
    the mean loss is replicated (reference train_batch loss reduce
    pipeline_parallel.py:220).
    """

    def __init__(self, pipeline_layer, optimizer, mesh, n_micro, axis="pp"):
        self.pl = pipeline_layer
        self.optimizer = optimizer
        self.mesh = mesh
        self.n_micro = int(n_micro)
        self.axis = axis
        self.n_stages = pipeline_layer.num_stages
        pp_devices = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)
        if self.n_stages != pp_devices:
            raise ValueError(
                f"PipelineLayer has {self.n_stages} stages but mesh axis "
                f"'{axis}' has {pp_devices} devices; they must match"
            )
        self.params = [p for p in pipeline_layer.parameters() if not p.stop_gradient]
        self.buffers = list(pipeline_layer.buffers())
        self._jits = {}  # (mb_shape, dtype) -> (jitted step, carrier)
        self._carrier = None  # (shape, dtype) of the inter-stage activation

    # -- stage bodies ------------------------------------------------------
    def _run_stage(self, stage_id, x):
        """Run stage `stage_id`'s layers on Tensor `x` (tracer-safe)."""
        for layer in self.pl.get_stage_layers(stage_id):
            if isinstance(layer, Layer):
                fwd = getattr(layer, "_pp_forward_func", None)
                x = fwd(layer, x) if fwd is not None else layer(x)
            else:
                x = layer(x)
        return x

    def _probe_carrier(self, mb_input):
        """Shape/dtype of the activation flowing between stages (= stage 0's
        output). All interior boundaries must match it — the constraint of
        collective-permute pipelining (uniform activation shape)."""
        from ....core.engine import no_grad

        def probe(arr):
            with no_grad():
                out = self._run_stage(0, Tensor(arr, stop_gradient=True))
            return out._data

        s = jax.eval_shape(probe, jax.ShapeDtypeStruct(mb_input.shape, mb_input.dtype))
        for mid_s in range(1, self.n_stages - 1):
            def probe_mid(arr, _s=mid_s):
                with no_grad():
                    out = self._run_stage(_s, Tensor(arr, stop_gradient=True))
                return out._data
            mid = jax.eval_shape(probe_mid, jax.ShapeDtypeStruct(s.shape, s.dtype))
            if mid.shape != s.shape or mid.dtype != s.dtype:
                raise ValueError(
                    "pipeline stages must preserve activation shape/dtype "
                    f"between boundaries: stage0 -> {s.shape}/{s.dtype}, "
                    f"stage{mid_s} -> {mid.shape}/{mid.dtype}"
                )
        return s.shape, s.dtype

    # -- compiled step -----------------------------------------------------
    def _build(self):
        from ....core import random as random_state
        from ....core.engine import no_grad

        n_stages, n_micro, axis = self.n_stages, self.n_micro, self.axis
        params, buffers, pl = self.params, self.buffers, self.pl
        loss_fn = getattr(pl, "_loss_fn", None)
        carrier_shape, carrier_dtype = self._carrier
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def step_fn(param_arrays, opt_state, ids_mb, labels_mb, lr, key):
            def loss_of(p_arrays):
                def spmd(p_arrays, ids_mb, labels_mb):
                    saved = [(t, t._data) for t in params + buffers]

                    def bound(fn):
                        # last positional arg is a per-(tick, stage) PRNG key
                        # so dropout masks differ across microbatches/stages
                        def wrapped(*args):
                            *rest, k = args
                            try:
                                for t, a in zip(params, p_arrays):
                                    t._data = a
                                with random_state.traced_keys(k):
                                    with no_grad():
                                        return fn(*rest)
                            finally:
                                for t, a in saved:
                                    t._data = a
                        return wrapped

                    @bound
                    def first_stage(x, ids_t, lbl_t):
                        h = self._run_stage(0, Tensor(ids_t, stop_gradient=True))
                        return h._data.astype(carrier_dtype), jnp.float32(0.0)

                    def mid_stage(s):
                        @bound
                        def run(x, ids_t, lbl_t):
                            h = self._run_stage(s, Tensor(x))
                            return h._data.astype(carrier_dtype), jnp.float32(0.0)
                        return run

                    @bound
                    def last_stage(x, ids_t, lbl_t):
                        out = self._run_stage(n_stages - 1, Tensor(x))
                        if loss_fn is not None:
                            l = loss_fn(out, Tensor(lbl_t, stop_gradient=True))
                        else:
                            l = out.mean()
                        l = l._data if isinstance(l, Tensor) else l
                        return x, l.astype(jnp.float32)

                    branches = (
                        [first_stage]
                        + [mid_stage(s) for s in range(1, n_stages - 1)]
                        + [last_stage]
                    )
                    stage_id = lax.axis_index(axis)

                    def tick(carry, t):
                        x, loss_acc = carry
                        mb_idx = jnp.clip(t - stage_id, 0, n_micro - 1)
                        ids_t = lax.dynamic_index_in_dim(ids_mb, mb_idx, keepdims=False)
                        lbl_t = lax.dynamic_index_in_dim(labels_mb, mb_idx, keepdims=False)
                        k_t = jax.random.fold_in(jax.random.fold_in(key, t), stage_id)
                        run = jax.checkpoint(
                            lambda x, i, l, k: lax.switch(stage_id, branches, x, i, l, k)
                        )
                        y, l = run(x, ids_t, lbl_t, k_t)
                        valid = (t - stage_id >= 0) & (t - stage_id < n_micro)
                        is_last = stage_id == n_stages - 1
                        loss_acc = loss_acc + jnp.where(valid & is_last, l, 0.0)
                        y = lax.ppermute(y, axis, perm)
                        return (y, loss_acc), None

                    x0 = jnp.zeros(carrier_shape, carrier_dtype)
                    (_, loss_acc), _ = lax.scan(
                        tick, (x0, jnp.float32(0.0)), jnp.arange(n_micro + n_stages - 1)
                    )
                    return lax.psum(loss_acc, axis) / n_micro

                from jax.sharding import PartitionSpec as P

                from ...mesh import shard_map_compat

                _shard_map, _check = shard_map_compat()

                fn = _shard_map(
                    spmd,
                    mesh=self.mesh,
                    in_specs=(
                        tuple(P() for _ in p_arrays), P(), P(),
                    ),
                    out_specs=P(),
                    **_check,
                )
                return fn(tuple(p_arrays), ids_mb, labels_mb)

            loss, grads = jax.value_and_grad(loss_of)(list(param_arrays))
            new_params, new_state = self.optimizer._functional_update(
                param_arrays, grads, opt_state, lr, params=params
            )
            return loss, new_params, new_state

        return jax.jit(step_fn, donate_argnums=(0, 1))

    def __call__(self, inputs, labels):
        from ....core import random as random_state
        from ....core.engine import no_grad

        ids = inputs._data if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        lbls = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
        b = ids.shape[0]
        if b % self.n_micro:
            raise ValueError(
                f"batch size {b} not divisible by accumulate_steps {self.n_micro}"
            )
        mb = b // self.n_micro
        ids_mb = ids.reshape((self.n_micro, mb) + ids.shape[1:])
        lbls_mb = lbls.reshape((self.n_micro, mb) + lbls.shape[1:])

        # one executable per input shape: the carrier (inter-stage activation
        # shape) is baked into the schedule, so re-probe + rebuild on change
        shape_key = (ids_mb.shape, str(ids_mb.dtype))
        step = self._jits.get(shape_key)
        if step is None:
            self._carrier = self._probe_carrier(ids_mb[0])
            step = self._jits[shape_key] = self._build()

        with no_grad():
            param_arrays = [p._data for p in self.params]
            opt_state = self.optimizer._functional_state(self.params)
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
            key = random_state.next_key()
            loss, new_params, new_state = step(
                param_arrays, opt_state, ids_mb, lbls_mb, lr, key
            )
            for p, a in zip(self.params, new_params):
                p._set_data(a)
            self.optimizer._functional_restore(self.params, new_state)
            self.optimizer._step_count += 1
        return Tensor(loss)


class PipelineParallelModel(Layer):
    """fleet.distributed_model output for pp_degree>1.

    ``train_batch(data, optimizer)`` compiles one SPMD program: microbatch
    split → pipelined forward (ppermute schedule over the 'pp' axis, per-stage
    ``lax.switch`` bodies) → loss on last stage → AD backward through the
    schedule → fused optimizer update (reference train_batch
    pipeline_parallel.py:152 + 1F1B forward_backward_pipeline:80).
    """

    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg
        self._strategy = strategy
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.micro_batches = strategy.pipeline_configs.get("accumulate_steps", 1)
        self._train_fn = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Pipelined train step. pp_degree>1 requires a PipelineLayer (the
        reference imposes the same: pipeline_parallel.py asserts
        isinstance(layers, PipelineLayer)); pp_degree==1 runs a plain fused
        step."""
        from ....jit import CompiledTrainStep
        from .pp_layers import PipelineLayer

        inputs, labels = data

        if scaler is not None:
            raise NotImplementedError(
                "train_batch does not thread GradScaler loss scaling through the "
                "compiled pipeline step; train in bf16 (TPU-native, no scaling "
                "needed) or scale the loss inside the model's loss_fn"
            )
        if self.num_stages > 1 and not isinstance(self._layers, PipelineLayer):
            raise TypeError(
                "pp_degree>1 requires the model to be a PipelineLayer; got "
                f"{type(self._layers).__name__}"
            )
        if self.num_stages > 1:
            acc = max(self.micro_batches, 1)
            mb_cfg = self._strategy.pipeline_configs.get("micro_batch_size")
            b = inputs.shape[0]
            if acc > 1 and mb_cfg and b != acc * mb_cfg:
                raise ValueError(
                    f"batch size {b} != accumulate_steps({acc}) * "
                    f"micro_batch_size({mb_cfg}); fix pipeline_configs"
                )
            if self._train_fn is None:
                self._train_fn = PipelineTrainStep(
                    self._layers, optimizer, self._hcg.mesh, n_micro=acc, axis="pp",
                )
            loss = self._train_fn(inputs, labels)
        else:
            loss_fn = getattr(self._layers, "_loss_fn", None)

            def full_loss(model, x, y):
                out = model(x)
                if loss_fn is not None:
                    return loss_fn(out, y)
                return out.mean()

            if self._train_fn is None:
                self._train_fn = CompiledTrainStep(self._layers, full_loss, optimizer)
            loss = self._train_fn(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
