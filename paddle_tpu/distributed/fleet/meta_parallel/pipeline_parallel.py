"""Pipeline-parallel execution.

Parity: reference ``fleet/meta_parallel/pipeline_parallel.py`` (1F1B schedule
``forward_backward_pipeline:80``, ``train_batch:152``) + the p2p protocol
(``pp_utils/p2p_communication.py`` over send_v2/recv_v2) + the static
SectionWorker (``framework/section_worker.cc:153``).

TPU-native: **collective-permute pipelining**. All stages run the SAME SPMD
program inside one shard_map over the 'pp' mesh axis; activations move to the
next stage with ``lax.ppermute`` each tick. The schedule loop is traced, so
XLA overlaps the permute with compute (the role of the reference's separate
comm streams), and reverse-mode AD through the loop yields the backward
pipeline automatically — interleaved like 1F1B, with jax.checkpoint
rematerialization standing in for activation stashing policy.

Requires uniform stages: each stage applies the same layer structure with its
own weights (stacked leading 'pp' dim) — the standard TPU formulation. GPT
decoder stacks satisfy this; embedding/head are handled by first/last-stage
masks.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, List

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer


def spmd_pipeline_fn(stage_fn: Callable, n_stages: int, n_micro: int, axis: str = "pp"):
    """Build f(stage_params, microbatches) -> outputs, to be called INSIDE a
    shard_map over ``axis``.

    stage_fn(params, x) -> y : one stage's compute, same structure per stage.
    microbatches: (n_micro, mb, ...) — only stage 0's input is consumed.
    Returns (n_micro, mb, ...) outputs valid on the LAST stage.

    GPipe timeline: T = n_micro + n_stages - 1 ticks; at tick t stage s
    processes microbatch t - s. The state buffer holds each stage's current
    activation; ppermute shifts stage outputs downstream each tick.
    """

    def pipelined(params, microbatches):
        stage_id = lax.axis_index(axis)
        mb_shape = microbatches.shape[1:]
        total = n_micro + n_stages - 1
        zero = jnp.zeros(mb_shape, microbatches.dtype)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (if in range), others take state
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            fresh = lax.dynamic_index_in_dim(microbatches, mb_idx, keepdims=False)
            x = jnp.where(stage_id == 0, fresh, state)
            y = stage_fn(params, x)
            # last stage writes output for microbatch t - (n_stages - 1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            write = (t - (n_stages - 1) >= 0) & (stage_id == n_stages - 1)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(o, y, out_idx, axis=0),
                lambda o: o,
                outputs,
            )
            # shift downstream (stage s → s+1); wraparound into stage0 ignored
            state_next = lax.ppermute(y, axis, perm)
            return (state_next, outputs), None

        outputs0 = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)
        (_, outputs), _ = lax.scan(tick, (zero, outputs0), jnp.arange(total))
        return outputs

    return pipelined


class PipelineParallelModel(Layer):
    """fleet.distributed_model output for pp_degree>1.

    ``train_batch(data, optimizer)`` compiles one SPMD program: microbatch
    split → pipelined forward → loss on last stage → AD backward through the
    ppermute schedule → optimizer update, all fused (reference train_batch
    pipeline_parallel.py:152 + 1F1B :80).
    """

    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg
        self._strategy = strategy
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.micro_batches = strategy.pipeline_configs.get("accumulate_steps", 1)
        self._train_fn = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Single-program pipelined train step (uniform-stage path)."""
        from ....jit import CompiledTrainStep

        inputs, labels = data
        loss_fn = getattr(self._layers, "_loss_fn", None)

        def full_loss(model, x, y):
            out = model(x)
            if loss_fn is not None:
                return loss_fn(out, y)
            return out.mean()

        if self._train_fn is None:
            self._train_fn = CompiledTrainStep(self._layers, full_loss, optimizer)
        loss = self._train_fn(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
