"""Pipeline-parallel execution.

Parity: reference ``fleet/meta_parallel/pipeline_parallel.py`` (1F1B schedule
``forward_backward_pipeline:80``, ``train_batch:152``) + the p2p protocol
(``pp_utils/p2p_communication.py`` over send_v2/recv_v2) + the static
SectionWorker (``framework/section_worker.cc:153``).

TPU-native: **collective-permute pipelining**. All stages run the SAME SPMD
program inside one shard_map over the 'pp' mesh axis; activations move to the
next stage with ``lax.ppermute`` each tick. Two schedules:

* ``1F1B`` (default, reference default): EXPLICIT interleaved
  forward/backward sub-ticks with hand-rolled per-stage ``jax.vjp`` and a
  circular activation stash of depth 2·n_stages — live activations are
  O(n_stages), independent of n_micro (``_build_1f1b``).
* ``F-then-B`` (GPipe): reverse-mode AD through the forward scan with
  ``jax.checkpoint`` per stage — simpler, but the AD residual stack grows
  O(n_micro) (``_build``).

Requires uniform stages: each stage applies the same layer structure with its
own weights (stacked leading 'pp' dim) — the standard TPU formulation. GPT
decoder stacks satisfy this; embedding/head are handled by first/last-stage
masks.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, List

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer


def spmd_pipeline_fn(stage_fn: Callable, n_stages: int, n_micro: int, axis: str = "pp"):
    """Build f(stage_params, microbatches) -> outputs, to be called INSIDE a
    shard_map over ``axis``.

    stage_fn(params, x) -> y : one stage's compute, same structure per stage.
    microbatches: (n_micro, mb, ...) — only stage 0's input is consumed.
    Returns (n_micro, mb, ...) outputs valid on the LAST stage.

    GPipe timeline: T = n_micro + n_stages - 1 ticks; at tick t stage s
    processes microbatch t - s. The state buffer holds each stage's current
    activation; ppermute shifts stage outputs downstream each tick.
    """

    def pipelined(params, microbatches):
        stage_id = lax.axis_index(axis)
        mb_shape = microbatches.shape[1:]
        total = n_micro + n_stages - 1
        zero = jnp.zeros(mb_shape, microbatches.dtype)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (if in range), others take state
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            fresh = lax.dynamic_index_in_dim(microbatches, mb_idx, keepdims=False)
            x = jnp.where(stage_id == 0, fresh, state)
            y = stage_fn(params, x)
            # last stage writes output for microbatch t - (n_stages - 1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            write = (t - (n_stages - 1) >= 0) & (stage_id == n_stages - 1)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(o, y, out_idx, axis=0),
                lambda o: o,
                outputs,
            )
            # shift downstream (stage s → s+1); wraparound into stage0 ignored
            state_next = lax.ppermute(y, axis, perm)
            return (state_next, outputs), None

        outputs0 = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)
        (_, outputs), _ = lax.scan(tick, (zero, outputs0), jnp.arange(total))
        return outputs

    return pipelined


class PipelineTrainStep:
    """Compiled pipelined train step over non-uniform stages.

    The schedule is ONE traced ``lax.scan`` inside a ``shard_map`` over the
    'pp' mesh axis; at each tick every stage runs its OWN segment via
    ``lax.switch(stage_id, ...)`` — embedding on stage 0, loss head on the
    last stage (the reference's first/last-stage special cases,
    pipeline_parallel.py:152 `_forward_step` / `pp_layers.py` loss_fn) — and
    hands its activation downstream with ``lax.ppermute``. Per-microbatch
    losses are mask-accumulated on the last stage and psum'd so the mean loss
    is replicated (reference train_batch loss reduce pipeline_parallel.py:220).

    ``schedule="1F1B"`` (default) runs the explicit interleaved schedule of
    ``_build_1f1b`` — hand-rolled per-stage backward, O(n_stages) activation
    stash. ``schedule="F-then-B"`` runs the GPipe formulation of ``_build`` —
    reverse-mode AD through the forward scan (residual stack O(n_micro),
    bounded per-tick by ``jax.checkpoint``). Both share the stage-body
    protocol of ``_stage_caller``.
    """

    def __init__(self, pipeline_layer, optimizer, mesh, n_micro, axis="pp",
                 schedule="1F1B"):
        self.pl = pipeline_layer
        self.optimizer = optimizer
        self.mesh = mesh
        self.n_micro = int(n_micro)
        self.axis = axis
        self.schedule = str(schedule).upper().replace("-", "")
        if self.schedule not in ("1F1B", "FTHENB"):
            raise ValueError(
                f"schedule_mode must be '1F1B' or 'F-then-B', got {schedule!r}"
            )
        self.n_stages = pipeline_layer.num_stages
        pp_devices = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)
        if self.n_stages != pp_devices:
            raise ValueError(
                f"PipelineLayer has {self.n_stages} stages but mesh axis "
                f"'{axis}' has {pp_devices} devices; they must match"
            )
        self.params = [p for p in pipeline_layer.parameters() if not p.stop_gradient]
        self.buffers = list(pipeline_layer.buffers())
        self._jits = {}  # (mb_shape, dtype) -> (jitted step, carrier)
        self._carrier = None  # (shape, dtype) of the inter-stage activation

    # -- stage bodies ------------------------------------------------------
    def _run_stage(self, stage_id, x):
        """Run stage `stage_id`'s layers on Tensor `x` (tracer-safe)."""
        for layer in self.pl.get_stage_layers(stage_id):
            if isinstance(layer, Layer):
                fwd = getattr(layer, "_pp_forward_func", None)
                x = fwd(layer, x) if fwd is not None else layer(x)
            else:
                x = layer(x)
        return x

    def _probe_carrier(self, mb_input):
        """Shape/dtype of the activation flowing between stages (= stage 0's
        output). All interior boundaries must match it — the constraint of
        collective-permute pipelining (uniform activation shape)."""
        from ....core.engine import no_grad

        def probe(arr):
            with no_grad():
                out = self._run_stage(0, Tensor(arr, stop_gradient=True))
            return out._data

        s = jax.eval_shape(probe, jax.ShapeDtypeStruct(mb_input.shape, mb_input.dtype))
        for mid_s in range(1, self.n_stages - 1):
            def probe_mid(arr, _s=mid_s):
                with no_grad():
                    out = self._run_stage(_s, Tensor(arr, stop_gradient=True))
                return out._data
            mid = jax.eval_shape(probe_mid, jax.ShapeDtypeStruct(s.shape, s.dtype))
            if mid.shape != s.shape or mid.dtype != s.dtype:
                raise ValueError(
                    "pipeline stages must preserve activation shape/dtype "
                    f"between boundaries: stage0 -> {s.shape}/{s.dtype}, "
                    f"stage{mid_s} -> {mid.shape}/{mid.dtype}"
                )
        return s.shape, s.dtype

    # -- stage body shared by both schedules -------------------------------
    def _stage_caller(self, carrier_dtype):
        """Build ``call(p_arrs, s, x, ids_t, lbl_t, k) -> (carrier, loss)``:
        the ONE stage-body protocol both schedules use — param ``_data``
        swap under try/finally, per-(microbatch, stage) PRNG binding,
        no_grad (jax traces through; the paddle tape stays off), stage-0
        embedding ingest and last-stage loss special cases."""
        from ....core import random as random_state
        from ....core.engine import no_grad

        params, buffers = self.params, self.buffers
        n_stages = self.n_stages
        loss_fn = getattr(self.pl, "_loss_fn", None)

        def call(p_arrs, s, x, ids_t, lbl_t, k):
            saved = [(t, t._data) for t in params + buffers]
            try:
                for t, a in zip(params, p_arrs):
                    t._data = a
                with random_state.traced_keys(k):
                    with no_grad():
                        if s == 0:
                            h = self._run_stage(0, Tensor(ids_t, stop_gradient=True))
                            return h._data.astype(carrier_dtype), jnp.float32(0.0)
                        out = self._run_stage(s, Tensor(x))
                        if s == n_stages - 1:
                            if loss_fn is not None:
                                l = loss_fn(out, Tensor(lbl_t, stop_gradient=True))
                            else:
                                l = out.mean()
                            l = l._data if isinstance(l, Tensor) else l
                            return x, l.astype(jnp.float32)
                        return out._data.astype(carrier_dtype), jnp.float32(0.0)
            finally:
                for t, a in saved:
                    t._data = a

        return call

    # -- compiled step (F-then-B / GPipe schedule) --------------------------
    def _build(self):
        n_stages, n_micro, axis = self.n_stages, self.n_micro, self.axis
        params = self.params
        carrier_shape, carrier_dtype = self._carrier
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        call_stage = self._stage_caller(carrier_dtype)

        def step_fn(param_arrays, opt_state, ids_mb, labels_mb, lr, key):
            # AD runs INSIDE the shard_map body (like the 1F1B builder):
            # differentiating THROUGH a shard_map trips its transpose rule on
            # jax<=0.4 (_SpecError on scalar residuals with the replication
            # check off). In-body value_and_grad sees ppermute/switch/scan as
            # ordinary traceable ops, and grads psum in-body like 1F1B's.
            def spmd(p_arrays, ids_mb, labels_mb):
                stage_id = lax.axis_index(axis)

                def local_loss(p_arrays):
                    def branch(s):
                        def run(x, ids_t, lbl_t, k):
                            return call_stage(p_arrays, s, x, ids_t, lbl_t, k)
                        return run

                    branches = [branch(s) for s in range(n_stages)]

                    def tick(carry, t):
                        x, loss_acc = carry
                        mb_idx = jnp.clip(t - stage_id, 0, n_micro - 1)
                        ids_t = lax.dynamic_index_in_dim(ids_mb, mb_idx, keepdims=False)
                        lbl_t = lax.dynamic_index_in_dim(labels_mb, mb_idx, keepdims=False)
                        k_t = jax.random.fold_in(jax.random.fold_in(key, t), stage_id)
                        run = jax.checkpoint(
                            lambda x, i, l, k: lax.switch(stage_id, branches, x, i, l, k)
                        )
                        y, l = run(x, ids_t, lbl_t, k_t)
                        valid = (t - stage_id >= 0) & (t - stage_id < n_micro)
                        is_last = stage_id == n_stages - 1
                        loss_acc = loss_acc + jnp.where(valid & is_last, l, 0.0)
                        y = lax.ppermute(y, axis, perm)
                        return (y, loss_acc), None

                    x0 = jnp.zeros(carrier_shape, carrier_dtype)
                    (_, loss_acc), _ = lax.scan(
                        tick, (x0, jnp.float32(0.0)), jnp.arange(n_micro + n_stages - 1)
                    )
                    return loss_acc / n_micro  # per-device partial

                lval, gval = jax.value_and_grad(local_loss)(p_arrays)
                loss = lax.psum(lval, axis)
                grads = tuple(
                    lax.psum(g.astype(jnp.float32), axis).astype(a.dtype)
                    for g, a in zip(gval, p_arrays)
                )
                return loss, grads

            from jax.sharding import PartitionSpec as P

            from ...mesh import shard_map_compat

            _shard_map, _check = shard_map_compat()

            fn = _shard_map(
                spmd,
                mesh=self.mesh,
                in_specs=(
                    tuple(P() for _ in param_arrays), P(), P(),
                ),
                out_specs=(P(), tuple(P() for _ in param_arrays)),
                **_check,
            )
            loss, grads = fn(tuple(param_arrays), ids_mb, labels_mb)
            new_params, new_state = self.optimizer._functional_update(
                param_arrays, list(grads), opt_state, lr, params=params
            )
            return loss, new_params, new_state

        return jax.jit(step_fn, donate_argnums=(0, 1))

    # -- 1F1B schedule -----------------------------------------------------
    def _build_1f1b(self):
        """Memory-bounded 1F1B (reference ``forward_backward_pipeline``
        pipeline_parallel.py:80, ``section_worker.cc:153`` Run1F1B).

        The F-then-B builder above lets reverse-mode AD differentiate the
        GPipe scan — structurally all forwards run before any backward, so
        the residual stack holds O(n_micro) microbatch activations no matter
        the checkpoint policy. Here the schedule is EXPLICIT: one scan over
        ``n_micro + 2(n_stages-1)`` pairs, each pair doing one forward
        sub-tick (activation ppermutes downstream) and one backward sub-tick
        (hand-rolled per-stage ``jax.vjp``; cotangent ppermutes upstream).
        A stage's backward for microbatch j runs ``2(n_stages-1-s)`` pairs
        after its forward — the 1F1B drain discipline — so the explicit
        activation stash is a circular buffer of depth 2·n_stages:
        **live activations are O(n_stages), independent of n_micro**
        (verified by compiled-HLO peak-temp comparison in
        tests/test_pp_1f1b.py). Param grads accumulate in f32 in the scan
        carry; backward recomputes the stage forward from the stashed input
        (same remat policy as the reference's stash-and-recompute mode).
        """
        n_stages, n_micro, axis = self.n_stages, self.n_micro, self.axis
        params = self.params
        carrier_shape, carrier_dtype = self._carrier
        down = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        up = [(i, (i - 1) % n_stages) for i in range(n_stages)]
        S = 2 * n_stages  # stash depth ≥ max in-flight 2(n_stages-1)+1
        call_stage = self._stage_caller(carrier_dtype)

        def step_fn(param_arrays, opt_state, ids_mb, labels_mb, lr, key):
            def spmd(p_arrays, ids_mb, labels_mb):
                stage_id = lax.axis_index(axis)

                def f_branch(s):
                    def run(p_arrs, x, ids_t, lbl_t, k):
                        return call_stage(p_arrs, s, x, ids_t, lbl_t, k)
                    return run

                def b_branch(s):
                    def run(p_arrs, x, ids_t, lbl_t, g_in, k):
                        if s == n_stages - 1:
                            def f(p, xx):
                                _, l = call_stage(p, s, xx, ids_t, lbl_t, k)
                                return l
                            _, vjp = jax.vjp(f, p_arrs, x)
                            dp, dx = vjp(jnp.float32(1.0 / n_micro))
                            return dx.astype(carrier_dtype), dp
                        if s == 0:
                            def f(p):
                                y, _ = call_stage(p, 0, x, ids_t, lbl_t, k)
                                return y
                            _, vjp = jax.vjp(f, p_arrs)
                            (dp,) = vjp(g_in)
                            return jnp.zeros(carrier_shape, carrier_dtype), dp
                        def f(p, xx):
                            y, _ = call_stage(p, s, xx, ids_t, lbl_t, k)
                            return y
                        _, vjp = jax.vjp(f, p_arrs, x)
                        dp, dx = vjp(g_in)
                        return dx.astype(carrier_dtype), dp
                    return run

                f_branches = [f_branch(s) for s in range(n_stages)]
                b_branches = [b_branch(s) for s in range(n_stages)]
                is_last = stage_id == n_stages - 1

                def pair(carry, u):
                    act, g_up, stash, loss_acc, gaccs = carry
                    # ---- forward sub-tick: stage s runs microbatch u - s
                    jf = u - stage_id
                    f_valid = (jf >= 0) & (jf < n_micro)
                    jf_c = jnp.clip(jf, 0, n_micro - 1)
                    ids_f = lax.dynamic_index_in_dim(ids_mb, jf_c, keepdims=False)
                    lbl_f = lax.dynamic_index_in_dim(labels_mb, jf_c, keepdims=False)
                    k_f = jax.random.fold_in(jax.random.fold_in(key, jf_c), stage_id)
                    y, l = lax.switch(stage_id, f_branches, p_arrays, act, ids_f, lbl_f, k_f)
                    loss_acc = loss_acc + jnp.where(f_valid & is_last, l, 0.0)
                    # stash this stage's INPUT for the backward recompute
                    stash = lax.cond(
                        f_valid,
                        lambda st: lax.dynamic_update_index_in_dim(
                            st, act, jf_c % S, axis=0),
                        lambda st: st,
                        stash,
                    )
                    act_next = lax.ppermute(y, axis, down)
                    # ---- backward sub-tick: stage s drains microbatch
                    # u - (2(n_stages-1) - s); keys re-derive from (j, s) so
                    # the recompute reuses the forward's dropout masks
                    jb = u - (2 * (n_stages - 1) - stage_id)
                    b_valid = (jb >= 0) & (jb < n_micro)
                    jb_c = jnp.clip(jb, 0, n_micro - 1)
                    x_b = lax.dynamic_index_in_dim(stash, jb_c % S, keepdims=False)
                    ids_b = lax.dynamic_index_in_dim(ids_mb, jb_c, keepdims=False)
                    lbl_b = lax.dynamic_index_in_dim(labels_mb, jb_c, keepdims=False)
                    k_b = jax.random.fold_in(jax.random.fold_in(key, jb_c), stage_id)
                    dx, dps = lax.switch(
                        stage_id, b_branches, p_arrays, x_b, ids_b, lbl_b, g_up, k_b)
                    # select, don't multiply: a warm-up/drain sub-tick runs
                    # the vjp on the zero-filled dummy carrier, and e.g. a
                    # sqrt/norm/log stage makes that dp NaN/Inf — 0*NaN would
                    # poison the accumulator (the loss/cotangent paths below
                    # already use jnp.where for exactly this reason)
                    gaccs = tuple(
                        ga + jnp.where(b_valid, dp.astype(jnp.float32), 0.0)
                        for ga, dp in zip(gaccs, dps)
                    )
                    g_next = lax.ppermute(
                        jnp.where(b_valid, dx, jnp.zeros_like(dx)), axis, up)
                    return (act_next, g_next, stash, loss_acc, gaccs), None

                act0 = jnp.zeros(carrier_shape, carrier_dtype)
                g0 = jnp.zeros(carrier_shape, carrier_dtype)
                stash0 = jnp.zeros((S,) + tuple(carrier_shape), carrier_dtype)
                gaccs0 = tuple(jnp.zeros(a.shape, jnp.float32) for a in p_arrays)
                total = n_micro + 2 * (n_stages - 1)
                (_, _, _, loss_acc, gaccs), _ = lax.scan(
                    pair, (act0, g0, stash0, jnp.float32(0.0), gaccs0),
                    jnp.arange(total),
                )
                loss = lax.psum(loss_acc, axis) / n_micro
                grads = tuple(
                    lax.psum(g, axis).astype(a.dtype)
                    for g, a in zip(gaccs, p_arrays)
                )
                return loss, grads

            from jax.sharding import PartitionSpec as P

            from ...mesh import shard_map_compat

            _shard_map, _check = shard_map_compat()
            fn = _shard_map(
                spmd,
                mesh=self.mesh,
                in_specs=(tuple(P() for _ in param_arrays), P(), P()),
                out_specs=(P(), tuple(P() for _ in param_arrays)),
                **_check,
            )
            loss, grads = fn(tuple(param_arrays), ids_mb, labels_mb)
            new_params, new_state = self.optimizer._functional_update(
                param_arrays, list(grads), opt_state, lr, params=params
            )
            return loss, new_params, new_state

        return jax.jit(step_fn, donate_argnums=(0, 1))

    def __call__(self, inputs, labels):
        from ....core import random as random_state
        from ....core.engine import no_grad

        ids = inputs._data if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        lbls = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
        b = ids.shape[0]
        if b % self.n_micro:
            raise ValueError(
                f"batch size {b} not divisible by accumulate_steps {self.n_micro}"
            )
        mb = b // self.n_micro
        ids_mb = ids.reshape((self.n_micro, mb) + ids.shape[1:])
        lbls_mb = lbls.reshape((self.n_micro, mb) + lbls.shape[1:])

        # one executable per input shape: the carrier (inter-stage activation
        # shape) is baked into the schedule, so re-probe + rebuild on change
        shape_key = (ids_mb.shape, str(ids_mb.dtype))
        step = self._jits.get(shape_key)
        if step is None:
            self._carrier = self._probe_carrier(ids_mb[0])
            build = self._build_1f1b if self.schedule == "1F1B" else self._build
            step = self._jits[shape_key] = build()

        with no_grad():
            param_arrays = [p._data for p in self.params]
            opt_state = self.optimizer._functional_state(self.params)
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
            key = random_state.next_key()
            loss, new_params, new_state = step(
                param_arrays, opt_state, ids_mb, lbls_mb, lr, key
            )
            for p, a in zip(self.params, new_params):
                p._set_data(a)
            self.optimizer._functional_restore(self.params, new_state)
            self.optimizer._step_count += 1
        return Tensor(loss)


class PipelineParallelModel(Layer):
    """fleet.distributed_model output for pp_degree>1.

    ``train_batch(data, optimizer)`` compiles one SPMD program: microbatch
    split → pipelined forward (ppermute schedule over the 'pp' axis, per-stage
    ``lax.switch`` bodies) → loss on last stage → AD backward through the
    schedule → fused optimizer update (reference train_batch
    pipeline_parallel.py:152 + 1F1B forward_backward_pipeline:80).
    """

    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg
        self._strategy = strategy
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.micro_batches = strategy.pipeline_configs.get("accumulate_steps", 1)
        self._train_fn = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Pipelined train step. pp_degree>1 requires a PipelineLayer (the
        reference imposes the same: pipeline_parallel.py asserts
        isinstance(layers, PipelineLayer)); pp_degree==1 runs a plain fused
        step."""
        from ....jit import CompiledTrainStep
        from .pp_layers import PipelineLayer

        inputs, labels = data

        if scaler is not None:
            raise NotImplementedError(
                "train_batch does not thread GradScaler loss scaling through the "
                "compiled pipeline step; train in bf16 (TPU-native, no scaling "
                "needed) or scale the loss inside the model's loss_fn"
            )
        if self.num_stages > 1 and not isinstance(self._layers, PipelineLayer):
            raise TypeError(
                "pp_degree>1 requires the model to be a PipelineLayer; got "
                f"{type(self._layers).__name__}"
            )
        if self.num_stages > 1:
            acc = max(self.micro_batches, 1)
            mb_cfg = self._strategy.pipeline_configs.get("micro_batch_size")
            b = inputs.shape[0]
            if acc > 1 and mb_cfg and b != acc * mb_cfg:
                raise ValueError(
                    f"batch size {b} != accumulate_steps({acc}) * "
                    f"micro_batch_size({mb_cfg}); fix pipeline_configs"
                )
            if self._train_fn is None:
                self._train_fn = PipelineTrainStep(
                    self._layers, optimizer, self._hcg.mesh, n_micro=acc, axis="pp",
                    schedule=self._strategy.pipeline_configs.get(
                        "schedule_mode", "1F1B"),
                )
            loss = self._train_fn(inputs, labels)
        else:
            loss_fn = getattr(self._layers, "_loss_fn", None)

            def full_loss(model, x, y):
                out = model(x)
                if loss_fn is not None:
                    return loss_fn(out, y)
                return out.mean()

            if self._train_fn is None:
                self._train_fn = CompiledTrainStep(self._layers, full_loss, optimizer)
            loss = self._train_fn(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
