"""Model wrappers per parallelism mode (reference fleet/meta_parallel/
{sharding_parallel,tensor_parallel,...}.py). On TPU these mostly tag intent —
the sharding itself is GSPMD specs applied when the train step is compiled."""
from __future__ import annotations

from ....nn.layer.layers import Layer


class _MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self.add_sublayer("_layers", layers)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)


class ShardingParallel(_MetaParallelBase):
    pass


class TensorParallel(_MetaParallelBase):
    pass


class PipelineParallel(_MetaParallelBase):
    pass
