"""ZeRO sharding stages 1/2/3.

Parity: reference dygraph sharding —
 stage 1: ``fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:28``
          (param partition ``_partition_parameters:86``, per-rank opt step)
 stage 2: ``fleet/meta_parallel/sharding/sharding_stage2.py:43`` (grad
          reduce-to-owner + grad storage buffers)
 stage 3: ``fleet/meta_parallel/sharding/sharding_stage3.py:51`` (param
          sharding with fwd/bwd gather/release, CPU offload)

TPU-native: a ZeRO stage is a *sharding spec*, not program surgery
("Automatic Cross-Replica Sharding of Weight Update" — the GSPMD paper
lineage; see PAPERS.md). Stage 1 shards optimizer-state arrays over the
'sharding' axis; stage 2 also reduce-scatters gradients (XLA does this
automatically when state is sharded and grads feed sharded updates); stage 3
shards the parameters themselves — the partitioner inserts all-gathers before
use and frees shards after (the reference's fwd/bwd gather+release, done by
the compiler's liveness analysis instead of hooks).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import PartitionSpec

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer
from ....optimizer import Optimizer


def _largest_divisible_dim(shape, n):
    for i, s in enumerate(shape):
        if s % n == 0 and s >= n:
            return i
    return None


def shard_spec_for(p, axis_name: str, n: int) -> PartitionSpec:
    """Pick the dim to shard (prefer dim0, reference partitions flat)."""
    dim = _largest_divisible_dim(tuple(p.shape), n)
    if dim is None:
        return PartitionSpec()
    spec = [None] * len(p.shape)
    spec[dim] = axis_name
    return PartitionSpec(*spec)


class ShardingOptimizerStage1(Optimizer):
    """Wraps an optimizer; optimizer STATE is sharded over the sharding axis.

    Under the compiled train step the accumulators carry sharded layouts, so
    each device updates only its shard and XLA all-gathers updated params —
    exactly ZeRO-1 semantics with compiler-scheduled comms.
    """

    def __init__(self, optimizer: Optimizer, hcg=None, group=None):
        self.inner = optimizer
        self._hcg = hcg
        self.group = group or (hcg.get_sharding_parallel_group() if hcg else None)
        self._parameter_list = optimizer._parameter_list
        self._mark_specs()

    def _mark_specs(self):
        n = self.group.nranks if self.group else 1
        axis = self.group.axis_name if self.group else "sharding"
        if n <= 1:
            return
        for p in self._parameter_list or []:
            p.opt_state_pspec = shard_spec_for(p, axis, n)

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def step(self):
        self.inner.step()

    def clear_grad(self, *a, **k):
        self.inner.clear_grad()

    def state_dict(self):
        return self.inner.state_dict()

    def set_state_dict(self, sd):
        return self.inner.set_state_dict(sd)

    # functional (fused-step) API must hit the INNER rule — inherited base
    # methods would otherwise shadow __getattr__ delegation and raise
    def _functional_state(self, params):
        return self.inner._functional_state(params)

    def _functional_update(self, *a, **k):
        return self.inner._functional_update(*a, **k)

    def _functional_restore(self, *a, **k):
        return self.inner._functional_restore(*a, **k)

    def get_lr(self):
        return self.inner.get_lr()

    @property
    def _step_count(self):
        return self.inner._step_count

    @_step_count.setter
    def _step_count(self, v):
        self.inner._step_count = v


DygraphShardingOptimizer = ShardingOptimizerStage1


class ShardingStage2(Layer):
    """ZeRO-2 wrapper: stage-1 state sharding + gradient reduce-scatter
    layout (grads consumed shard-wise). Reference sharding_stage2.py:43."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False, buffer_max_size=2**23, device="tpu"):
        super().__init__()
        self._layers = layer
        self.add_sublayer("_layers", layer)
        self.group = group
        n = group.nranks if group else 1
        axis = group.axis_name if group else "sharding"
        if n > 1:
            for p in layer.parameters():
                p.opt_state_pspec = shard_spec_for(p, axis, n)
                p.grad_pspec = shard_spec_for(p, axis, n)
        if optimizer is not None:
            self._optim = optimizer

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)


class ShardingStage3(Layer):
    """ZeRO-3: parameters themselves sharded (reference sharding_stage3.py:51).
    GSPMD all-gathers a param right before its op and drops the full copy
    after — the compiler's version of _forward_gather/_release."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False, segment_size=2**20, offload=False, device="tpu"):
        super().__init__()
        self._layers = layer
        self.add_sublayer("_layers", layer)
        self.group = group
        self.offload = offload
        n = group.nranks if group else 1
        axis = group.axis_name if group else "sharding"
        if n > 1:
            for p in layer.parameters():
                spec = shard_spec_for(p, axis, n)
                p.pspec = spec
                p.opt_state_pspec = spec
                p.grad_pspec = spec
        if optimizer is not None:
            self._optim = optimizer

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def get_all_parameters(self):
        return list(self._layers.parameters())


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None, offload=False, sync_buffers=False, buffer_max_size=2**23, segment_size=2**20, sync_comm=False):
    """paddle.distributed.sharding.group_sharded_parallel parity."""
    if level in ("os", "os_g"):
        opt = ShardingOptimizerStage1(optimizer, group=group)
        if level == "os_g":
            model = ShardingStage2(model, opt, group=group)
        return model, opt, scaler
    if level == "p_g_os":
        model = ShardingStage3(model, optimizer, group=group, offload=offload)
        return model, optimizer, scaler
    raise ValueError(f"unknown sharding level {level}")
