"""Pipeline layer description & segmentation.

Parity: reference ``fleet/meta_parallel/parallel_layers/pp_layers.py`` —
LayerDesc:?, SharedLayerDesc:49, SegmentLayers:63, PipelineLayer:132. The
descriptor API is kept; on TPU the stages live on mesh axis 'pp' and the
schedule is collective-permute pipelining (see pipeline_parallel.py) instead
of p2p send_v2/recv_v2 ops.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ....nn.layer.layers import Layer


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied weights across stages (reference pp_layers.py:49 — e.g. embedding
    ↔ lm head). On TPU tying is free: both stages reference the same logical
    parameter; GSPMD replicates/reshards as needed."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layers into M stages (reference pp_layers.py:63)."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self._layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method
        if len(layers_desc) < num_parts:
            raise ValueError("layer number should be greater than number of segments")

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(len(self._layers_desc), self.num_parts)
        if self.method.startswith("layer:"):
            # segment on named layer boundaries (reference behavior)
            name = self.method.split(":", 1)[1]
            marks = [
                i for i, d in enumerate(self._layers_desc)
                if (d.layer_cls.__name__ if isinstance(d, LayerDesc) else type(d).__name__) == name
            ]
            if len(marks) >= self.num_parts:
                per = len(marks) // self.num_parts
                bounds = [0] + [marks[per * i] for i in range(1, self.num_parts)] + [len(self._layers_desc)]
                return bounds
        return self.uniform(len(self._layers_desc), self.num_parts)

    @staticmethod
    def uniform(num_items, num_parts):
        base = num_items // num_parts
        extra = num_items % num_parts
        bounds = [0]
        for i in range(num_parts):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        return bounds


class PipelineLayer(Layer):
    """Reference pp_layers.py:132. Builds ALL stages (single-controller: every
    stage's params live in this process, sharded over 'pp' by the engine)."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None, seg_method="uniform", recompute_interval=0, **kwargs):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = num_stages or 1
        self._recompute_interval = recompute_interval

        seg = SegmentLayers(self._layers_desc, self._num_stages, seg_method)
        self.segment_parts = seg.do_segment()

        self._stage_layers: List[List[Layer]] = []
        self.shared_layers = {}
        self.run_function: List = []
        idx = 0
        for stage in range(self._num_stages):
            start, end = self.segment_parts[stage], self.segment_parts[stage + 1]
            built = []
            for i in range(start, end):
                desc = self._layers_desc[i]
                if isinstance(desc, SharedLayerDesc):
                    if desc.layer_name not in self.shared_layers:
                        self.shared_layers[desc.layer_name] = desc.build_layer()
                    layer = self.shared_layers[desc.layer_name]
                    if desc.forward_func is not None:
                        fwd = desc.forward_func
                        layer._pp_forward_func = fwd
                elif isinstance(desc, LayerDesc):
                    layer = desc.build_layer()
                else:
                    layer = desc  # plain Layer or callable
                if isinstance(layer, Layer):
                    self.add_sublayer(f"stage{stage}_{i}", layer)
                built.append(layer)
                self.run_function.append(layer)
            self._stage_layers.append(built)

    @property
    def num_stages(self):
        return self._num_stages

    def get_stage_layers(self, stage_id):
        return self._stage_layers[stage_id]

    def stage_parameters(self, stage_id):
        seen, out = set(), []
        for l in self._stage_layers[stage_id]:
            if isinstance(l, Layer):
                for p in l.parameters():
                    if id(p) not in seen:
                        seen.add(id(p))
                        out.append(p)
        return out

    def forward(self, x):
        """Reference semantics: run all segments sequentially (single-stage
        fallback / debugging); the engine uses the stage structure for SPMD."""
        for layer in self.run_function:
            if isinstance(layer, Layer):
                fwd = getattr(layer, "_pp_forward_func", None)
                x = fwd(layer, x) if fwd is not None else layer(x)
            else:
                x = layer(x)
        return x
