"""Sequence/context parallelism — ring attention & Ulysses.

ABSENT in the reference (SURVEY.md §2.3 verified) — this is the designed-in
leapfrog: long sequences sharded over the 'sp' mesh axis.

 * **Ring attention**: K/V blocks rotate around the ICI ring via
   ``lax.ppermute`` while each device keeps its Q shard; softmax is
   accumulated online (flash-attention style running max/sum), so the full
   T×T score matrix never materializes. Comm overlaps compute tick-by-tick.
 * **Ulysses**: all_to_all swaps the sharded axis sequence↔heads so standard
   attention runs locally with full sequence but 1/sp of the heads.

Both are pure functions usable inside shard_map over axis 'sp' and are
differentiable (AD through ppermute/all_to_all).
"""
from __future__ import annotations

import math
from functools import partial

import jax

import jax.numpy as jnp
from jax import lax

from ....core.compat import axis_size


_Q_CHUNK = 512  # per-chunk score block is (C, T_local): memory ∝ C·T, not T²


def _chunk_size(t: int) -> int:
    """Largest chunk ≤ _Q_CHUNK (halving ladder) that divides t — covers the
    power-of-two T_locals of practice; t itself for small/indivisible
    lengths (single chunk, no map)."""
    c = _Q_CHUNK
    while c >= 64:
        if t >= c and t % c == 0:
            return c
        c //= 2
    return t


def _block_attn(q, k, v, mask_fn=None, scale=None):
    """One Q-block × K/V-block partial attention: returns (out_unnorm, m, l).

    Scores accumulate in f32 on the MXU (operands stay in the input dtype —
    bf16 K/V ride the ring at half the comm volume) and the Q axis is
    processed in chunks, so the peak score block AND mask are (C, T_local),
    never the (T_local, T_local) the round-3 version materialized.
    ``mask_fn(q_start, q_len) -> (q_len, T) bool`` builds masks lazily per
    chunk."""
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    T = q.shape[1]
    C = _chunk_size(T)

    def one_chunk(qc, q_start):
        s = jnp.einsum(
            "...qhd,...khd->...hqk", qc, k, preferred_element_type=jnp.float32
        ) * scale
        if mask_fn is not None:
            s = jnp.where(mask_fn(q_start, qc.shape[1])[None, None], s, -1e30)
        m = jnp.max(s, axis=-1)  # (..., h, c)
        p = jnp.exp(s - m[..., None])
        l = jnp.sum(p, axis=-1)
        o = jnp.einsum(
            "...hqk,...khd->...qhd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        return o, m, l

    if C == T:
        return one_chunk(q, 0)
    n = T // C
    qs = jnp.moveaxis(q.reshape(q.shape[0], n, C, *q.shape[2:]), 1, 0)
    o, m, l = lax.map(
        lambda a: one_chunk(a[0], a[1] * C), (qs, jnp.arange(n))
    )
    # stitch chunks back: o is (n, B, C, H, D) -> (B, T, H, D); m/l are
    # (n, B, H, C) -> (B, H, T)
    o = jnp.moveaxis(o, 0, 1).reshape(q.shape[0], T, *q.shape[2:])
    m = jnp.moveaxis(m, 0, -2).reshape(*m.shape[1:-1], T)
    l = jnp.moveaxis(l, 0, -2).reshape(*l.shape[1:-1], T)
    return o, m, l


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False):
    """q,k,v: (B, T_local, H, D) — local sequence shard. Call inside shard_map
    over ``axis_name``. Returns (B, T_local, H, D).
    """
    sp = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i - 1) % sp) for i in range(sp)]  # kv blocks rotate upstream

    def make_mask_fn(kv_idx):
        if not causal:
            return None

        def mask_fn(q_start, q_len):
            # global positions: q row r -> my_idx*t + q_start + r;
            # kv col c -> kv_idx*t + c. Built lazily PER CHUNK: (q_len, T),
            # never the full (T, T)
            qpos = my_idx * t_local + q_start + jnp.arange(q_len)
            kpos = kv_idx * t_local + jnp.arange(t_local)
            return qpos[:, None] >= kpos[None, :]

        return mask_fn

    def tick(carry, step):
        k_cur, v_cur, o_acc, m_acc, l_acc = carry
        kv_idx = (my_idx + step) % sp

        def attend(carry_in):
            o_acc, m_acc, l_acc = carry_in
            o_b, m_b, l_b = _block_attn(q, k_cur, v_cur, make_mask_fn(kv_idx), scale)
            m_new = jnp.maximum(m_acc, m_b)
            alpha = jnp.exp(m_acc - m_new)
            beta = jnp.exp(m_b - m_new)
            # o accumulators are (..., q, h, d); m/l are (..., h, q)
            o2 = o_acc * jnp.swapaxes(alpha, -1, -2)[..., None] + o_b * jnp.swapaxes(beta, -1, -2)[..., None]
            return o2, m_new, l_acc * alpha + l_b * beta

        if causal:
            # a kv block strictly in the future is FULLY masked for every
            # local q row — skip its T_local² of dead work entirely
            o_acc, m_acc, l_acc = lax.cond(
                kv_idx <= my_idx, attend, lambda c: c, (o_acc, m_acc, l_acc)
            )
        else:
            o_acc, m_acc, l_acc = attend((o_acc, m_acc, l_acc))
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, o_acc, m_acc, l_acc), None

    B, T, H, D = q.shape
    # accumulators derive from q so they carry the same device-varying type
    # under shard_map (fresh constants would fail the scan carry check).
    # Causal fully-masked (future) kv blocks are SKIPPED via lax.cond in
    # tick(); initial accumulators must therefore be valid "no keys seen yet"
    # state (m=-inf, l=0), which they are.
    o0 = q.astype(jnp.float32) * 0.0
    zero_bht = jnp.swapaxes(q[..., 0].astype(jnp.float32), 1, 2) * 0.0  # (B,H,T)
    m0 = zero_bht - 1e30
    l0 = zero_bht
    # K/V rotate in their INPUT dtype: bf16 halves the per-tick ppermute
    # volume vs the round-3 f32 carry (scores still accumulate in f32)
    (k_f, v_f, o, m, l), _ = lax.scan(tick, (k, v, o0, m0, l0), jnp.arange(sp))
    out = o / jnp.maximum(jnp.swapaxes(l, -1, -2)[..., None], 1e-30)
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = False):
    """Ulysses: all_to_all seq-shard → head-shard, local attention, back.
    q,k,v: (B, T_local, H, D) with H divisible by sp."""
    sp = axis_size(axis_name)

    def seq_to_heads(x):
        # (B, T/sp, H, D) -> (B, T, H/sp, D); tiled all_to_all has a clean
        # transpose rule, so AD through it yields the reverse exchange
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        # (B, T, H/sp, D) -> (B, T/sp, H, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    o = _local_attention(qg, kg, vg, causal)
    return heads_to_seq(o).astype(q.dtype)


def _local_attention(qg, kg, vg, causal):
    """Full-sequence local attention for the Ulysses inner step. Routes to
    the Pallas flash kernel (blockwise online softmax — peak memory ∝
    T·block instead of T², which is the entire point of the long-context
    path); falls back to the einsum formulation only when the head dim
    can't tile (D>256 or D%8)."""
    d = qg.shape[-1]
    if d <= 256 and d % 8 == 0:
        try:
            from ....ops.pallas.flash_attention import flash_attention_array
        except ImportError:
            flash_attention_array = None
        if flash_attention_array is not None:
            return flash_attention_array(qg, kg, vg, causal=causal)
    scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", qg, kg) * scale
    if causal:
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vg)


def split_sequence(x, axis_name="sp", seq_axis=1):
    """Slice this rank's sequence shard (inside shard_map)."""
    sp = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    size = x.shape[seq_axis] // sp
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis=seq_axis)


def gather_sequence(x, axis_name="sp", seq_axis=1):
    return lax.all_gather(x, axis_name, axis=seq_axis, tiled=True)


class RingAttention:
    """Layer-style wrapper holding the axis name."""

    def __init__(self, axis_name="sp", causal=True):
        self.axis_name = axis_name
        self.causal = causal

    def __call__(self, q, k, v):
        from ....core.dispatch import as_tensor, eager_call

        qt, kt, vt = as_tensor(q), as_tensor(k), as_tensor(v)
        if isinstance(qt._data, jax.core.Tracer):
            return eager_call(
                "ring_attention",
                lambda a, b, c: ring_attention(a, b, c, self.axis_name, self.causal),
                [qt, kt, vt],
            )
        # single-device fallback: exact attention
        from ....nn.functional.attention import scaled_dot_product_attention

        return scaled_dot_product_attention(qt, kt, vt, is_causal=self.causal)
