"""Tensor-parallel (Megatron) layers.

Parity: reference ``fleet/meta_parallel/parallel_layers/mp_layers.py`` —
VocabParallelEmbedding:30, ColumnParallelLinear:97, RowParallelLinear:170,
ParallelCrossEntropy:249, which issue c_identity/c_concat/mp_allreduce ops.

TPU-native: two composable modes —
 (a) **GSPMD mode** (default): full-size logical weights carry a
     PartitionSpec; inside pjit the partitioner shards the matmul and inserts
     the same collectives the reference codes by hand. Zero comm code.
 (b) **shard_map mode**: when called inside an explicit shard_map over the
     'mp' axis, per-rank shard weights + explicit psum — bit-for-bit the
     Megatron formulation, used by the hybrid engine's manual path.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import PartitionSpec

from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn import initializer as I
from ....nn.layer.layers import Layer
from ....nn.param_attr import ParamAttr
from ... import collective
from ...collective import _c_identity, _c_split, _mp_allreduce, _c_concat, _c_softmax_with_cross_entropy


def _mp_group(mp_group):
    if mp_group is not None:
        return mp_group
    from ..base.fleet_base import get_hybrid_communicate_group

    hcg = get_hybrid_communicate_group()
    return hcg.get_model_parallel_group() if hcg is not None else None


def _mp_degree(group):
    return group.nranks if group is not None else 1


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.group = _mp_group(mp_group)
        self.world_size = _mp_degree(self.group)
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        # GSPMD: full logical weight, sharded on vocab dim over 'mp'
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierUniform(),
        )
        self.weight.pspec = PartitionSpec("mp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return out


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None, gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.group = _mp_group(mp_group)
        self.world_size = _mp_degree(self.group)
        self.gather_output = gather_output
        self._name = name
        self.weight = self.create_parameter(
            [in_features, out_features], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierUniform(),
        )
        self.weight.pspec = PartitionSpec(None, "mp")  # column sharding
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True, default_initializer=I.Constant(0.0))
            self.bias.pspec = PartitionSpec("mp")
        else:
            self.bias = None

    def forward(self, x):
        x = _c_identity(x, self.group)
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = _c_concat(out, self.group)
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True, input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.group = _mp_group(mp_group)
        self.world_size = _mp_degree(self.group)
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierUniform(),
        )
        self.weight.pspec = PartitionSpec("mp", None)  # row sharding
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True, default_initializer=I.Constant(0.0))
            self.bias.pspec = PartitionSpec()
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = _c_split(x, self.group)
        out = F.linear(x, self.weight, None)
        out = _mp_allreduce(out, self.group)
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.group = _mp_group(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return _c_softmax_with_cross_entropy(input, label, self.group, self.ignore_index)
