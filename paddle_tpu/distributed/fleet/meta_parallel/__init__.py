"""Meta-parallel layers & wrappers (reference fleet/meta_parallel/)."""
from .mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy,
)
from .parallel_wrappers import ShardingParallel, TensorParallel, PipelineParallel  # noqa: F401
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer  # noqa: F401
from .pipeline_parallel import PipelineParallelModel  # noqa: F401
from .sharding import ShardingOptimizerStage1, ShardingStage2, ShardingStage3  # noqa: F401
from .moe_layer import MoELayer  # noqa: F401
from .sequence_parallel import (  # noqa: F401
    RingAttention, ring_attention, ulysses_attention, split_sequence, gather_sequence,
)
