"""Mixture-of-Experts layer with expert parallelism.

Parity: reference EP = global_scatter/global_gather all-to-all-v ops
(``operators/collective/global_scatter_op.cc``, py ``distributed/utils.py:57``)
— the reference has the routing prims but no packaged MoE layer; this is the
capability packaged TPU-first: top-k gating, capacity-bucketed dispatch
(static shapes), all_to_all over the 'ep' axis, expert FFN, combine.
"""
from __future__ import annotations

import math

import numpy as np
import jax

import jax.numpy as jnp
from jax import lax

from ....core.compat import axis_size

from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn.layer.common import Linear
from ....nn.layer.layers import Layer


def moe_dispatch_combine(x, gate_logits, expert_fn, n_experts, capacity_factor=1.25, axis_name=None, k=2):
    """Pure function: (tokens, gate logits) → routed expert outputs.

    x: (T, D) local tokens; gate_logits: (T, E). When ``axis_name`` is set
    (inside shard_map over 'ep'), experts are partitioned across the axis and
    tokens cross via all_to_all; otherwise all experts are local.
    """
    T, D = x.shape
    E = n_experts
    probs = jax.nn.softmax(gate_logits, axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, k)  # (T, k)
    capacity = int(math.ceil(k * T * capacity_factor / E))

    # position of each token within its expert bucket
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # (T, k, E)
    pos_in_expert = jnp.cumsum(onehot.reshape(T * k, E), axis=0).reshape(T, k, E) * onehot
    pos = jnp.sum(pos_in_expert, axis=-1) - 1  # (T, k)
    keep = pos < capacity

    # scatter tokens into (E, capacity, D)
    buckets = jnp.zeros((E, capacity, D), x.dtype)
    flat_e = gate_idx.reshape(-1)
    flat_pos = jnp.clip(pos.reshape(-1), 0, capacity - 1)
    flat_keep = keep.reshape(-1)
    flat_x = jnp.repeat(x, k, axis=0)
    buckets = buckets.at[flat_e, flat_pos].add(
        jnp.where(flat_keep[:, None], flat_x, 0.0)
    )

    if axis_name is not None:
        ep = axis_size(axis_name)
        local_e = E // ep
        # (E, C, D) → (ep, local_e, C, D) → all_to_all → experts local
        b = buckets.reshape(ep, local_e, capacity, D)
        b = lax.all_to_all(b, axis_name, split_axis=0, concat_axis=0, tiled=False)
        # now (ep, local_e, C, D): rows from every rank for MY experts
        y = expert_fn(b.reshape(ep * local_e, capacity, D), local=True)
        y = y.reshape(ep, local_e, capacity, D)
        y = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0, tiled=False)
        out_buckets = y.reshape(E, capacity, D)
    else:
        out_buckets = expert_fn(buckets, local=False)

    # combine: gather back with gate weights
    gathered = out_buckets[flat_e, flat_pos]  # (T*k, D)
    weights = (gate_vals.reshape(-1) * flat_keep).astype(x.dtype)
    combined = (gathered * weights[:, None]).reshape(T, k, D).sum(axis=1)
    # aux load-balancing loss (Switch-style)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], E, dtype=probs.dtype), axis=0)
    aux = jnp.sum(me * ce) * E
    return combined, aux


class MoELayer(Layer):
    """Top-k gated expert FFN layer (expert-parallel over 'ep' when meshed)."""

    def __init__(self, d_model, d_hidden, n_experts, top_k=2, capacity_factor=1.25, ep_group=None, activation="gelu"):
        super().__init__()
        self.n_experts = n_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_group = ep_group
        self.gate = Linear(d_model, n_experts, bias_attr=False)
        # stacked expert weights: (E, D, H), (E, H, D) — shardable on dim 0
        self.w_up = self.create_parameter([n_experts, d_model, d_hidden])
        self.w_down = self.create_parameter([n_experts, d_hidden, d_model])
        from jax.sharding import PartitionSpec

        self.w_up.pspec = PartitionSpec("ep", None, None)
        self.w_down.pspec = PartitionSpec("ep", None, None)
        self.act = activation
        self.aux_loss = None

    def forward(self, x):
        from ....core.dispatch import as_tensor, eager_call

        xt = as_tensor(x)
        orig_shape = xt.shape
        axis = self.ep_group.axis_name if self.ep_group is not None else None
        act_name = self.act
        n_experts, top_k, cf = self.n_experts, self.top_k, self.capacity_factor

        def fn(xa, gate_w, w_up, w_down):
            tokens = xa.reshape(-1, xa.shape[-1])
            logits = tokens @ gate_w

            def expert_fn(buckets, local=False):
                wu, wd = w_up, w_down
                if local and axis is not None:
                    ep = axis_size(axis)
                    # my local experts tiled over incoming rank-blocks
                    local_e = n_experts // ep
                    wu = jnp.tile(wu[:local_e], (ep, 1, 1)) if wu.shape[0] != buckets.shape[0] else wu
                    wd = jnp.tile(wd[:local_e], (ep, 1, 1)) if wd.shape[0] != buckets.shape[0] else wd
                h = jnp.einsum("ecd,edh->ech", buckets, wu)
                h = getattr(jax.nn, act_name)(h)
                return jnp.einsum("ech,ehd->ecd", h, wd)

            in_traced = isinstance(xa, jax.core.Tracer) and axis is not None
            out, aux = moe_dispatch_combine(
                tokens, logits, expert_fn, n_experts, cf,
                axis_name=axis if in_traced else None, k=top_k,
            )
            return out.reshape(xa.shape), aux

        out = eager_call("moe", fn, [xt, self.gate.weight, self.w_up, self.w_down])
        self.aux_loss = out[1]
        return out[0]
