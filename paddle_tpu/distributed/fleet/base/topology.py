"""Hybrid communicate topology.

Parity: reference ``fleet/base/topology.py:36`` (CommunicateTopology: N-D
rank space) and ``:117`` (HybridCommunicateGroup: builds NCCL sub-groups per
axis). TPU-native: the N-D topology IS a jax.sharding.Mesh; each axis is a
named mesh dimension, and "groups" are Group handles bound to axis names —
no communicator setup, XLA lowers per-axis collectives onto ICI.

Axis order (outer→inner) follows the reference ["pp","dp","sharding","mp"]
with sp/ep appended (TPU-native extensions), so ring-adjacent mp ranks map to
adjacent devices — the same locality argument as the reference's ordering.
"""
from __future__ import annotations

import itertools
from typing import Dict, List

import numpy as np
import jax


def _devices():
    return jax.devices()


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("pipe", "data", "sharding", "model"), dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = dict(zip(self._parallel_names, self._dims))
        ranges = [range(d) for d in self._dims]
        self._coord2rank = {coord: i for i, coord in enumerate(itertools.product(*ranges))}
        self._rank2coord = {v: k for k, v in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self.coordinate[axis_name]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return sorted(r for coord, r in self._coord2rank.items() if coord[axis] == index)

    def get_comm_list(self, axis_name):
        axis = self._parallel_names.index(axis_name)
        other_ranges = [range(d) for i, d in enumerate(self._dims) if i != axis]
        out = []
        for other in itertools.product(*other_ranges):
            group = []
            for v in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, v)
                group.append(self._coord2rank[tuple(coord)])
            out.append(group)
        return out


class HybridCommunicateGroup:
    """Builds the global mesh + per-axis Groups (reference topology.py:117)."""

    AXIS_MAP = {"pipe": "pp", "data": "dp", "sharding": "sharding", "model": "mp", "sequence": "sp", "expert": "ep"}

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.nranks = topology.world_size()
        self.global_rank = 0

        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]
        axis_names = [self.AXIS_MAP.get(n, n) for n in names]

        from ...mesh import build_mesh, set_global_mesh

        devs = _devices()
        n_needed = int(np.prod(dims))
        if n_needed <= len(devs):
            self._mesh = build_mesh(axis_names, dims, devs)
            set_global_mesh(self._mesh)
        else:
            self._mesh = None  # abstract topology (e.g. planning on CPU)

        self._axis_names = axis_names
        from ...collective import new_group

        self._groups = {a: new_group(axis_name=a) for a in axis_names}

        self._dp_degree = self._degree("dp")
        self._mp_degree = self._degree("mp")
        self._pp_degree = self._degree("pp")
        self._sharding_degree = self._degree("sharding")
        self._sp_degree = self._degree("sp")
        self._ep_degree = self._degree("ep")

    def _degree(self, axis):
        if axis in self._axis_names:
            return self._topo.get_dim(
                [k for k, v in self.AXIS_MAP.items() if v == axis][0]
                if axis in self.AXIS_MAP.values()
                else axis
            )
        return 1

    @property
    def mesh(self):
        return self._mesh

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return 0

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._groups.get("dp")

    def get_data_parallel_group_src_rank(self):
        return 0

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return 0

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._groups.get("mp")

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline
    def get_stage_id(self):
        return 0

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._groups.get("pp")

    def is_first_stage(self):
        return True

    def is_last_stage(self):
        return self._pp_degree <= 1

    # sharding
    def get_sharding_parallel_rank(self):
        return 0

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._groups.get("sharding")

    def get_sharding_parallel_group_src_rank(self):
        return 0

    # sequence parallel (TPU-native extension)
    def get_sequence_parallel_world_size(self):
        return self._sp_degree

    def get_sequence_parallel_group(self):
        return self._groups.get("sp")

    # expert parallel
    def get_expert_parallel_world_size(self):
        return self._ep_degree

    def get_expert_parallel_group(self):
        return self._groups.get("ep")

    def get_check_parallel_group(self):
        return self._groups.get("dp")

    def get_rank_from_stage(self, stage_id, **kwargs):
        return stage_id
