"""Role makers (reference fleet/base/role_maker.py:519 PaddleCloudRoleMaker —
reads PADDLE_* env to determine rank/endpoints)."""
from __future__ import annotations

import os

import jax


class RoleMakerBase:
    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        return jax.process_index()

    def worker_num(self):
        return jax.process_count()


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective

    def worker_index(self):
        return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))

    def worker_num(self):
        return int(os.environ.get("PADDLE_TRAINERS_NUM", jax.process_count()))


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective)
        self._kwargs = kwargs
