"""DistributedStrategy.

Parity: reference ``fleet/base/distributed_strategy.py:109`` backed by
``paddle/fluid/framework/distributed_strategy.proto`` (RecomputeConfig,
ShardingConfig, HybridConfig, AMPConfig...). Plain attribute bag here — the
proto is an implementation detail we don't need.
"""
from __future__ import annotations


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel degrees (proto: HybridConfig distributed_strategy.proto:51)
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sp_degree": 1,  # TPU-native extension: sequence parallel (absent in reference)
            "ep_degree": 1,  # expert parallel axis
        }
        # AMP (proto: AMPConfig)
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "use_dynamic_loss_scaling": True,
            "incr_every_n_steps": 1000,
            "decr_every_n_nan_or_inf": 2,
            "incr_ratio": 2.0,
            "decr_ratio": 0.5,
            "use_pure_fp16": False,
            "use_bf16": True,
        }
        # Recompute (proto: RecomputeConfig)
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        # Sharding / ZeRO (proto: ShardingConfig)
        self.sharding = False
        self.sharding_configs = {
            "sharding_degree": 1,
            "stage": 1,
            "offload": False,
            "segment_broadcast_MB": 32.0,
        }
        # pipeline (proto: PipelineConfig)
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1, "schedule_mode": "1F1B"}
        # misc meta-optimizer toggles (reference fleet/meta_optimizers/*)
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lamb_configs = {}
        self.lars = False
        self.lars_configs = {}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "sparsity": (0.999,)}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 4}
        self.fp16_allreduce = False
        self.a_sync = False
        self.a_sync_configs = {}
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.without_graph_optimization = False

    def to_dict(self):
        return {k: v for k, v in self.__dict__.items()}

    def __repr__(self):
        lines = ["DistributedStrategy("]
        for k, v in sorted(self.__dict__.items()):
            lines.append(f"  {k}={v},")
        return "\n".join(lines) + "\n)"
