"""Fleet entry points.

Parity: reference ``fleet/base/fleet_base.py`` — ``init:170`` builds the
HybridCommunicateGroup from strategy.hybrid_configs;
``distributed_model:896`` dispatches to Sharding/Data/Tensor/Pipeline
wrappers (``:954-992``); ``distributed_optimizer:839`` wraps the optimizer.
"""
from __future__ import annotations

from typing import Optional

import jax

from .distributed_strategy import DistributedStrategy
from .role_maker import PaddleCloudRoleMaker
from .topology import CommunicateTopology, HybridCommunicateGroup

_strategy: Optional[DistributedStrategy] = None
_hcg: Optional[HybridCommunicateGroup] = None
_role_maker = None


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    global _strategy, _hcg, _role_maker
    _strategy = strategy or DistributedStrategy()
    _role_maker = role_maker or PaddleCloudRoleMaker(is_collective=is_collective)

    hc = _strategy.hybrid_configs
    topo = CommunicateTopology(
        hybrid_group_names=["pipe", "data", "sharding", "sequence", "model"],
        dims=[
            hc.get("pp_degree", 1),
            hc.get("dp_degree", 1),
            hc.get("sharding_degree", 1),
            hc.get("sp_degree", 1),
            hc.get("mp_degree", 1),
        ],
    )
    _hcg = HybridCommunicateGroup(topo)
    return None


def _get_strategy() -> DistributedStrategy:
    return _strategy or DistributedStrategy()


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


fleet = None  # populated lazily for reference-style `fleet.fleet` access


def is_first_worker():
    return _role_maker.is_first_worker() if _role_maker else jax.process_index() == 0


def worker_index():
    return _role_maker.worker_index() if _role_maker else jax.process_index()


def worker_num():
    return _role_maker.worker_num() if _role_maker else jax.process_count()


def is_worker():
    return True


def distributed_model(model):
    """Wrap for the active parallelism mix (reference fleet_base.py:954-992)."""
    strategy = _get_strategy()
    hcg = _hcg
    if hcg is None:
        return model
    from ..meta_parallel.parallel_wrappers import (
        PipelineParallel, ShardingParallel, TensorParallel,
    )
    from ...parallel import DataParallel

    if hcg.get_pipe_parallel_world_size() > 1:
        from ..meta_parallel.pipeline_parallel import PipelineParallelModel

        return PipelineParallelModel(model, hcg, strategy)
    if hcg.get_sharding_parallel_world_size() > 1:
        return ShardingParallel(model, hcg, strategy)
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, strategy)
    if hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model, group=hcg.get_data_parallel_group())
    return model


def distributed_optimizer(optimizer, strategy=None):
    """Reference fleet_base.py:839 → HybridParallelOptimizer; the localsgd /
    dgc strategy flags wrap the inner optimizer first (reference composes
    them as meta-optimizers via strategy_compiler.py)."""
    from ..meta_optimizers.hybrid_parallel_optimizer import HybridParallelOptimizer

    strategy = strategy or _get_strategy()
    # DGC wraps FIRST (it replaces the update rule of the raw Momentum/SGD);
    # LocalSGD composes on top by delegating step() — so localsgd+dgc works
    if getattr(strategy, "dgc", False):
        from ..meta_optimizers.dgc_optimizer import DGCMomentumOptimizer

        # the reference restricts DGC to Momentum (dgc_optimizer.py asserts
        # the inner type); silently replacing e.g. AdamW's update rule with
        # momentum SGD would be a correctness surprise
        tname = type(optimizer).__name__
        if tname not in ("Momentum", "SGD", "DGCMomentumOptimizer"):
            raise ValueError(
                f"strategy.dgc requires a Momentum/SGD inner optimizer "
                f"(got {tname}); DGC replaces the update rule with "
                "compressed momentum SGD"
            )
        cfg = getattr(strategy, "dgc_configs", {}) or {}
        if tname != "DGCMomentumOptimizer":
            optimizer = DGCMomentumOptimizer(
                learning_rate=optimizer.get_lr(),
                lr_fn=optimizer.get_lr,  # live: LR schedulers keep working
                momentum=getattr(optimizer, "_momentum", 0.9),
                parameters=optimizer._parameter_list,
                rampup_begin_step=cfg.get("rampup_begin_step", 0),
                rampup_step=cfg.get("rampup_step", 1),
                sparsity=cfg.get("sparsity", (0.999,)),
            )
    if getattr(strategy, "localsgd", False):
        from ..meta_optimizers.localsgd_optimizer import LocalSGDOptimizer

        cfg = getattr(strategy, "localsgd_configs", {}) or {}
        optimizer = LocalSGDOptimizer(optimizer, k_steps=cfg.get("k_steps", 4))
    return HybridParallelOptimizer(optimizer, _hcg, strategy)
