"""paddle.distributed.fleet parity — TPU-native.

Reference: ``python/paddle/distributed/fleet/`` — fleet.init /
distributed_model / distributed_optimizer, DistributedStrategy, hybrid
topology. Here the hybrid topology materializes ONE jax.sharding.Mesh with
named axes and the "meta-optimizers"/"meta-parallel" wrappers become sharding
rules + shard_map programs compiled by XLA.
"""
from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from .base.fleet_base import (  # noqa: F401
    init, is_first_worker, worker_index, worker_num, is_worker,
    distributed_model, distributed_optimizer, get_hybrid_communicate_group,
    _get_strategy,
)
from .base.role_maker import PaddleCloudRoleMaker, UserDefinedRoleMaker  # noqa: F401
from .. import collective as _collective
from . import meta_parallel  # noqa: F401
from .utils import recompute  # noqa: F401
