"""Elastic training — fault detection, heartbeats, scale events, relaunch.

Parity: reference ``python/paddle/distributed/fleet/elastic/manager.py``
(ElasticManager:130 — etcd heartbeats, np scaling, watch loop → relaunch) and
``collective.py`` (worker registration). TPU-native: the KV substrate is our
C++ TCPStore (the coordination-service analogue of the reference's etcd), so
no external dependency; the watch loop drives the launcher's restart policy.
"""
from __future__ import annotations

import json
import threading
import time
from enum import Enum
from typing import Callable, Dict, List, Optional

from ....fault import inject as _inject
from ....fault.preemption import RESUMABLE_EXIT_CODE
from ....fault.retry import retry_call


class ElasticStatus(Enum):
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"       # membership stable
    RESTART = "restart" # membership changed: relaunch with new world
    EXIT = "exit"


class ElasticManager:
    """Heartbeat-based membership tracking over a TCPStore.

    Workers call ``register()`` (spawns a heartbeat thread); the launcher-side
    watcher calls ``watch()`` each interval and reacts to scale events —
    the reference manager.py watch/_match/_update_hosts loop, minus etcd.
    """

    PREFIX = "elastic"

    def __init__(
        self,
        store,
        np_target: int,
        worker_id: Optional[str] = None,
        heartbeat_interval: float = 1.0,
        timeout: float = 5.0,
        min_np: Optional[int] = None,
        max_np: Optional[int] = None,
        store_retries: int = 3,
        retry_base_delay: float = 0.05,
    ):
        self.store = store
        self.np_target = int(np_target)
        self.min_np = int(min_np or np_target)
        self.max_np = int(max_np or np_target)
        self.worker_id = worker_id
        self.heartbeat_interval = float(heartbeat_interval)
        self.timeout = float(timeout)
        self.store_retries = int(store_retries)
        self.retry_base_delay = float(retry_base_delay)
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_world: Optional[List[str]] = None

    def _store_op(self, fn, *args):
        """Every store round-trip goes through the shared retry-with-backoff
        helper (fault/retry.py) behind the ``store.op`` injection point — one
        transient TCPStore error must not mark a worker dead or kill the
        heartbeat thread."""

        def op():
            _inject.check("store.op")
            return fn(*args)

        return retry_call(
            op,
            retries=self.store_retries,
            base_delay=self.retry_base_delay,
            exceptions=(OSError, ConnectionError, TimeoutError, RuntimeError),
        )

    # -- worker side -------------------------------------------------------
    def _hb_key(self, wid):
        return f"{self.PREFIX}/hb/{wid}"

    def register(self):
        """Join the membership and start heartbeating (reference
        collective.py worker register + manager heartbeat thread)."""
        assert self.worker_id is not None, "worker_id required to register"
        self._store_op(self.store.add, f"{self.PREFIX}/registered", 1)
        self._beat()
        self._stop.clear()
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()
        return self

    def _beat(self):
        # the heartbeat is progress-AWARE: it carries the rank's last
        # published (step, phase, span) record, so the watcher can tell a
        # live-but-stuck rank (fresh ts, stale step) from a dead one (stale
        # ts) and the watchdog can name the straggler
        rec = {"ts": time.time()}
        try:
            from ...watchdog import local_progress

            rec.update(local_progress())
            rec["ts"] = time.time()  # heartbeat freshness wins over publish ts
        except Exception:
            pass
        self._store_op(self.store.set, self._hb_key(self.worker_id), json.dumps(rec))

    def _hb_loop(self):
        # each _beat already retries with backoff; only give up (and let the
        # watcher declare us dead) after several beats fail THROUGH their
        # retries — i.e. the store is persistently gone, not hiccuping
        consecutive = 0
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
                consecutive = 0
            except Exception:
                consecutive += 1
                if consecutive >= 3:
                    return

    def deregister(self):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
            self._hb_thread = None
        try:
            self.store.delete_key(self._hb_key(self.worker_id))
        except Exception:
            pass

    # -- watcher side ------------------------------------------------------
    def alive_workers(self, known_ids: List[str]) -> List[str]:
        now = time.time()
        alive = []
        for wid in known_ids:
            try:
                raw = self._store_op(self.store.get, self._hb_key(wid))
            except Exception:
                continue  # persistent store failure: treat as no heartbeat
            if not raw:
                continue
            try:
                ts = json.loads(raw)["ts"]
            except Exception:
                continue
            if now - ts <= self.timeout:
                alive.append(wid)
        return alive

    def progress(self, known_ids: List[str]) -> Dict[str, dict]:
        """Watcher-side view of every worker's last heartbeat record
        (ts + the rank's step/phase/span progress): the launcher includes
        this in its failure report so a dead rank's last known position
        survives the relaunch."""
        out: Dict[str, dict] = {}
        for wid in known_ids:
            try:
                raw = self._store_op(self.store.get, self._hb_key(wid))
            except Exception:
                continue
            if not raw:
                continue
            try:
                out[wid] = json.loads(raw)
            except Exception:
                continue
        return out

    def watch(self, known_ids: List[str]) -> ElasticStatus:
        """One watch tick (reference manager.py:398 watch loop)."""
        alive = self.alive_workers(known_ids)
        if self._last_world is None:
            self._last_world = alive
        if len(alive) == 0:
            return ElasticStatus.EXIT
        if len(alive) < self.min_np:
            # below the floor: fault — wait for relaunch
            self._last_world = alive
            return ElasticStatus.ERROR
        if set(alive) != set(self._last_world):
            self._last_world = alive
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def world(self) -> List[str]:
        return list(self._last_world or [])


class ElasticLauncher:
    """Supervise worker processes with elastic restarts (reference
    fleet/launch.py elastic mode + manager relaunch)."""

    def __init__(self, spawn_fn: Callable[[List[str]], Dict[str, object]],
                 manager: ElasticManager, watch_interval: float = 1.0,
                 max_restarts: int = 3, max_resumes: int = 32):
        self.spawn_fn = spawn_fn
        self.manager = manager
        self.watch_interval = watch_interval
        self.max_restarts = max_restarts
        # preemption-drain exits (RESUMABLE_EXIT_CODE) are normal operations,
        # not failures: they get their own (much larger) budget
        self.max_resumes = max_resumes

    def _respawn(self, procs, worker_ids):
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            p.wait()
        return self.spawn_fn(worker_ids)

    def run(self, worker_ids: List[str]):
        restarts = 0
        resumes = 0
        procs = self.spawn_fn(worker_ids)
        while True:
            time.sleep(self.watch_interval)
            # process exits take precedence over heartbeat staleness
            codes = {w: p.poll() for w, p in procs.items()}
            if all(c == 0 for c in codes.values()):
                return 0
            failed = [
                w for w, c in codes.items()
                if c not in (None, 0, RESUMABLE_EXIT_CODE)
            ]
            if not failed and any(c == RESUMABLE_EXIT_CODE for c in codes.values()):
                # clean preemption drain: the worker checkpointed and asked
                # for a restart — relaunch without consuming the failure
                # budget (resume comes from AutoCheckpoint on the worker side)
                resumes += 1
                if resumes > self.max_resumes:
                    for p in procs.values():
                        if p.poll() is None:
                            p.terminate()
                    raise RuntimeError(
                        f"elastic: exceeded max_resumes={self.max_resumes} "
                        "preemption restarts"
                    )
                procs = self._respawn(procs, worker_ids)
                continue
            status = self.manager.watch(worker_ids)
            if failed or status in (ElasticStatus.RESTART, ElasticStatus.ERROR):
                restarts += 1
                if restarts > self.max_restarts:
                    for p in procs.values():
                        if p.poll() is None:
                            p.terminate()
                    raise RuntimeError(
                        f"elastic: exceeded max_restarts={self.max_restarts}; failed={failed}"
                    )
                procs = self._respawn(procs, worker_ids)
