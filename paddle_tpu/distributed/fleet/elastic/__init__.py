"""Elastic training — fault detection, heartbeats, scale events, relaunch.

Parity: reference ``python/paddle/distributed/fleet/elastic/manager.py``
(ElasticManager:130 — etcd heartbeats, np scaling, watch loop → relaunch) and
``collective.py`` (worker registration). TPU-native: the KV substrate is our
C++ TCPStore (the coordination-service analogue of the reference's etcd), so
no external dependency; the watch loop drives the launcher's restart policy.
"""
from __future__ import annotations

import json
import threading
import time
from enum import Enum
from typing import Callable, Dict, List, Optional


class ElasticStatus(Enum):
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"       # membership stable
    RESTART = "restart" # membership changed: relaunch with new world
    EXIT = "exit"


class ElasticManager:
    """Heartbeat-based membership tracking over a TCPStore.

    Workers call ``register()`` (spawns a heartbeat thread); the launcher-side
    watcher calls ``watch()`` each interval and reacts to scale events —
    the reference manager.py watch/_match/_update_hosts loop, minus etcd.
    """

    PREFIX = "elastic"

    def __init__(
        self,
        store,
        np_target: int,
        worker_id: Optional[str] = None,
        heartbeat_interval: float = 1.0,
        timeout: float = 5.0,
        min_np: Optional[int] = None,
        max_np: Optional[int] = None,
    ):
        self.store = store
        self.np_target = int(np_target)
        self.min_np = int(min_np or np_target)
        self.max_np = int(max_np or np_target)
        self.worker_id = worker_id
        self.heartbeat_interval = float(heartbeat_interval)
        self.timeout = float(timeout)
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_world: Optional[List[str]] = None

    # -- worker side -------------------------------------------------------
    def _hb_key(self, wid):
        return f"{self.PREFIX}/hb/{wid}"

    def register(self):
        """Join the membership and start heartbeating (reference
        collective.py worker register + manager heartbeat thread)."""
        assert self.worker_id is not None, "worker_id required to register"
        self.store.add(f"{self.PREFIX}/registered", 1)
        self._beat()
        self._stop.clear()
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()
        return self

    def _beat(self):
        self.store.set(self._hb_key(self.worker_id), json.dumps({"ts": time.time()}))

    def _hb_loop(self):
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
            except Exception:
                return  # store gone: let the watcher declare us dead

    def deregister(self):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
            self._hb_thread = None
        try:
            self.store.delete_key(self._hb_key(self.worker_id))
        except Exception:
            pass

    # -- watcher side ------------------------------------------------------
    def alive_workers(self, known_ids: List[str]) -> List[str]:
        now = time.time()
        alive = []
        for wid in known_ids:
            raw = self.store.get(self._hb_key(wid))
            if not raw:
                continue
            try:
                ts = json.loads(raw)["ts"]
            except Exception:
                continue
            if now - ts <= self.timeout:
                alive.append(wid)
        return alive

    def watch(self, known_ids: List[str]) -> ElasticStatus:
        """One watch tick (reference manager.py:398 watch loop)."""
        alive = self.alive_workers(known_ids)
        if self._last_world is None:
            self._last_world = alive
        if len(alive) == 0:
            return ElasticStatus.EXIT
        if len(alive) < self.min_np:
            # below the floor: fault — wait for relaunch
            self._last_world = alive
            return ElasticStatus.ERROR
        if set(alive) != set(self._last_world):
            self._last_world = alive
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def world(self) -> List[str]:
        return list(self._last_world or [])


class ElasticLauncher:
    """Supervise worker processes with elastic restarts (reference
    fleet/launch.py elastic mode + manager relaunch)."""

    def __init__(self, spawn_fn: Callable[[List[str]], Dict[str, object]],
                 manager: ElasticManager, watch_interval: float = 1.0,
                 max_restarts: int = 3):
        self.spawn_fn = spawn_fn
        self.manager = manager
        self.watch_interval = watch_interval
        self.max_restarts = max_restarts

    def run(self, worker_ids: List[str]):
        restarts = 0
        procs = self.spawn_fn(worker_ids)
        while True:
            time.sleep(self.watch_interval)
            # process exits take precedence over heartbeat staleness
            codes = {w: p.poll() for w, p in procs.items()}
            if all(c == 0 for c in codes.values()):
                return 0
            failed = [w for w, c in codes.items() if c not in (None, 0)]
            status = self.manager.watch(worker_ids)
            if failed or status in (ElasticStatus.RESTART, ElasticStatus.ERROR):
                restarts += 1
                if restarts > self.max_restarts:
                    for p in procs.values():
                        if p.poll() is None:
                            p.terminate()
                    raise RuntimeError(
                        f"elastic: exceeded max_restarts={self.max_restarts}; failed={failed}"
                    )
                for p in procs.values():
                    if p.poll() is None:
                        p.terminate()
                for p in procs.values():
                    p.wait()
                procs = self.spawn_fn(worker_ids)
