"""LocalSGD (reference fleet/meta_optimizers/localsgd_optimizer.py:1):
every worker takes k local optimizer steps, then parameters are averaged
across the data-parallel group. The reference rewrites the static program to
insert c_allreduce on params every k steps; TPU-native, the sync is a pmean
on the dp mesh axis inside the traced step (or a device_put-mean eagerly),
and the wrapper composes with any inner optimizer.
"""
from __future__ import annotations

import jax
from jax import lax

from ....core.tensor import Tensor

__all__ = ["LocalSGDOptimizer"]


class LocalSGDOptimizer:
    """Wrap an inner optimizer with k-step local training + param averaging.

    Inside a shard_map/pmap-traced step the sync is ``lax.pmean`` over the
    group's mesh axis; eagerly (single replica) it is a no-op — matching the
    reference's behavior where LocalSGD only alters multi-worker runs.
    """

    def __init__(self, inner, k_steps: int = 4, group=None, axis_name=None):
        self.inner = inner
        self.k_steps = max(int(k_steps), 1)
        self.group = group
        self.axis_name = axis_name or (group.axis_name if group is not None else "dp")
        self._local_steps = 0

    # -- delegation --------------------------------------------------------
    def __getattr__(self, name):
        return getattr(self.inner, name)

    @property
    def _parameter_list(self):
        return self.inner._parameter_list

    def step(self):
        self.inner.step()
        self._local_steps += 1
        if self._local_steps % self.k_steps == 0:
            self.sync_params()

    def sync_params(self):
        """Average parameters across the dp axis (the reference's inserted
        c_allreduce(param)/nranks block)."""
        for p in self.inner._parameter_list or []:
            arr = p._data
            if isinstance(arr, jax.core.Tracer):
                p._set_data(lax.pmean(arr, self.axis_name))

    def clear_grad(self, set_to_zero=True):
        self.inner.clear_grad(set_to_zero)

    def state_dict(self):
        st = self.inner.state_dict()
        st["@local_steps"] = self._local_steps
        return st

    def set_state_dict(self, st):
        self._local_steps = st.pop("@local_steps", 0)
        self.inner.set_state_dict(st)
