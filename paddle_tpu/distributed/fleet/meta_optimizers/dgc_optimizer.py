"""DGC — Deep Gradient Compression momentum optimizer (reference
fleet/meta_optimizers/dgc_optimizer.py:1 + operators/optimizers/
dgc_momentum_op; Lin et al. 2018).

Semantics kept from the reference: per-parameter velocity u and
error-feedback accumulator v; each step u = m·u + g, v += u; only the top
(1 − sparsity) fraction of |v| is COMMUNICATED and applied, the rest stays
in v (error feedback) with momentum-factor masking on u; a ramp-up window
trains dense. TPU-native adaptation: the "communicated sparse gradient" is
the masked dense tensor pmean-ed over the dp axis when traced — ICI
all-reduce of a mostly-zero dense tensor replaces the reference's
sparse-index NCCL path (XLA has no sparse collective; the SEMANTIC
compression — what gets applied vs. accumulated — is identical).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ....core.lazy import concrete as _concrete

__all__ = ["DGCMomentumOptimizer"]


class DGCMomentumOptimizer:
    """Momentum SGD with top-k gradient compression + error feedback."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 group=None, axis_name=None, grad_clip=None, name=None,
                 lr_fn=None):
        # lr_fn: live getter (e.g. inner_optimizer.get_lr) so an attached LR
        # scheduler keeps working instead of freezing at the wrap-time value
        self._lr_fn = lr_fn
        self._lr = float(learning_rate() if callable(learning_rate) else learning_rate)
        self._momentum = float(momentum)
        self._parameter_list = list(parameters) if parameters is not None else []
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._sparsity = tuple(sparsity) if isinstance(sparsity, (list, tuple)) else (float(sparsity),)
        self.axis_name = axis_name or (group.axis_name if group is not None else "dp")
        self._step_count = 0
        self._u = {}  # param name -> velocity
        self._v = {}  # param name -> error-feedback accumulator
        # observability: fraction of elements communicated last step
        self.last_comm_fraction = 1.0

    def get_lr(self):
        return float(self._lr_fn()) if self._lr_fn is not None else self._lr

    def set_lr(self, lr):
        self._lr = float(lr)

    def _pmean(self, arr):
        if isinstance(arr, jax.core.Tracer):
            return lax.pmean(arr, self.axis_name)
        return arr

    def step(self):
        lr = self.get_lr()
        if self._step_count >= self._rampup_begin:
            # reference schedule (optimizer.py:1571): each sparsity rung is
            # held for rampup_step/len(sparsity) steps, clamped to the last
            idx = ((self._step_count - self._rampup_begin) * len(self._sparsity)
                   // self._rampup_step)
            sparsity = self._sparsity[min(len(self._sparsity) - 1, idx)]
        else:
            sparsity = None
        total = kept = 0
        for p in self._parameter_list:
            if p.grad is None or p.stop_gradient:
                continue
            g = p.grad._data
            key = p.name
            if self._step_count < self._rampup_begin:
                # dense ramp-up: plain distributed momentum
                g = self._pmean(g)
                u = self._momentum * self._u.get(key, jnp.zeros_like(g)) + g
                self._u[key] = u
                p._set_data(p._data - lr * u)
                continue
            u = self._momentum * self._u.get(key, jnp.zeros_like(g)) + g
            v = self._v.get(key, jnp.zeros_like(g)) + u
            k = max(1, int(round(v.size * (1.0 - sparsity))))
            absv = jnp.abs(v).ravel()
            thr = lax.top_k(absv, k)[0][-1]
            mask = jnp.abs(v) >= thr
            send = jnp.where(mask, v, 0)
            # momentum-factor masking + error feedback (Lin et al. §3.2)
            self._v[key] = jnp.where(mask, 0, v)
            self._u[key] = jnp.where(mask, 0, u)
            send = self._pmean(send)
            p._set_data(p._data - lr * send)
            total += v.size
            kept += int(k)
        if total:
            self.last_comm_fraction = kept / total
        self._step_count += 1

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad()

    def state_dict(self):
        return {
            "step": self._step_count,
            "u": {k: _concrete(a) for k, a in self._u.items()},
            "v": {k: _concrete(a) for k, a in self._v.items()},
        }

    def set_state_dict(self, state):
        # a key that matches no parameter would silently restart that
        # parameter's velocity/error-feedback from zero — fail loudly instead
        names = {p.name for p in self._parameter_list}
        for part in ("u", "v"):
            stale = set(state.get(part, {})) - names
            if stale:
                raise ValueError(
                    f"DGC state_dict {part!r} keys {sorted(stale)} match no "
                    f"parameter of this optimizer (have {sorted(names)}); "
                    "checkpoints from the old integer-keyed format cannot be "
                    "restored"
                )
        self._step_count = int(state.get("step", 0))
        self._u = {k: jnp.asarray(a) for k, a in state.get("u", {}).items()}
        self._v = {k: jnp.asarray(a) for k, a in state.get("v", {}).items()}
