"""HybridParallelOptimizer.

Parity: reference ``fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:170`` — wraps the user optimizer, fixes grad
clipping across groups, syncs where needed. TPU-native: per-group clip-norm
partial sums become psums over mesh axes when running inside the compiled
sharded train step; eagerly it simply delegates.
"""
from __future__ import annotations

from ....optimizer import Optimizer


class HybridParallelOptimizer:
    def __init__(self, optimizer: Optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        # apply sharding-stage1 state specs when sharding_degree > 1
        if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
            from ..meta_parallel.sharding import ShardingOptimizerStage1

            self._inner_opt = ShardingOptimizerStage1(optimizer, hcg=hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
