"""HybridParallelOptimizer + the ZeRO-1 sharded weight update.

Parity: reference ``fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:170`` — wraps the user optimizer, fixes grad
clipping across groups, syncs where needed — plus the sharded weight update
of "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv:2004.13336): instead of every replica redundantly running
the full optimizer step after an all-reduce, gradients are reduce-SCATTERED
so each replica updates only its 1/dp shard of params + optimizer moments and
the updated params are all-gathered back. Optimizer-state memory per replica
drops to ~1/dp and the gradient sync moves half the bytes of a ring
all-reduce.

``ShardedWeightUpdate`` is the TPU-native engine for that: it owns a
``BucketPlan`` (fleet/grad_buckets.py — reverse-backward-order, size-capped,
dtype-homogeneous flat buckets) and applies the per-shard update INSIDE a
``shard_map`` over the dp mesh axis, with optional EQuARX-style int8
compression of the gradient reduce-scatter (collective.py quantized prims,
``FLAGS_quantized_allreduce``) and an error-feedback accumulator. The
distributed engine (distributed/engine.py) builds its pure-DP train step
around it when ``FLAGS_shard_weight_update`` is on.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework import flags as _flags
from ....optimizer import Optimizer
from ...collective import quantized_psum_scatter_mean
from ..grad_buckets import build_bucket_plan


class ShardedWeightUpdate:
    """ZeRO-1 weight-update sharding over one mesh axis.

    The optimizer-state layout is per-bucket FLAT arrays of global shape
    ``(padded,)`` sharded ``P(axis)`` — each replica physically holds
    ``padded/dp`` elements per moment. ``apply`` runs inside a shard_map
    body: bucket grads (reverse-backward order) → reduce-scatter (optionally
    int8-quantized with error feedback) → elementwise rule on the local shard
    → all-gather updated params.

    Only ELEMENTWISE update rules are eligible (``Optimizer._elementwise_rule``
    — LAMB/LARS need full-param norms and fall back to the replicated path).
    """

    def __init__(self, optimizer, params, axis: str, nranks: int):
        self.optimizer = optimizer
        self.params = list(params)
        self.axis = axis
        self.nranks = int(nranks)
        self.quantized = bool(_flags.flag("FLAGS_quantized_allreduce", False))
        self.block = int(_flags.flag("FLAGS_quantized_allreduce_block", 128))
        self.error_feedback = self.quantized and bool(
            _flags.flag("FLAGS_quantized_allreduce_error_feedback", False)
        )

        def plr_of(p):
            if hasattr(p, "optimize_attr"):
                return p.optimize_attr.get("learning_rate", 1.0)
            return 1.0

        self.plan = build_bucket_plan(
            self.params,
            nranks=self.nranks,
            bucket_bytes=_flags.flag("FLAGS_dp_bucket_bytes"),
            block=self.block,
            wd_of=optimizer._wd_on,
            plr_of=plr_of,
        )
        # accumulator keys per bucket (probe the rule's state layout)
        self._keys = []
        for b in self.plan.buckets:
            probe = optimizer._init_accums(jnp.zeros((1,), b.dtype))
            self._keys.append(tuple(sorted(probe)))

    # -- enablement --------------------------------------------------------
    @staticmethod
    def maybe_build(optimizer, params, mesh, dp_axes, grad_accumulate=1):
        """Return a ShardedWeightUpdate when the configuration is a pure-DP
        group eligible for weight-update sharding, else None (the caller
        falls back to the replicated GSPMD update)."""
        if not _flags.flag("FLAGS_shard_weight_update", True):
            return None
        if grad_accumulate and int(grad_accumulate) > 1:
            return None
        if not params:
            return None
        if not getattr(optimizer, "_elementwise_rule", False):
            return None
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        dp_present = [a for a in dp_axes if sizes.get(a, 1) > 1]
        other = [a for a, s in sizes.items() if a not in tuple(dp_axes) and s > 1]
        if len(dp_present) != 1 or other:
            return None  # hybrid mesh: GSPMD owns the sharding

        def live(spec):
            # a spec is only a real sharding if it names a mesh axis of
            # size > 1 (Megatron pspecs are inert on a pure-DP mesh)
            if spec is None:
                return False
            for s in tuple(spec):
                axes = s if isinstance(s, (tuple, list)) else (s,)
                if any(isinstance(a, str) and sizes.get(a, 1) > 1 for a in axes):
                    return True
            return False

        if any(live(getattr(p, "pspec", None)) or
               live(getattr(p, "grad_pspec", None)) for p in params):
            return None  # model/grad sharding present: not pure DP
        return ShardedWeightUpdate(optimizer, params, dp_present[0],
                                   sizes[dp_present[0]])

    # -- state (global arrays, engine-resident) ----------------------------
    def state_specs(self):
        specs = {
            "t": P(),
            "accums": [
                {k: P(self.axis) for k in keys} for keys in self._keys
            ],
            "ef": [P(self.axis, None) for _ in self.plan.buckets]
            if self.error_feedback else [],
        }
        return specs

    def init_state(self, mesh):
        """Pack the optimizer's per-param accumulators (or cold-start zeros)
        into per-bucket flat arrays placed sharded over the dp axis."""
        from ....core import lazy as _lazy

        opt = self.optimizer
        accums = []
        for bi, b in enumerate(self.plan.buckets):
            have = [bool(opt._accumulators.get(id(self.params[i])))
                    for i in b.indices]
            if any(have):
                # warm/restore: pack per-param state (init missing ones)
                per_key = {}
                for k in self._keys[bi]:
                    parts = []
                    for i in b.indices:
                        p = self.params[i]
                        st = opt._state(p)
                        if not st:
                            st.update(opt._init_accums(_lazy.concrete(p._data)))
                        parts.append(_lazy.concrete(st[k]))
                    per_key[k] = self.plan.flatten(b, parts)
                flats = per_key
            else:
                flats = opt._init_accums(jnp.zeros((b.padded,), b.dtype))
            accums.append({
                k: jax.device_put(v, NamedSharding(mesh, P(self.axis)))
                for k, v in flats.items()
            })
        state = {
            "t": jnp.asarray(float(opt._step_count + 1), jnp.float32),
            "accums": accums,
            "ef": [
                jax.device_put(
                    jnp.zeros((self.nranks, b.padded), jnp.float32),
                    NamedSharding(mesh, P(self.axis, None)),
                )
                for b in self.plan.buckets
            ] if self.error_feedback else [],
        }
        return state

    def sync_back(self, state):
        """Unpack the bucket-flat state into the optimizer's per-param
        accumulators (checkpointing / inspection). The flats are global
        arrays; on a multihost mesh call this only where they are fully
        addressable. Slices are materialized into fresh single-device
        buffers: a lazily-sliced view of the dp-sharded flat keeps a device
        sharding spanning the mesh, and downstream consumers (orbax save,
        donation) must see plain owned arrays."""
        opt = self.optimizer
        for bi, b in enumerate(self.plan.buckets):
            for k, flat in state["accums"][bi].items():
                host = np.asarray(flat)
                for pos, i in enumerate(b.indices):
                    p = self.params[i]
                    off, sz = b.offsets[pos], b.sizes[pos]
                    opt._state(p)[k] = jnp.asarray(
                        host[off:off + sz].reshape(b.shapes[pos])
                    )

    # -- the sharded update (inside shard_map) -----------------------------
    def apply(self, p_arrays, grads, state, lr):
        """(full replicated params, local grads, local state shards, lr) →
        (new full params, new state shards). Traced inside shard_map over
        ``self.axis``; collectives are the real reduce-scatter/all-gather."""
        opt = self.optimizer
        axis, n = self.axis, self.nranks
        ridx = lax.axis_index(axis)
        new_params = list(p_arrays)
        new_accums, new_efs = [], []
        t = state["t"]
        for bi, b in enumerate(self.plan.buckets):
            flat = self.plan.flatten(b, [grads[i] for i in b.indices])
            gf = flat.astype(jnp.float32)
            if self.quantized:
                if self.error_feedback:
                    gf = gf + state["ef"][bi].reshape(-1)
                gshard, err = quantized_psum_scatter_mean(gf, axis, n, self.block)
                if self.error_feedback:
                    new_efs.append(err.reshape(1, -1))
            else:
                gshard = lax.psum_scatter(
                    gf, axis, scatter_dimension=0, tiled=True
                ) / n
            pflat = self.plan.flatten(b, [p_arrays[i] for i in b.indices])
            s = self.plan.shard_size(b)
            pshard = lax.dynamic_slice_in_dim(pflat, ridx * s, s)
            g = opt._regularize_arr(pshard, gshard.astype(pshard.dtype))
            wd = b.wd_scale
            if wd is None:  # mixed decay gates: per-element vector
                wd = lax.dynamic_slice_in_dim(self.plan.wd_vector(b), ridx * s, s)
            new_pshard, new_st = opt._rule(
                pshard, g, state["accums"][bi], lr * b.plr, t, wd
            )
            pnew = lax.all_gather(new_pshard.astype(b.dtype), axis, tiled=True)
            for i, arr in zip(b.indices, self.plan.unflatten(b, pnew)):
                new_params[i] = arr.astype(p_arrays[i].dtype)
            new_accums.append(new_st)
        return new_params, {"t": t + 1.0, "accums": new_accums, "ef": new_efs}

    # -- analytic per-step wire accounting ---------------------------------
    def step_counters(self):
        return {
            "dp_sync_bytes": self.plan.sync_bytes("reduce_scatter", self.quantized),
            "dp_gather_bytes": self.plan.gather_bytes(),
            "dp_buckets": len(self.plan),
            "dp_reduce_scatters": len(self.plan),
        }


class HybridParallelOptimizer:
    """Wraps the user optimizer for hybrid-parallel training (reference
    hybrid_parallel_optimizer.py:170). Sharding-stage-1 state specs apply
    when sharding_degree > 1; pure-DP groups get the ZeRO-1 sharded weight
    update automatically when the train step is built by the distributed
    engine (see ShardedWeightUpdate.maybe_build)."""

    def __init__(self, optimizer: Optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        # apply sharding-stage1 state specs when sharding_degree > 1
        if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
            from ..meta_parallel.sharding import ShardingOptimizerStage1

            self._inner_opt = ShardingOptimizerStage1(optimizer, hcg=hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
