"""fleet.utils — recompute (activation checkpointing).

Parity: reference ``fleet/utils/recompute.py:63,194`` (RecomputeFunction
PyLayer: stash RNG, re-run forward in backward). TPU-native:
``jax.checkpoint`` — residuals are dropped and XLA re-materializes the
forward inside the backward pass; RNG is functional so no state juggling.
"""
from __future__ import annotations

import jax

from ....core.dispatch import eager_call, as_tensor
from ....core.tensor import Tensor


def recompute(function, *args, **kwargs):
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    tensor_args = [as_tensor(a) for a in args if isinstance(a, (Tensor,)) or not callable(a)]

    def fn(*arrays):
        ts = [Tensor(a, stop_gradient=True) for a in arrays]
        out = function(*ts, **kwargs)
        return out._data if isinstance(out, Tensor) else tuple(o._data for o in out)

    ck = jax.checkpoint(fn)
    # jax.checkpoint returns an opaque callable whose identity changes every
    # call; key on the WRAPPED function so the lazy flush signature is stable
    # across identical iterations (no per-step recompiles under remat)
    from ....core.lazy import _fn_key

    return eager_call(
        "recompute", ck, tensor_args, fn_key=("recompute", _fn_key(function))
    )


class recompute_sequential:
    def __init__(self, functions, segments=1):
        self.functions = functions
        self.segments = segments

    def __call__(self, x):
        for f in self.functions:
            x = recompute(f, x)
        return x
