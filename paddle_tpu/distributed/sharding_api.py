"""Sharding annotation API (auto-parallel style).

Parity+: reference auto-parallel ``shard_tensor``
(``python/paddle/distributed/auto_parallel/interface.py``) — here it IS the
GSPMD annotation: attach a NamedSharding / apply with_sharding_constraint.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from .mesh import global_mesh


def shard_tensor(x, mesh=None, placement=None, dist_attr=None):
    """Place/annotate a tensor on the mesh. ``placement`` is a PartitionSpec
    or a list of axis names (None = replicated dim)."""
    mesh = mesh or global_mesh()
    if placement is None:
        spec = PartitionSpec()
    elif isinstance(placement, PartitionSpec):
        spec = placement
    else:
        spec = PartitionSpec(*placement)
    sharding = NamedSharding(mesh, spec)
    if isinstance(x, Tensor):
        if isinstance(x._data, jax.core.Tracer):
            x._data = jax.lax.with_sharding_constraint(x._data, sharding)
            return x
        x._data = jax.device_put(x._data, sharding)
        return x
    return jax.device_put(x, sharding)


def shard_op(op, mesh=None, in_specs=None, out_specs=None):
    """Wrap a callable so inputs/outputs carry sharding constraints."""
    mesh = mesh or global_mesh()

    def wrapped(*args, **kwargs):
        if in_specs is not None:
            args = tuple(
                shard_tensor(a, mesh, s) if s is not None else a
                for a, s in zip(args, in_specs)
            )
        out = op(*args, **kwargs)
        if out_specs is not None:
            if isinstance(out, (list, tuple)):
                out = type(out)(
                    shard_tensor(o, mesh, s) if s is not None else o
                    for o, s in zip(out, out_specs)
                )
            else:
                out = shard_tensor(out, mesh, out_specs)
        return out

    return wrapped
