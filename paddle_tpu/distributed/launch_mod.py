"""Multi-host launcher.

Parity: reference ``python -m paddle.distributed.launch``
(``fleet/launch.py``: Cluster/Pod topology, endpoint assignment, proc
supervision). TPU-native: one process per HOST (not per chip); each process
calls jax.distributed.initialize against a coordinator and sees its local
chips; XLA handles cross-host DCN. This module supervises those per-host
processes on the current node.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time


def launch(training_script, training_script_args=None, hosts=None, coordinator_port=8476, nproc_per_node=1, log_dir=None):
    """Launch `nproc_per_node` worker processes on this node."""
    training_script_args = training_script_args or []
    procs = []
    n = int(nproc_per_node)
    coordinator = f"127.0.0.1:{coordinator_port}"
    for rank in range(n):
        env = dict(os.environ)
        env.update(
            {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_LOCAL_RANK": str(rank),
                "PADDLE_TRAINERS_NUM": str(n),
                "PADDLE_TPU_COORDINATOR": coordinator,
                "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{coordinator_port + rank}",
                "PADDLE_TRAINER_ENDPOINTS": ",".join(
                    f"127.0.0.1:{coordinator_port + i}" for i in range(n)
                ),
            }
        )
        p = subprocess.Popen([sys.executable, training_script] + list(training_script_args), env=env)
        procs.append(p)
    codes = [p.wait() for p in procs]
    if any(codes):
        raise RuntimeError(f"workers exited with codes {codes}")
    return codes


def main():
    import argparse

    ap = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs="...")
    args = ap.parse_args()
    launch(args.script, args.script_args, nproc_per_node=args.nproc_per_node, log_dir=args.log_dir)


if __name__ == "__main__":
    main()
