"""Multi-host launcher.

Parity: reference ``python -m paddle.distributed.launch``
(``fleet/launch.py`` + ``launch_utils.py:272`` get_cluster_from_args —
Cluster/Pod/Trainer topology, endpoint assignment, log redirection, proc
supervision; elastic relaunch via ``fleet/elastic``). TPU-native process
model: ONE worker process per HOST (not per chip) — each calls
``jax.distributed.initialize`` against the coordinator and owns its local
chips; XLA routes cross-host collectives over ICI/DCN. This module builds
the cluster topology from ``--ips``/env, supervises this node's workers, and
(elastic mode) restarts on failure with heartbeat-based fault detection.
"""
from __future__ import annotations

import os
import subprocess
import sys
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class Trainer:
    endpoint: str
    rank: int
    local_rank: int


@dataclass
class Pod:
    """One host's workers (reference launch_utils.py Pod)."""

    addr: str
    node_rank: int
    trainers: List[Trainer] = field(default_factory=list)


@dataclass
class Cluster:
    """The whole-job topology (reference launch_utils.py Cluster)."""

    pods: List[Pod] = field(default_factory=list)

    @property
    def world_size(self):
        return sum(len(p.trainers) for p in self.pods)

    def trainer_endpoints(self):
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pod_by_addr(self, addr):
        for p in self.pods:
            if p.addr == addr:
                return p
        return None


def get_cluster(ips: List[str], nproc_per_node: int, base_port: int = 8476) -> Cluster:
    """Build the topology (reference launch_utils.py get_cluster:272)."""
    cluster = Cluster()
    rank = 0
    for node_rank, ip in enumerate(ips):
        pod = Pod(addr=ip, node_rank=node_rank)
        for local in range(nproc_per_node):
            pod.trainers.append(
                Trainer(endpoint=f"{ip}:{base_port + 1 + local}", rank=rank, local_rank=local)
            )
            rank += 1
        cluster.pods.append(pod)
    return cluster


def _current_node_ip(ips: List[str]) -> str:
    explicit = os.environ.get("PADDLE_CURRENT_NODE") or os.environ.get("POD_IP")
    if explicit and explicit in ips:
        return explicit
    nr = os.environ.get("PADDLE_NODE_RANK")
    if nr is not None and int(nr) < len(ips):
        return ips[int(nr)]
    import socket

    names = {"127.0.0.1", "localhost", socket.gethostname()}
    try:
        names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    for ip in ips:
        if ip in names:
            return ip
    if len(ips) == 1:
        return ips[0]
    # multi-node with no identity match: guessing node 0 would duplicate
    # ranks across hosts — demand explicit identification instead
    raise RuntimeError(
        f"cannot identify this host among --ips {ips}; set PADDLE_CURRENT_NODE "
        "or PADDLE_NODE_RANK"
    )


def launch(
    training_script: str,
    training_script_args: Optional[List[str]] = None,
    ips: str = "127.0.0.1",
    nproc_per_node: int = 1,
    coordinator_port: int = 8476,
    log_dir: Optional[str] = None,
    elastic: bool = False,
    max_restarts: int = 3,
    max_resumes: int = 32,
    hosts=None,
):
    """Launch this node's workers per the cluster topology; supervise them.

    Multi-node: run the same command on every host in ``ips`` — each node
    starts only its own pod's processes (reference launch.py behavior).
    """
    training_script_args = training_script_args or []
    if hosts is not None:  # backwards-compatible alias
        ips = hosts if isinstance(hosts, str) else ",".join(hosts)
    ip_list = [s.strip() for s in str(ips).split(",") if s.strip()]
    cluster = get_cluster(ip_list, int(nproc_per_node), coordinator_port)
    me = _current_node_ip(ip_list)
    pod = cluster.pod_by_addr(me)
    if pod is None:
        raise RuntimeError(f"current node {me} not in --ips {ip_list}")
    coordinator = f"{ip_list[0]}:{coordinator_port}"
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    # supervision substrate: workers publish watchdog progress here (and the
    # coordinated-checkpoint FileStore lives beside it), so the launcher's
    # failure report can name each dead rank's last known position
    import tempfile

    supervise_root = (
        os.path.join(log_dir, "supervise") if log_dir
        else tempfile.mkdtemp(prefix="paddle_tpu_supervise_")
    )
    progress_dir = os.path.join(supervise_root, "progress")
    store_dir = os.path.join(supervise_root, "store")
    os.makedirs(progress_dir, exist_ok=True)
    os.makedirs(store_dir, exist_ok=True)

    def _progress_report():
        try:
            from .watchdog import _read_progress_dir

            table = _read_progress_dir(progress_dir)
        except Exception:
            return ""
        if not table:
            return ""
        return " | last progress: " + "; ".join(
            f"rank {r}: step {rec.get('step')} phase {rec.get('phase')!r}"
            for r, rec in sorted(table.items())
        )

    def spawn_all(_ids=None, _elastic_port=None):
        procs = {}
        for t in pod.trainers:
            env = dict(os.environ)
            env.update(
                {
                    "PADDLE_TRAINER_ID": str(t.rank),
                    "PADDLE_LOCAL_RANK": str(t.local_rank),
                    "PADDLE_TRAINERS_NUM": str(cluster.world_size),
                    "PADDLE_TPU_COORDINATOR": coordinator,
                    "PADDLE_CURRENT_ENDPOINT": t.endpoint,
                    "PADDLE_TRAINER_ENDPOINTS": ",".join(cluster.trainer_endpoints()),
                    "PADDLE_NODE_RANK": str(pod.node_rank),
                    "PADDLE_NNODES": str(len(cluster.pods)),
                    "PADDLE_TPU_PROGRESS_DIR": progress_dir,
                    "PADDLE_TPU_STORE_DIR": store_dir,
                }
            )
            if _elastic_port is not None:
                # workers auto-register heartbeats in init_parallel_env
                env["PADDLE_ELASTIC_STORE"] = f"{ip_list[0]}:{_elastic_port}"
                env["PADDLE_ELASTIC_WORKER_ID"] = f"w{t.rank}"
            stdout = (
                open(os.path.join(log_dir, f"worker.{t.rank}.log"), "ab")
                if log_dir else None
            )
            p = subprocess.Popen(
                [sys.executable, training_script] + list(training_script_args),
                env=env, stdout=stdout, stderr=subprocess.STDOUT if stdout else None,
            )
            if stdout is not None:
                stdout.close()  # child holds its own copy of the fd
            procs[f"w{t.rank}"] = p
        return procs

    if elastic:
        from . import TCPStore
        from .fleet.elastic import ElasticLauncher, ElasticManager

        elastic_port = coordinator_port - 1
        store = TCPStore(
            host=ip_list[0], port=elastic_port,
            is_master=(pod.node_rank == 0),
        )
        manager = ElasticManager(store, cluster.world_size, timeout=10.0)
        launcher = ElasticLauncher(
            lambda ids: spawn_all(ids, _elastic_port=elastic_port),
            manager, max_restarts=max_restarts, max_resumes=max_resumes,
        )
        return launcher.run([f"w{t.rank}" for t in pod.trainers])

    # Non-elastic supervision still honors the preemption-drain contract: a
    # worker that exits with RESUMABLE_EXIT_CODE checkpointed cleanly and
    # wants a restart (it resumes from AutoCheckpoint), so respawn instead of
    # failing the job.
    from ..fault.preemption import RESUMABLE_EXIT_CODE

    resumes = 0
    procs = spawn_all()
    while True:
        codes = {w: p.wait() for w, p in procs.items()}
        if any(c not in (0, RESUMABLE_EXIT_CODE) for c in codes.values()):
            raise RuntimeError(
                f"workers exited with codes {codes}{_progress_report()}"
            )
        if all(c == 0 for c in codes.values()):
            return 0
        # preemption drains are normal operations, not failures: same
        # separate (larger) budget as ElasticLauncher.max_resumes
        resumes += 1
        if resumes > max_resumes:
            raise RuntimeError(
                f"workers preempted more than max_resumes={max_resumes} "
                f"times (codes {codes})"
            )
        procs = spawn_all()


def main():
    import argparse

    ap = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    ap.add_argument("--ips", default="127.0.0.1", help="comma-separated host list")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--coordinator_port", type=int, default=8476)
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--elastic", action="store_true")
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs="...")
    args = ap.parse_args()
    launch(
        args.script, args.script_args, ips=args.ips,
        nproc_per_node=args.nproc_per_node, coordinator_port=args.coordinator_port,
        log_dir=args.log_dir, elastic=args.elastic, max_restarts=args.max_restarts,
    )


if __name__ == "__main__":
    main()
