"""Progress-aware heartbeat + collective watchdog.

The oldest distributed failure mode: one dead or wedged rank stalls every
collective silently and forever — the async runtime makes it worse because
errors surface up to a step late and far from their producing rank. This
module converts that into bounded-time, attributed recovery:

* every rank **publishes progress** — ``(step, phase, last span, ts)`` —
  through the TCPStore heartbeat path (elastic mode) and/or a per-rank file
  under ``PADDLE_TPU_PROGRESS_DIR`` (spawn / chaos harness);
* every blocking collective / barrier / host sync runs under a **deadline**
  (``FLAGS_collective_timeout_s``; 0 disables). On expiry the rank dumps a
  flight-recorder post-mortem tagged with the **suspected straggler/dead
  rank** derived from the progress table, then exits with the resumable
  code (75) so the launcher relaunches instead of hanging.

Disabled-path contract (tier-1 tripwire): with ``FLAGS_collective_timeout_s=0``
the watchdog adds **zero host syncs and zero threads** — ``guard`` is a flag
probe, ``publish`` without a configured session is a no-op attribute check.

Serving (round 12): a supervised serving engine publishes ``serve.step``
phase records through ``publish(unit=...)`` — per-unit sub-records in this
rank's progress entry, so the cross-rank table carries serving progress
without clobbering the training step (serving/supervisor.py).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

from ..framework import flags as _flags

__all__ = [
    "configure", "reset", "configured", "enabled", "timeout_s", "publish",
    "remove_unit", "local_progress", "progress_table", "suspect", "guard",
    "guarded_wait", "trip", "set_abort_fn",
]

_flags.register_flag("FLAGS_collective_timeout_s", 0.0)

# RLock, not Lock: FLAGS_thread_checks verifies mutations via the lock's
# ownership (`_is_owned`), which a plain Lock cannot answer — `locked()`
# is true when ANY thread holds it, a false negative for exactly the races
# the runtime mode exists to catch. Never re-entered in this module.
_lock = threading.RLock()
_cfg: Optional[dict] = None          # guarded_by: _lock
_local: Dict[str, object] = {}       # guarded_by: _lock
_last_push = 0.0                     # guarded_by: _lock
_PUSH_INTERVAL_S = 0.2               # rate limit on store/file write-through

# token -> (deadline_monotonic, what)
_guards: Dict[int, Tuple[float, str]] = {}   # guarded_by: _lock
_guard_ids = iter(range(1, 1 << 62)).__next__
_monitor: Optional[threading.Thread] = None  # guarded_by: _lock
_monitor_wake = threading.Event()
_monitor_stop = threading.Event()

_PROGRESS_PREFIX = "wd/progress"


def _snapshot_local_locked() -> dict:
    """Copy of ``_local`` safe to serialize OUTSIDE ``_lock`` (caller must
    hold it): the ``units`` sub-dict is deep-copied, since another thread's
    unit insert during a later ``json.dumps`` on a shallow alias is a
    RuntimeError mid-train-step."""
    rec = dict(_local)
    if "units" in rec:
        rec["units"] = {k: dict(v) for k, v in rec["units"].items()}
    return rec


def _default_abort(code: int) -> None:
    # sys.exit only raises in the calling thread; the wedged thread is
    # blocked in a C call it will never return from. os._exit is the only
    # exit that works from the monitor thread — flush stdio first so the
    # worker's log survives.
    try:
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    os._exit(code)


_abort_fn = _default_abort


def set_abort_fn(fn) -> None:
    """Replace the process-abort action (tests). ``None`` restores os._exit."""
    global _abort_fn
    _abort_fn = fn if fn is not None else _default_abort


# -- session -----------------------------------------------------------------
def configure(
    rank: Optional[int] = None,
    world_size: Optional[int] = None,
    store=None,
    progress_dir: Optional[str] = None,
) -> None:
    """Bind this process to a supervision session. Missing values come from
    the launcher env (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
    PADDLE_TPU_PROGRESS_DIR / PADDLE_TPU_STORE_DIR). Also registers the
    progress table as a flight-recorder context provider, so EVERY crash
    dump carries the cross-rank view."""
    global _cfg, _guards, _local
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if world_size is None:
        world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if progress_dir is None:
        progress_dir = os.environ.get("PADDLE_TPU_PROGRESS_DIR")
    if store is None:
        from .coord import store_from_env

        store = store_from_env()
    if progress_dir:
        os.makedirs(progress_dir, exist_ok=True)
    with _lock:
        # FLAGS_thread_checks: wrap the shared tables so an unguarded
        # mutation anywhere raises at the mutation site (no-op when off,
        # identity when already wrapped)
        from ..analysis import thread_checks

        _guards = thread_checks.guarded(_guards, _lock, "watchdog._guards")
        _local = thread_checks.guarded(_local, _lock, "watchdog._local")
        _cfg = {
            "rank": int(rank),
            "world_size": int(world_size),
            "store": store,
            "progress_dir": progress_dir,
        }
        _local.clear()
        _local.update(rank=int(rank), step=-1, phase="init", span=None, ts=time.time())
    try:
        from ..profiler import flight

        flight.add_context_provider("watchdog", _dump_context)
    except Exception:
        pass


def reset() -> None:
    """Drop the session (tests). Outstanding guards are cleared and the
    monitor thread (if any) is stopped — after reset the process is back to
    the zero-thread disabled state the inert tripwire pins."""
    global _cfg, _monitor, _guards, _local
    with _lock:
        from ..analysis import thread_checks

        # drop any FLAGS_thread_checks proxies installed by configure() so
        # the disabled state is byte-identical to a fresh import (the inert
        # tripwire measures THIS state)
        _guards = thread_checks.unwrap(_guards)
        _local = thread_checks.unwrap(_local)
        _cfg = None
        _local.clear()
        _guards.clear()
    t = _monitor
    if t is not None and t.is_alive():
        _monitor_stop.set()
        _monitor_wake.set()
        t.join(timeout=2.0)
    with _lock:
        _monitor = None
    _monitor_stop.clear()
    _monitor_wake.clear()
    try:
        from ..profiler import flight

        flight.remove_context_provider("watchdog")
    except Exception:
        pass


def configured() -> bool:
    return _cfg is not None


def timeout_s() -> float:
    try:
        return float(_flags.flag("FLAGS_collective_timeout_s", 0.0) or 0.0)
    except (TypeError, ValueError):
        return 0.0


def enabled() -> bool:
    return timeout_s() > 0.0


# -- progress ----------------------------------------------------------------
def publish(step: Optional[int] = None, phase: Optional[str] = None,
            span: Optional[str] = None, force: bool = False,
            unit: Optional[str] = None) -> None:
    """Record this rank's progress. Called at step boundaries (engine /
    training loops) and phase transitions (checkpoint, drain). Near-zero
    when no session is configured; the store/file write-through is
    rate-limited to one per ``_PUSH_INTERVAL_S``. Chaos injection points
    ``rank.kill`` / ``rank.hang`` / ``rank.slow`` fire here.

    ``unit`` scopes the record to a named sub-unit of this rank — e.g. a
    supervised serving engine's scheduler thread publishing ``serve.step``
    phase records — landing under ``units[unit]`` in the rank's record
    instead of clobbering the training step/phase, so the progress table
    (and every flight dump carrying it) shows serving progress next to
    training progress."""
    from ..fault import inject as _inject

    cfg = _cfg
    rank = cfg["rank"] if cfg else None
    if _inject._armed and unit is None:
        # rank-level chaos (rank.kill/hang/slow) fires only on RANK-level
        # publishes: a serving engine's unit publish must not evaluate a
        # training-targeted `rank.hang:at=N` against the serving step
        # counter (the serving path has its own serve.* points)
        _inject.chaos(step=step, rank=rank, phase=phase)
    if cfg is None:
        return
    global _last_push
    now = time.time()       # record timestamp: peers compare it cross-process
    mono = time.monotonic()  # rate-limit clock: immune to wall-clock jumps
    with _lock:
        if unit is not None:
            units = _local.setdefault("units", {})
            rec_u = dict(units.get(unit) or {})
            if step is not None:
                rec_u["step"] = int(step)
            if phase is not None:
                rec_u["phase"] = str(phase)
            if span is not None:
                rec_u["span"] = str(span)
            rec_u["ts"] = now
            units[unit] = rec_u
        else:
            if step is not None:
                _local["step"] = int(step)
            if phase is not None:
                _local["phase"] = str(phase)
            if span is not None:
                _local["span"] = str(span)
            # rank-level freshness moves ONLY on rank-level publishes: a
            # live serving engine must not keep a hung training loop's
            # timestamp fresh (suspect() ranks stalest-ts among step ties);
            # unit records carry their own ts above
            _local["ts"] = now
        rec = _snapshot_local_locked()
        due = force or (mono - _last_push) >= _PUSH_INTERVAL_S
        if due:
            _last_push = mono
    if not due:
        return
    _push_record(rec, cfg)


def _push_record(rec: dict, cfg: dict) -> None:
    """Write one progress record through to the store and/or progress file
    (shared by publish and remove_unit)."""
    payload = json.dumps(rec)
    store = cfg["store"]
    if store is not None:
        try:
            store.set(f"{_PROGRESS_PREFIX}/{cfg['rank']}", payload)
        except Exception:
            pass  # progress is advisory; the heartbeat path has its own retry
    pdir = cfg["progress_dir"]
    if pdir:
        try:
            tmp = os.path.join(pdir, f".rank_{cfg['rank']}.tmp")
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, os.path.join(pdir, f"rank_{cfg['rank']}.json"))
        except Exception:
            pass


def remove_unit(unit: str) -> None:
    """Drop a sub-unit's progress record. A closed or quarantined serving
    engine must not leave a stale ``units`` entry riding every heartbeat
    merge, progress-file write, and flight dump forever — each supervisor
    restart would otherwise accumulate one dead unit per engine
    incarnation. The removal WRITES THROUGH immediately: waiting for the
    next publish would leave the dead unit persisted indefinitely in a
    process where the closed engine was the last publisher."""
    cfg = _cfg
    if cfg is None:
        return
    with _lock:
        units = _local.get("units")
        if not units or units.pop(unit, None) is None:
            return
        rec = _snapshot_local_locked()
    _push_record(rec, cfg)


def local_progress() -> dict:
    """This rank's latest record (merged into the elastic heartbeat value)."""
    with _lock:
        return _snapshot_local_locked()


def _read_progress_dir(pdir: str) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    try:
        names = os.listdir(pdir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("rank_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(pdir, name)) as f:
                rec = json.load(f)
            out[int(name[len("rank_"):-len(".json")])] = rec
        except Exception:
            continue
    return out


def progress_table(cfg: Optional[dict] = None) -> Dict[int, dict]:
    """Every rank's last published record, keyed by rank. Store records win
    over progress-dir files at the same rank (fresher path)."""
    cfg = cfg or _cfg
    if cfg is None:
        return {}
    table: Dict[int, dict] = {}
    if cfg.get("progress_dir"):
        table.update(_read_progress_dir(cfg["progress_dir"]))
    store = cfg.get("store")
    if store is not None:
        for r in range(cfg["world_size"]):
            try:
                raw = store.get(f"{_PROGRESS_PREFIX}/{r}")
            except Exception:
                continue
            if raw:
                try:
                    table[r] = json.loads(raw)
                except Exception:
                    pass
    return table


def suspect(table: Optional[Dict[int, dict]] = None) -> Tuple[Optional[int], str]:
    """(rank, reason) for the most likely straggler/dead rank: a rank with
    NO record at all, else the rank furthest behind in step, ties broken by
    stalest timestamp. Returns (None, reason) when there is nothing to
    compare (single rank, no session)."""
    cfg = _cfg
    if table is None:
        table = progress_table()
    if cfg is not None:
        # never suspect the REPORTING rank (it is alive enough to be asking);
        # with several silent ranks, name them all — an early-startup hang
        # can predate everyone's first publish
        missing = [
            r for r in range(cfg["world_size"])
            if r not in table and r != cfg["rank"]
        ]
        if missing:
            return missing[0], (
                "no progress record published"
                + (f" (also missing: ranks {missing[1:]})" if missing[1:] else "")
            )
    others = {
        r: rec for r, rec in table.items()
        if cfg is None or r != cfg["rank"]
    } or table
    if not others:
        return None, "no progress records"
    sus = min(
        others,
        key=lambda r: (others[r].get("step", -1), others[r].get("ts", 0.0)),
    )
    rec = others[sus]
    return sus, (
        f"behind at step {rec.get('step')} phase {rec.get('phase')!r} "
        f"(last heard {time.time() - rec.get('ts', 0.0):.1f}s ago)"
    )


def _dump_context() -> dict:
    cfg = _cfg
    table = progress_table()
    sus, why = suspect(table)
    return {
        "rank": cfg["rank"] if cfg else None,
        "world_size": cfg["world_size"] if cfg else None,
        "local": local_progress(),
        "progress": {str(k): v for k, v in table.items()},
        "suspect_rank": sus,
        "suspect_reason": why,
    }


# -- deadline guard ----------------------------------------------------------
def trip(what: str, code: Optional[int] = None) -> None:
    """Watchdog verdict: dump the post-mortem naming the suspect, then abort
    with the resumable exit code so the launcher relaunches this rank. The
    terminal action of an expired guard; also callable from interruptible
    waits (store polls) that caught their own DeadlineExceeded."""
    from ..fault.preemption import RESUMABLE_EXIT_CODE

    try:
        from .. import profiler
        from ..profiler import flight

        profiler.counter_inc("watchdog_trips")
        table = progress_table()
        sus, why = suspect(table)
        flight.dump(
            "collective_timeout",
            extra={
                "what": what,
                "timeout_s": timeout_s(),
                "suspect_rank": sus,
                "suspect_reason": why,
            },
        )
    except Exception:
        pass
    _abort_fn(RESUMABLE_EXIT_CODE if code is None else code)


def _monitor_loop() -> None:
    while True:
        _monitor_wake.wait(timeout=0.1)
        _monitor_wake.clear()
        if _monitor_stop.is_set():
            return
        now = time.monotonic()
        expired = None
        with _lock:
            for tok, (deadline, what) in _guards.items():
                if now >= deadline:
                    expired = (tok, what)
                    break
            if expired is not None:
                _guards.pop(expired[0], None)
        if expired is not None:
            trip(expired[1])


def _ensure_monitor() -> None:
    global _monitor
    if _monitor is not None and _monitor.is_alive():
        return
    with _lock:
        if _monitor is not None and _monitor.is_alive():
            return
        t = threading.Thread(target=_monitor_loop, daemon=True, name="paddle-tpu-watchdog")
        t.start()
        _monitor = t


class guard:
    """Deadline scope for an opaque blocking wait (an XLA collective, a
    ``block_until_ready``): arm before blocking, disarm after. When the wait
    never returns the monitor thread trips at the deadline. With the flag at
    0 this is a float compare and nothing else — no thread, no allocation
    beyond the instance."""

    __slots__ = ("what", "_tok")

    def __init__(self, what: str):
        self.what = what
        self._tok = None

    def __enter__(self):
        t = timeout_s()
        if t <= 0.0:
            return self
        # the collective.drop chaos point wedges THIS rank right before it
        # would enter the collective — the canonical "peer never arrives"
        from ..fault import inject as _inject

        if _inject._armed:
            cfg = _cfg
            _inject.chaos_drop(
                rank=cfg["rank"] if cfg else None,
                step=_local.get("step") if cfg else None,
            )
        tok = _guard_ids()
        with _lock:
            _guards[tok] = (time.monotonic() + t, self.what)
        self._tok = tok
        _ensure_monitor()
        return self

    def __exit__(self, *exc):
        if self._tok is not None:
            with _lock:
                _guards.pop(self._tok, None)
            self._tok = None
        return False


def guarded_wait(poll, what: str, timeout: Optional[float] = None,
                 interval_s: float = 0.05) -> None:
    """Interruptible wait with watchdog semantics: poll until truthy; past
    the deadline, dump + resumable abort (same verdict as an expired guard).
    ``timeout=None`` uses FLAGS_collective_timeout_s; both 0 → no deadline."""
    from .coord import DeadlineExceeded, wait_for

    t = timeout_s() if timeout is None else float(timeout)
    try:
        wait_for(poll, what, t, interval_s=interval_s)
    except DeadlineExceeded:
        trip(what)
