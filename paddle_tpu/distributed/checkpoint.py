"""Distributed (sharded, async) checkpointing + crash-safe auto-resume.

Parity: reference distributed save/load (``fleet.utils.fs`` +
``incubate/checkpoint/auto_checkpoint.py:71`` — periodic checkpoint with
automatic resume) and sharded state persistence. TPU-native: orbax — each
host writes only its own shards of a GSPMD-sharded train state (no gather to
host 0), restore re-places shards per the target sharding; the async saver
overlaps serialization with the next training steps.

Crash safety: every checkpoint carries a MANIFEST (``<path>.manifest.json``,
written via tmp + ``os.replace`` ONLY after the orbax write finalized) with
the flat array tree, per-leaf CRC32 checksums and a commit marker. The
manifest is the source of truth for resume: ``AutoCheckpoint.resume`` walks
back to the newest checkpoint whose manifest verifies (including the
``.old`` backup parked aside by an in-place re-save) instead of trusting
``latest.json``, and GC never deletes the last verified-good copy. A save
that dies at ANY point leaves either the previous manifest+dir intact or an
uncommitted dir that resume skips.
"""
from __future__ import annotations

import json
import os
import shutil
import time
import warnings
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.lazy import concrete as _concrete
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..framework import flags as _flags

MANIFEST_SUFFIX = ".manifest.json"
_MANIFEST_FORMAT = 1


class CheckpointError(RuntimeError):
    """A checkpoint failed verification (missing manifest, checksum
    mismatch, or tree mismatch under strict loading)."""


def _prof():
    from .. import profiler

    return profiler


def _has_state_dict(v) -> bool:
    """Model/optimizer-like tree nodes: anything exposing ``state_dict()``
    (nn.Layer, Optimizer, LRScheduler). They participate in the checkpoint
    tree as nested dicts and restore through ``set_state_dict`` — so a train
    loop checkpoints ``{"model": model, "optimizer": opt}`` directly and
    resume brings back Adam moments / step counts, not just params."""
    return (
        not isinstance(v, (Tensor, dict))
        and callable(getattr(v, "state_dict", None))
    )


def _to_arrays(state: Dict[str, Any]):

    out = {}
    for k, v in state.items():
        if isinstance(v, Tensor):
            out[k] = _concrete(v._data)
        elif isinstance(v, dict):
            out[k] = _to_arrays(v)
        elif _has_state_dict(v):
            out[k] = _to_arrays(dict(v.state_dict()))
        elif isinstance(v, (bool, int, float)):
            # scalar metadata (e.g. an optimizer's "@step") — normalize to an
            # array so orbax round-trips it
            out[k] = np.asarray(v)
        else:
            out[k] = _concrete(v)
    return out


def _apply_arrays(state: Dict[str, Any], arrays: Dict[str, Any]):
    for k, v in state.items():
        a = arrays.get(k)
        if a is None:
            continue
        if isinstance(v, Tensor):
            # restore onto the tensor's current sharding (GSPMD layout kept)
            sharding = getattr(v._data, "sharding", None)
            arr = jax.device_put(a, sharding) if sharding is not None else a
            v._set_data(arr.astype(v._data.dtype) if hasattr(arr, "astype") else arr)
        elif isinstance(v, dict) and isinstance(a, dict):
            _apply_arrays(v, a)
        elif _has_state_dict(v) and isinstance(a, dict):
            if callable(getattr(v, "set_state_dict", None)):
                v.set_state_dict(a)
            else:
                _apply_arrays(dict(v.state_dict()), a)


def _flat_keys(tree: Dict[str, Any], prefix: str = ""):
    """Yield (flat_key, leaf) for every non-dict leaf, '/'-joined."""
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            yield from _flat_keys(v, key)
        else:
            yield key, v


def _tree_keys(state: Dict[str, Any]):
    """Flat key sets of a STATE tree for strict comparison: exact keys for
    Tensor/plain leaves, root prefixes for state_dict-bearing objects (their
    inner key set is owned by set_state_dict — e.g. a fresh optimizer has no
    accumulator slots until the first step, yet absorbs them on restore)."""
    exact, objroots = set(), set()

    def walk(tree, prefix):
        for k, v in tree.items():
            key = f"{prefix}/{k}" if prefix else str(k)
            if isinstance(v, dict):
                walk(v, key)
            elif _has_state_dict(v):
                objroots.add(key)
            else:
                exact.add(key)

    walk(state, "")
    return exact, objroots


def _own_leaves(tree):
    """Copy restored leaves into buffers OWNED by jax's allocator. Orbax
    hands back TensorStore-backed ``jax.Array``s (and numpy leaves) that can
    alias restore-pool memory; if such a buffer later becomes a lazy-flush
    donation target, XLA writes the updated value into memory whose real
    owner can reclaim it, and the NEXT flush reads garbage — observed as
    nondeterministic NaN/divergence on the first steps after resume.
    ``jnp.array(copy=True)`` severs the alias at the restore boundary."""
    if isinstance(tree, dict):
        return {k: _own_leaves(v) for k, v in tree.items()}
    if isinstance(tree, np.ndarray):
        return jnp.array(tree)  # copy=True default: never borrows
    if isinstance(tree, jax.Array):
        try:
            if not tree.is_fully_addressable:
                return tree  # multihost shard: copying would gather/crash
            sharding = getattr(tree, "sharding", None)
            copied = jnp.array(tree)
            # re-place: the copy lands on the default device, but sharded
            # restores must keep their layout for non-Tensor consumers too
            return jax.device_put(copied, sharding) if sharding is not None else copied
        except Exception:
            return tree
    return tree


def _leaf_crc(a) -> Optional[int]:
    """CRC32 of a leaf's host bytes; None when the leaf has no stable byte
    view (non-addressable multihost shards, odd python objects) — such
    leaves are recorded but skipped by verification."""
    try:
        n = np.asarray(a)
        return zlib.crc32(n.tobytes()) & 0xFFFFFFFF
    except Exception:
        return None


# -- manifest ----------------------------------------------------------------
def _manifest_path(path: str) -> str:
    return path + MANIFEST_SUFFIX


def _build_manifest(arrays: Dict[str, Any], step: Optional[int] = None) -> dict:
    tree = {}
    for key, leaf in _flat_keys(arrays):
        entry = {"crc32": _leaf_crc(leaf)}
        if hasattr(leaf, "shape"):
            entry["shape"] = list(np.shape(leaf))
            entry["dtype"] = str(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        tree[key] = entry
    man = {"format": _MANIFEST_FORMAT, "ts": time.time(), "committed": True, "tree": tree}
    if step is not None:
        man["step"] = int(step)
    return man


def _write_manifest(man: dict, ckpt_path: str) -> None:
    """Atomic commit marker: the manifest lands via tmp + os.replace only
    after the checkpoint data is durable, so its presence IS the commit."""
    mp = _manifest_path(ckpt_path)
    tmp = mp + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, mp)


def read_manifest(ckpt_path: str) -> Optional[dict]:
    """The checkpoint's manifest, or None (legacy/uncommitted checkpoint)."""
    mp = _manifest_path(ckpt_path)
    try:
        with open(mp) as f:
            man = json.load(f)
    except (OSError, ValueError):
        return None
    return man if isinstance(man, dict) else None


def _verify_against_manifest(arrays: Dict[str, Any], man: dict, path: str) -> None:
    tree = man.get("tree", {})
    restored = dict(_flat_keys(arrays))
    missing = sorted(set(tree) - set(restored))
    if missing:
        raise CheckpointError(
            f"checkpoint {path}: manifest lists keys absent from the restored "
            f"tree: {missing}"
        )
    for key, entry in tree.items():
        want = entry.get("crc32")
        if want is None:
            continue
        got = _leaf_crc(restored[key])
        if got is not None and got != want:
            raise CheckpointError(
                f"checkpoint {path}: checksum mismatch for '{key}' "
                f"(manifest crc32={want}, restored crc32={got})"
            )


def _move_manifest(src_ckpt: str, dst_ckpt: str) -> None:
    mp = _manifest_path(src_ckpt)
    if os.path.exists(mp):
        os.replace(mp, _manifest_path(dst_ckpt))


def _remove_manifest(ckpt_path: str) -> None:
    try:
        os.remove(_manifest_path(ckpt_path))
    except OSError:
        pass


def _ckpt(async_mode=False):
    import orbax.checkpoint as ocp

    if async_mode:
        return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return ocp.StandardCheckpointer()


class _PendingSave:
    """Handle for an async save: ``wait_until_finished`` blocks on the orbax
    background write and THEN commits the manifest — a crash before the wait
    leaves the checkpoint uncommitted and resume walks past it."""

    def __init__(self, ck, manifest: Optional[dict], path: str, old: Optional[str]):
        self._ck = ck
        self._manifest = manifest
        self._path = path
        self._old = old
        self._done = False

    def wait_until_finished(self):
        if self._done:
            return
        from ..profiler import spans as _spans

        with _spans.span("ckpt_commit", path=self._path, async_save=True):
            self._ck.wait_until_finished()
            if self._manifest is not None:
                _write_manifest(self._manifest, self._path)
        _prof().counter_inc("ckpt_saves")
        self._done = True


def save_state_dict(
    state_dict: Dict[str, Any],
    path: str,
    async_save: bool = False,
    step: Optional[int] = None,
    manifest: bool = True,
):
    """Save a (possibly GSPMD-sharded) state dict WITHOUT gathering: every
    process writes its own shards (orbax OCDBT). ``async_save`` returns
    immediately and serializes in the background (reference async save).

    Checksums are computed from the live arrays BEFORE the write starts, and
    the manifest (commit marker) is written only after orbax finalizes — for
    async saves, inside ``wait_until_finished()``."""
    from ..profiler import spans as _spans

    with _spans.span("ckpt_save", step=step, async_save=async_save) as sp:
        with _spans.span("serialize"):
            arrays = _to_arrays(state_dict)
            path = os.path.abspath(path)
            man = _build_manifest(arrays, step=step) if manifest else None
        sp.set(leaves=len(man["tree"]) if man else 0, path=path)
        old = None
        if os.path.exists(path):
            # keep the previous checkpoint until the new one lands
            # (atomicity: orbax writes tmp+rename, so a fresh path is safe;
            # the old copy is parked aside WITH its manifest and dropped only
            # after a successful save — resume treats a committed .old as a
            # valid fallback)
            old = path + ".old"
            shutil.rmtree(old, ignore_errors=True)
            _remove_manifest(old)
            os.rename(path, old)
            _move_manifest(path, old)
        ck = _ckpt(async_mode=async_save)
        try:
            from ..fault import inject as _inject

            _inject.check("ckpt.write", path=path)
            with _spans.span("write", async_save=async_save):
                ck.save(path, arrays)
        except Exception:
            if old and not os.path.exists(path):
                os.rename(old, path)
                _move_manifest(old, path)
            raise
        # the .old backup is kept until the new checkpoint is COMMITTED: the
        # finalize (background atomic rename) may still fail/crash, and the
        # backup is the only good copy until the manifest lands. Async saves
        # keep it until the NEXT save parks it away.
        if async_save:
            return _PendingSave(ck, man, path, old)
        # StandardCheckpointer finalizes (atomic rename) in the background
        # even on the "sync" path — block so the artifact is durable, commit
        with _spans.span("commit"):
            getattr(ck, "wait_until_finished", lambda: None)()
            if man is not None:
                _write_manifest(man, path)
        _prof().counter_inc("ckpt_saves")
        if old:
            shutil.rmtree(old, ignore_errors=True)
            _remove_manifest(old)
        return None


def load_state_dict(
    state_dict: Dict[str, Any],
    path: str,
    strict: bool = True,
    verify: Optional[bool] = None,
):
    """Restore into ``state_dict`` in place, re-placing each array onto the
    destination tensor's current sharding.

    ``strict`` (default): raise CheckpointError listing keys missing from the
    checkpoint and unexpected keys present only in the checkpoint, instead of
    silently skipping them. ``strict=False`` keeps the old skip behavior.

    ``verify``: recompute per-leaf checksums of the restored arrays against
    the manifest. Default (None): verify when a manifest exists and
    ``FLAGS_ckpt_verify_on_load`` is set; legacy manifest-less checkpoints
    load unverified."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ck = ocp.StandardCheckpointer()
    arrays = _own_leaves(ck.restore(path))
    man = read_manifest(path)
    if verify is None:
        verify = man is not None and bool(_flags.flag("FLAGS_ckpt_verify_on_load", True))
    if verify:
        if man is None:
            raise CheckpointError(f"checkpoint {path}: no manifest to verify against")
        if not man.get("committed"):
            raise CheckpointError(f"checkpoint {path}: manifest present but not committed")
        _verify_against_manifest(arrays, man, path)
    if strict:
        exact, objroots = _tree_keys(state_dict)
        have = {k for k, _ in _flat_keys(arrays)}

        def under_obj(key):
            return any(key == r or key.startswith(r + "/") for r in objroots)

        missing = sorted(k for k in exact if k not in have)
        missing += sorted(
            r for r in objroots
            if not any(h == r or h.startswith(r + "/") for h in have)
        )
        unexpected = sorted(h for h in have if h not in exact and not under_obj(h))
        if missing or unexpected:
            raise CheckpointError(
                f"checkpoint {path}: state mismatch — missing keys "
                f"{missing or '[]'}, unexpected keys {unexpected or '[]'} "
                f"(pass strict=False to skip silently)"
            )
    _apply_arrays(state_dict, arrays)
    return state_dict


class AutoCheckpoint:
    """Periodic checkpoint + automatic CRASH-SAFE resume (reference
    auto_checkpoint.py:71 ``train_epoch_range``): call ``maybe_save`` each
    step; on restart, ``resume`` returns the last completed step whose
    checkpoint verifies (or -1). A failed periodic save is retried with
    backoff, then logged and skipped — training outlives transient
    checkpoint I/O errors, and resume falls back to the previous good copy."""

    def __init__(
        self,
        save_dir: str,
        interval_steps: int = 100,
        keep_last: int = 2,
        async_save: bool = False,
        save_retries: int = 2,
    ):
        self.save_dir = os.path.abspath(save_dir)
        self.interval = int(interval_steps)
        self.keep_last = keep_last
        self.async_save = async_save
        self.save_retries = int(save_retries)
        self._pending = None
        # rollback-anchor pins (fault/sentinel.py): steps GC must never drop,
        # whatever keep_last says — the active rollback anchor may be older
        # than the retention window
        self._protected: set = set()
        os.makedirs(self.save_dir, exist_ok=True)

    # -- rollback anchor protocol ------------------------------------------
    def protect(self, step: int) -> None:
        """Pin ``step``: GC keeps it (and its ``.old`` backup) until
        :meth:`release`. The stability sentinel pins its active rollback
        anchor so keep_last can never collect the one checkpoint a rollback
        needs."""
        self._protected.add(int(step))

    def release(self, step: int) -> None:
        self._protected.discard(int(step))

    def protected(self) -> set:
        return set(self._protected)

    def invalidate(self, step: int) -> None:
        """Drop ``step``'s checkpoint (primary + backup + manifests) — the
        sentinel invalidates anchors saved inside a poisoned window after a
        rollback (a quarantined step is never replayed, so the bad copy
        would otherwise shadow future rollbacks). Pinned steps refuse."""
        step = int(step)
        if step in self._protected:
            raise ValueError(f"step {step} is a protected rollback anchor")
        for path in (self._step_path(step), self._step_path(step) + ".old"):
            shutil.rmtree(path, ignore_errors=True)
            _remove_manifest(path)

    def _meta_path(self):
        return os.path.join(self.save_dir, "latest.json")

    def _step_path(self, step):
        return os.path.join(self.save_dir, f"step_{step}")

    @staticmethod
    def _parse_step_dir(d: str) -> Optional[Tuple[int, bool]]:
        """``step_7`` -> (7, True); ``step_7.old`` -> (7, False); orbax tmp
        litter and anything else -> None."""
        if not d.startswith("step_"):
            return None
        rest = d[len("step_"):]
        if rest.isdigit():
            return int(rest), True
        if rest.endswith(".old") and rest[: -len(".old")].isdigit():
            return int(rest[: -len(".old")]), False
        return None

    def _candidates(self) -> List[Tuple[int, bool, str]]:
        """(step, is_primary, path) for every step dir incl. .old backups,
        newest first, primary before backup at the same step."""
        out = []
        for d in os.listdir(self.save_dir):
            parsed = self._parse_step_dir(d)
            if parsed is not None and os.path.isdir(os.path.join(self.save_dir, d)):
                step, primary = parsed
                out.append((step, primary, os.path.join(self.save_dir, d)))
        out.sort(key=lambda t: (t[0], t[1]), reverse=True)
        return out

    def _is_committed(self, path: str) -> bool:
        man = read_manifest(path)
        return bool(man and man.get("committed"))

    def _step_committed(self, step: int) -> bool:
        """Either the primary dir or its parked .old backup is committed —
        resume can use both, so GC must protect both."""
        return (
            self._is_committed(self._step_path(step))
            or self._is_committed(self._step_path(step) + ".old")
        )

    def maybe_save(self, step: int, state_dict: Dict[str, Any]) -> bool:
        if step == 0 or step % self.interval:
            # step 0 is the untrained state — saving it would also age out a
            # useful checkpoint one interval earlier under keep_last
            return False
        return self.save_now(step, state_dict)

    def save_now(self, step: int, state_dict: Dict[str, Any], sync: bool = False) -> bool:
        """Save unconditionally (``sync=True`` forces a synchronous save even
        in async mode — the preemption-drain path). Retries transient I/O
        failures with backoff; a save that still fails is logged and skipped
        (resume falls back to the previous verified checkpoint)."""
        from ..fault.retry import retry_call

        from ..profiler import flight as _flight

        try:
            # a failed async background write from the PREVIOUS save surfaces
            # here — log it like any other lost save instead of killing the
            # training loop (resume falls back to the last committed copy)
            self.wait()
        except Exception as e:
            _prof().counter_inc("ckpt_save_failures")
            _flight.dump(
                "ckpt_save_failure",
                extra={"step": step, "phase": "async_commit", "error": repr(e)},
            )
            warnings.warn(f"previous async checkpoint save failed (skipped): {e!r}")
        try:
            pend = retry_call(
                save_state_dict,
                state_dict,
                self._step_path(step),
                async_save=self.async_save and not sync,
                step=step,
                retries=self.save_retries,
                base_delay=0.05,
            )
        except Exception as e:
            _prof().counter_inc("ckpt_save_failures")
            _flight.dump(
                "ckpt_save_failure",
                extra={"step": step, "phase": "write", "error": repr(e)},
            )
            warnings.warn(f"checkpoint save at step {step} failed (skipped): {e!r}")
            return False
        self._pending = pend
        # legacy pointer only — resume verifies manifests instead; still
        # written atomically so a kill here can't leave torn JSON for any
        # legacy reader of latest.json
        from ..framework.io import atomic_open

        with atomic_open(self._meta_path(), "w") as f:
            json.dump({"step": step, "ts": time.time()}, f)
        self._gc()
        return True

    def _gc(self):
        """Drop old checkpoints, but NEVER the newest verified-good copy —
        if the last ``keep_last`` saves all turn out corrupt, the verified
        one is the only resumable state left."""
        steps = sorted({s for s, _primary, _ in self._candidates()})
        # keep_last=0 historically meant "keep everything" (old GC sliced
        # steps[:-0] == [])
        keep = set(steps[-self.keep_last:]) if self.keep_last else set(steps)
        committed = [s for s in steps if self._step_committed(s)]
        if committed:
            keep.add(committed[-1])
        keep |= self._protected  # pinned rollback anchors survive any window
        for s in steps:
            if s in keep:
                continue
            for path in (self._step_path(s), self._step_path(s) + ".old"):
                shutil.rmtree(path, ignore_errors=True)
                _remove_manifest(path)

    def resume(self, state_dict: Dict[str, Any], max_step: Optional[int] = None) -> int:
        """Load the newest VERIFIED checkpoint into state_dict; returns its
        step or -1. Walks candidates newest-first — primary dirs then their
        ``.old`` backups — skipping uncommitted (mid-write crash), corrupt
        (checksum mismatch) and unreadable checkpoints. Does NOT trust
        latest.json: the pointer can be ahead of the async finalize.

        ``max_step`` bounds the walk (rollback anchor protocol): checkpoints
        saved at later steps are skipped outright — a stability rollback
        must land STRICTLY BEFORE the poisoned step, and an anchor saved
        inside the detection window may already carry the bad update."""
        if not os.path.isdir(self.save_dir):
            return -1
        fell_back = 0
        for step, _primary, path in self._candidates():
            if max_step is not None and step > max_step:
                continue
            man = read_manifest(path)
            if man is not None and not man.get("committed"):
                fell_back += 1
                continue
            try:
                # legacy checkpoints (no manifest) load unverified; manifest
                # checkpoints verify checksums end-to-end. strict=False: a
                # tree mismatch here means the USER's model changed — every
                # older checkpoint shares the tree, so walking back would
                # only silently discard all progress instead of restoring
                # what still matches (the pre-manifest behavior).
                load_state_dict(state_dict, path, strict=False, verify=man is not None)
            except Exception:
                fell_back += 1
                continue
            if fell_back:
                _prof().counter_inc("ckpt_resume_fallbacks", fell_back)
            return step
        if fell_back:
            _prof().counter_inc("ckpt_resume_fallbacks", fell_back)
        return -1

    def wait(self):
        if self._pending is not None:
            try:
                self._pending.wait_until_finished()
            finally:
                # even on failure, drop the handle: re-raising the same error
                # from every later save would wedge the loop permanently
                self._pending = None


class CoordinatedCheckpoint:
    """Multi-rank checkpointing with a store-mediated TWO-PHASE commit, so a
    resume can never mix steps across ranks (ZeRO-1's engine-resident sharded
    optimizer state makes a torn multi-rank checkpoint unreconstructable, not
    merely stale).

    Layout: ``<dir>/step_K/rank_R`` — each rank's shard saved through
    :func:`save_state_dict` (per-rank manifest = that rank's durability
    marker). Phase 1: every rank serializes + CRCs + writes, then acks on the
    shared store. Phase 2: rank 0 waits for ``world_size`` acks, writes the
    durable step commit marker (``<dir>/step_K/COMMITTED.json``, tmp +
    ``os.replace``) and publishes the store commit record that releases the
    waiting ranks. A crash at ANY point before the marker lands leaves the
    step uncommitted on EVERY rank; resume walks past it.

    Resume: newest-first over step dirs; a dir is eligible only when the
    commit marker is present and every rank's manifest is committed. A dir
    whose rank manifests disagree on the step they were written at is
    corrupt-by-construction and rejected loudly (cross-rank manifest
    agreement check), naming the disagreeing steps. When a store is bound,
    ranks additionally publish the step they resolved and verify the whole
    world agreed before loading.
    """

    COMMIT_MARKER = "COMMITTED.json"

    def __init__(
        self,
        save_dir: str,
        world_size: Optional[int] = None,
        rank: Optional[int] = None,
        store=None,
        interval_steps: int = 100,
        keep_last: int = 2,
        commit_timeout_s: Optional[float] = None,
        save_retries: int = 2,
    ):
        from .coord import CommitBarrier, store_from_env

        self.save_dir = os.path.abspath(save_dir)
        self.world_size = int(
            world_size if world_size is not None
            else os.environ.get("PADDLE_TRAINERS_NUM", "1")
        )
        self.rank = int(
            rank if rank is not None else os.environ.get("PADDLE_TRAINER_ID", "0")
        )
        self.store = store if store is not None else store_from_env()
        self.interval = int(interval_steps)
        self.keep_last = keep_last
        self.save_retries = int(save_retries)
        self._commit_timeout_s = commit_timeout_s
        self.barrier = (
            CommitBarrier(self.store, self.world_size, self.rank, prefix="ckpt")
            if self.store is not None else None
        )
        self._protected: set = set()  # rollback-anchor pins (rank-local)
        os.makedirs(self.save_dir, exist_ok=True)

    # -- rollback anchor protocol (same contract as AutoCheckpoint) --------
    def protect(self, step: int) -> None:
        self._protected.add(int(step))

    def release(self, step: int) -> None:
        self._protected.discard(int(step))

    def invalidate(self, step: int) -> None:
        """Drop ``step``'s whole step dir (rank 0 only; other ranks no-op so
        a world-wide sentinel rollback deletes each dir exactly once)."""
        step = int(step)
        if step in self._protected:
            raise ValueError(f"step {step} is a protected rollback anchor")
        if self.rank == 0:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)

    # -- paths -------------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.save_dir, f"step_{int(step)}")

    def _rank_path(self, step: int, rank: Optional[int] = None) -> str:
        return os.path.join(
            self._step_dir(step), f"rank_{self.rank if rank is None else rank}"
        )

    def _marker_path(self, step: int) -> str:
        return os.path.join(self._step_dir(step), self.COMMIT_MARKER)

    def commit_timeout_s(self) -> float:
        """Deadline for the commit barrier: explicit > watchdog flag > 60s.
        A dead peer must fail the SAVE (uncommitted, training's caller
        decides), never hang it."""
        if self._commit_timeout_s is not None:
            return float(self._commit_timeout_s)
        from . import watchdog

        t = watchdog.timeout_s()
        return t if t > 0 else 60.0

    # -- save --------------------------------------------------------------
    def maybe_save(self, step: int, state_dict: Dict[str, Any]) -> bool:
        if step == 0 or step % self.interval:
            return False
        return self.save_now(step, state_dict)

    def save_now(self, step: int, state_dict: Dict[str, Any], sync: bool = True) -> bool:
        """Run this rank's side of the coordinated save. Returns True when
        the step COMMITTED (every rank acked and the marker landed); False
        when the save failed or the barrier timed out — the step stays
        invisible to resume, and the previous committed step remains the
        recovery point. ``sync`` is accepted for AutoCheckpoint drop-in
        compatibility (PreemptionGuard.drain): coordinated saves are always
        synchronous — the commit barrier IS the durability point."""
        from ..fault import inject as _inject
        from ..fault.retry import retry_call
        from ..profiler import flight as _flight
        from . import watchdog
        from .coord import DeadlineExceeded

        watchdog.publish(step=step, phase="ckpt_save")
        step = int(step)
        try:
            if self.barrier is not None and self.rank == 0:
                # a crashed earlier attempt at THIS step (relaunch replayed
                # to it) may have left acks/commit litter on the store;
                # counting those would let the marker land before every rank
                # of this attempt wrote durably — a torn-but-committed step
                self.barrier.reset(step)
            _inject.check("ckpt.serialize", step=step, rank=self.rank)
            os.makedirs(self._step_dir(step), exist_ok=True)
            retry_call(
                save_state_dict,
                state_dict,
                self._rank_path(step),
                async_save=False,
                step=step,
                retries=self.save_retries,
                base_delay=0.05,
            )
            _inject.check("ckpt.ack", step=step, rank=self.rank)
            if self.barrier is not None:
                self.barrier.ack(step)
                _inject.check("ckpt.commit", step=step, rank=self.rank)
                if self.rank == 0:
                    from .coord import wait_for

                    wait_for(
                        lambda: self.barrier.acks(step) >= self.world_size,
                        f"coordinated ckpt acks (step {step})",
                        self.commit_timeout_s(),
                    )
                    self._write_marker(step)
                    self.barrier.commit(step, timeout_s=0.0)  # acks already in
                else:
                    from .coord import wait_for

                    wait_for(
                        lambda: self.barrier.committed(step)
                        or os.path.exists(self._marker_path(step)),
                        f"coordinated ckpt commit marker (step {step})",
                        self.commit_timeout_s(),
                    )
            else:
                # single-rank session (world 1, no store): the marker is the
                # whole protocol
                _inject.check("ckpt.commit", step=step, rank=self.rank)
                self._write_marker(step)
        except DeadlineExceeded as e:
            _prof().counter_inc("ckpt_save_failures")
            _flight.dump(
                "coordinated_ckpt_timeout",
                extra={"step": step, "rank": self.rank, "error": str(e)},
            )
            warnings.warn(
                f"coordinated checkpoint at step {step} timed out "
                f"(uncommitted, skipped): {e}"
            )
            return False
        except Exception as e:
            _prof().counter_inc("ckpt_save_failures")
            _flight.dump(
                "ckpt_save_failure",
                extra={"step": step, "rank": self.rank, "phase": "coordinated",
                       "error": repr(e)},
            )
            warnings.warn(
                f"coordinated checkpoint at step {step} failed on rank "
                f"{self.rank} (uncommitted, skipped): {e!r}"
            )
            return False
        _prof().counter_inc("ckpt_coordinated_commits")
        if self.rank == 0:
            # resume-agreement votes describe the PREVIOUS world state; left
            # behind, a later resume could read a peer's stale vote and
            # spuriously reject. A committed step supersedes them.
            if self.store is not None:
                for r in range(self.world_size):
                    try:
                        self.store.delete_key(f"ckpt/resume/{r}")
                    except Exception:
                        pass
            self._gc()
        return True

    def _write_marker(self, step: int) -> None:
        """The step's durable commit record — written by rank 0 only after
        every rank acked a durable, CRC'd shard."""
        rec = {
            "step": int(step), "ts": time.time(),
            "world_size": self.world_size, "committed": True,
        }
        tmp = self._marker_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._marker_path(step))

    # -- resume ------------------------------------------------------------
    def _steps_on_disk(self) -> List[int]:
        try:
            names = os.listdir(self.save_dir)
        except OSError:
            return []
        out = []
        for d in names:
            if d.startswith("step_") and d[len("step_"):].isdigit() \
                    and os.path.isdir(os.path.join(self.save_dir, d)):
                out.append(int(d[len("step_"):]))
        return sorted(out, reverse=True)

    def _rank_manifests(self, step: int) -> Dict[int, Optional[dict]]:
        return {
            r: read_manifest(self._rank_path(step, r))
            for r in range(self.world_size)
        }

    def check_manifest_agreement(self, step: int) -> None:
        """Cross-rank manifest agreement: every rank manifest present in the
        step dir must have been written at the SAME step. Disagreement means
        the directory mixes shards from different saves — unloadable by
        construction (ZeRO shards from different steps are not a state), so
        reject loudly instead of walking on."""
        seen: Dict[int, List[int]] = {}
        for r, man in self._rank_manifests(step).items():
            if man is None or "step" not in man:
                continue
            seen.setdefault(int(man["step"]), []).append(r)
        if len(seen) > 1:
            detail = ", ".join(
                f"step {s} (ranks {sorted(rs)})" for s, rs in sorted(seen.items())
            )
            raise CheckpointError(
                f"checkpoint dir {self._step_dir(step)}: rank manifests "
                f"disagree on the step they were written at — {detail}; "
                "the directory mixes shards from different saves and cannot "
                "be restored"
            )

    def _step_fully_committed(self, step: int) -> bool:
        marker = self._marker_path(step)
        try:
            with open(marker) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return False
        if not rec.get("committed"):
            return False
        mans = self._rank_manifests(step)
        return all(m is not None and m.get("committed") for m in mans.values())

    def resume(self, state_dict: Dict[str, Any], max_step: Optional[int] = None) -> int:
        """Load this rank's shard of the newest step EVERY rank committed;
        returns that step or -1. Walks back past uncommitted/partial steps
        (a crashed save); raises on a mixed-step directory (corruption the
        protocol can't produce). With a store bound, the world additionally
        agrees on the resolved step before anyone loads. ``max_step`` bounds
        the walk (stability-rollback anchor protocol — see
        ``AutoCheckpoint.resume``); every rank must pass the same bound or
        the agreement check rejects the resume."""
        fell_back = 0
        for step in self._steps_on_disk():
            if max_step is not None and step > max_step:
                continue
            self.check_manifest_agreement(step)
            if not self._step_fully_committed(step):
                fell_back += 1
                continue
            agreed = self._agree_on_resume_step(step)
            try:
                load_state_dict(
                    state_dict, self._rank_path(step), strict=False, verify=True
                )
            except Exception as e:
                if agreed:
                    # the world already settled on this step — peers are
                    # loading it NOW. Walking back here would silently mix
                    # steps across ranks (the exact state the protocol
                    # exists to prevent); fail loudly so the launcher
                    # restarts the whole world instead.
                    raise CheckpointError(
                        f"rank {self.rank}: the world agreed to resume from "
                        f"step {step} but this rank's shard failed to load "
                        f"({e!r}); refusing to fall back to an older step "
                        "while peers load the agreed one"
                    ) from e
                fell_back += 1
                continue
            if fell_back:
                _prof().counter_inc("ckpt_resume_fallbacks", fell_back)
            return step
        if fell_back:
            _prof().counter_inc("ckpt_resume_fallbacks", fell_back)
        return -1

    def _agree_on_resume_step(self, step: int) -> bool:
        """Store-mediated resume agreement: each rank publishes the step it
        resolved; disagreement (a rank seeing different fs state) raises
        naming both. Returns True only when a full, unanimous agreement ran
        — the caller then treats this step as BINDING (a local load failure
        must raise, not walk back, because peers are loading it). Advisory
        (False) when no store is bound or peers never showed up."""
        if self.store is None:
            return False
        from .coord import DeadlineExceeded, wait_for

        key = f"ckpt/resume/{self.rank}"
        self.store.set(key, str(int(step)))

        def all_published():
            return all(
                self.store.get(f"ckpt/resume/{r}") is not None
                for r in range(self.world_size)
            )

        try:
            wait_for(all_published, "resume-step agreement", self.commit_timeout_s())
        except DeadlineExceeded:
            warnings.warn(
                "resume-step agreement timed out (peers absent); proceeding "
                f"with locally-resolved step {step}"
            )
            return False
        votes = {
            r: int(self.store.get(f"ckpt/resume/{r}"))
            for r in range(self.world_size)
        }
        if len(set(votes.values())) > 1:
            raise CheckpointError(
                f"ranks disagree on the resume step: {votes} — refusing to "
                "mix steps across ranks"
            )
        return True

    # -- GC ----------------------------------------------------------------
    def _gc(self) -> None:
        """Rank 0 only: drop old step dirs, but never the newest fully
        committed one (the only recovery point if later saves turn out
        torn)."""
        steps = sorted(self._steps_on_disk())
        keep = set(steps[-self.keep_last:]) if self.keep_last else set(steps)
        committed = [s for s in steps if self._step_fully_committed(s)]
        if committed:
            keep.add(committed[-1])
        keep |= self._protected  # pinned rollback anchors survive any window
        for s in steps:
            if s in keep:
                continue
            shutil.rmtree(self._step_dir(s), ignore_errors=True)


def engine_state_dict(engine) -> Dict[str, Any]:
    """Checkpointable view of a HybridParallelEngine: params + opt accums,
    all kept in their sharded placements. For SAVING; to restore use
    ``engine_load_state_dict`` (the accum entries here are wrappers around
    copies — writing into them alone would not reach the optimizer)."""
    state = {}
    sync = getattr(engine, "sync_optimizer_state", None)
    if sync is not None:
        sync()  # ZeRO-1 engines keep opt state bucket-flat/sharded; unpack
    for i, p in enumerate(engine.params):
        state[f"param_{i}"] = p
    opt_state = engine.optimizer._functional_state(engine.params)
    for i, st in enumerate(opt_state["accums"]):
        for k, v in st.items():
            state[f"accum_{i}_{k}"] = Tensor(v, stop_gradient=True)
    # step count drives Adam/AdamW bias correction (reference checkpoints
    # beta1_pow/beta2_pow); without it a resume restarts correction at t=1
    state["opt_step"] = Tensor(
        np.asarray(engine.optimizer._step_count, np.int64), stop_gradient=True
    )
    return state


def engine_apply_state(engine, state: Dict[str, Any]) -> None:
    """Push a RESTORED ``engine_state_dict`` tree back into the engine: the
    param entries restored in place (they wrap the live Tensors), but the
    accumulator entries are wrapper copies — copy them into the optimizer's
    accumulators, restore the step count, and invalidate the engine-resident
    ZeRO sharded state so the next step repacks from the restored
    accumulators (the PR 3 failed-step recovery path). Shared by
    ``engine_load_state_dict`` and the stability sentinel's rollback."""
    opt = engine.optimizer
    step_t = state.get("opt_step")
    if step_t is not None:
        # cold path (checkpoint restore): the step counter must materialize
        opt._step_count = int(np.asarray(_concrete(step_t._data)))  # lint: ok(host-sync)
    for i, p in enumerate(engine.params):
        accum = opt._accumulators.get(id(p))
        if accum is None:
            continue
        for k in list(accum):
            t = state.get(f"accum_{i}_{k}")
            if t is not None:
                accum[k] = t._data
    inval = getattr(engine, "invalidate_dp_state", None)
    if inval is not None:
        inval()  # next step repacks the sharded state from restored accums


def engine_load_state_dict(engine, path) -> None:
    """Restore params AND optimizer accumulators of a HybridParallelEngine
    from a checkpoint written via ``engine_state_dict``."""
    state = engine_state_dict(engine)
    load_state_dict(state, path)
    engine_apply_state(engine, state)


__all__ = [
    "save_state_dict", "load_state_dict", "AutoCheckpoint", "CheckpointError",
    "CoordinatedCheckpoint", "read_manifest", "engine_state_dict",
    "engine_apply_state", "engine_load_state_dict",
]
