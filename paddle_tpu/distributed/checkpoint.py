"""Distributed (sharded, async) checkpointing + auto-resume.

Parity: reference distributed save/load (``fleet.utils.fs`` +
``incubate/checkpoint/auto_checkpoint.py:71`` — periodic checkpoint with
automatic resume) and sharded state persistence. TPU-native: orbax — each
host writes only its own shards of a GSPMD-sharded train state (no gather to
host 0), restore re-places shards per the target sharding; the async saver
overlaps serialization with the next training steps.
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, Optional

import numpy as np

from ..core.lazy import concrete as _concrete
import jax

from ..core.tensor import Tensor


def _to_arrays(state: Dict[str, Any]):

    out = {}
    for k, v in state.items():
        if isinstance(v, Tensor):
            out[k] = _concrete(v._data)
        elif isinstance(v, dict):
            out[k] = _to_arrays(v)
        else:
            out[k] = v
    return out


def _apply_arrays(state: Dict[str, Any], arrays: Dict[str, Any]):
    for k, v in state.items():
        a = arrays.get(k)
        if a is None:
            continue
        if isinstance(v, Tensor):
            # restore onto the tensor's current sharding (GSPMD layout kept)
            sharding = getattr(v._data, "sharding", None)
            arr = jax.device_put(a, sharding) if sharding is not None else a
            v._set_data(arr.astype(v._data.dtype) if hasattr(arr, "astype") else arr)
        elif isinstance(v, dict) and isinstance(a, dict):
            _apply_arrays(v, a)


def _ckpt(async_mode=False):
    import orbax.checkpoint as ocp

    if async_mode:
        return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return ocp.StandardCheckpointer()


def save_state_dict(state_dict: Dict[str, Any], path: str, async_save: bool = False):
    """Save a (possibly GSPMD-sharded) state dict WITHOUT gathering: every
    process writes its own shards (orbax OCDBT). ``async_save`` returns
    immediately and serializes in the background (reference async save)."""
    arrays = _to_arrays(state_dict)
    path = os.path.abspath(path)
    old = None
    if os.path.exists(path):
        # keep the previous checkpoint until the new one lands (atomicity:
        # orbax writes tmp+rename, so a fresh path is safe; the old copy is
        # parked aside and dropped only after a successful save)
        old = path + ".old"
        shutil.rmtree(old, ignore_errors=True)
        os.rename(path, old)
    ck = _ckpt(async_mode=async_save)
    try:
        ck.save(path, arrays)
    except Exception:
        if old and not os.path.exists(path):
            os.rename(old, path)
        raise
    if old and not async_save:
        shutil.rmtree(old, ignore_errors=True)
    # async: the .old backup is kept until the NEXT save parks it away — the
    # background write may still fail/crash before commit, and the backup is
    # the only good copy until then
    if async_save:
        return ck  # caller may ck.wait_until_finished()
    # StandardCheckpointer finalizes (atomic rename) in the background even
    # on the "sync" path — block so the artifact is durable on return
    getattr(ck, "wait_until_finished", lambda: None)()
    return None


def load_state_dict(state_dict: Dict[str, Any], path: str):
    """Restore into ``state_dict`` in place, re-placing each array onto the
    destination tensor's current sharding."""
    import orbax.checkpoint as ocp

    ck = ocp.StandardCheckpointer()
    arrays = ck.restore(os.path.abspath(path))
    _apply_arrays(state_dict, arrays)
    return state_dict


class AutoCheckpoint:
    """Periodic checkpoint + automatic resume (reference
    auto_checkpoint.py:71 ``train_epoch_range``): call ``maybe_save`` each
    step; on restart, ``resume`` returns the last completed step (or -1)."""

    def __init__(self, save_dir: str, interval_steps: int = 100, keep_last: int = 2, async_save: bool = False):
        self.save_dir = os.path.abspath(save_dir)
        self.interval = int(interval_steps)
        self.keep_last = keep_last
        self.async_save = async_save
        self._pending = None
        os.makedirs(self.save_dir, exist_ok=True)

    def _meta_path(self):
        return os.path.join(self.save_dir, "latest.json")

    def _step_path(self, step):
        return os.path.join(self.save_dir, f"step_{step}")

    def maybe_save(self, step: int, state_dict: Dict[str, Any]):
        if step == 0 or step % self.interval:
            # step 0 is the untrained state — saving it would also age out a
            # useful checkpoint one interval earlier under keep_last
            return False
        if self._pending is not None:
            self._pending.wait_until_finished()
            self._pending = None
        self._pending = save_state_dict(
            state_dict, self._step_path(step), async_save=self.async_save
        )
        with open(self._meta_path(), "w") as f:
            json.dump({"step": step, "ts": time.time()}, f)
        # GC old checkpoints (skip orbax tmp dirs mid-rename)
        steps = sorted(
            int(d.split("_")[1])
            for d in os.listdir(self.save_dir)
            if d.startswith("step_") and d.split("_")[1].isdigit()
        )
        for s in steps[: -self.keep_last]:
            shutil.rmtree(self._step_path(s), ignore_errors=True)
        return True

    def resume(self, state_dict: Dict[str, Any]) -> int:
        """Load the newest FINALIZED checkpoint into state_dict; returns its
        step or -1. Falls back to older checkpoints when the latest save was
        interrupted mid-write (latest.json can be ahead of the async
        finalize)."""
        if not os.path.isdir(self.save_dir):
            return -1
        steps = sorted(
            (
                int(d.split("_")[1])
                for d in os.listdir(self.save_dir)
                if d.startswith("step_") and d.split("_")[1].isdigit()
            ),
            reverse=True,
        )
        for step in steps:
            try:
                load_state_dict(state_dict, self._step_path(step))
                return step
            except Exception:
                continue  # incomplete/corrupt dir: try the next-oldest
        return -1

    def wait(self):
        if self._pending is not None:
            self._pending.wait_until_finished()
            self._pending = None


def engine_state_dict(engine) -> Dict[str, Any]:
    """Checkpointable view of a HybridParallelEngine: params + opt accums,
    all kept in their sharded placements. For SAVING; to restore use
    ``engine_load_state_dict`` (the accum entries here are wrappers around
    copies — writing into them alone would not reach the optimizer)."""
    state = {}
    for i, p in enumerate(engine.params):
        state[f"param_{i}"] = p
    opt_state = engine.optimizer._functional_state(engine.params)
    for i, st in enumerate(opt_state["accums"]):
        for k, v in st.items():
            state[f"accum_{i}_{k}"] = Tensor(v, stop_gradient=True)
    # step count drives Adam/AdamW bias correction (reference checkpoints
    # beta1_pow/beta2_pow); without it a resume restarts correction at t=1
    state["opt_step"] = Tensor(
        np.asarray(engine.optimizer._step_count, np.int64), stop_gradient=True
    )
    return state


def engine_load_state_dict(engine, path) -> None:
    """Restore params AND optimizer accumulators of a HybridParallelEngine
    from a checkpoint written via ``engine_state_dict``."""
    state = engine_state_dict(engine)
    load_state_dict(state, path)
    opt = engine.optimizer
    step_t = state.get("opt_step")
    if step_t is not None:
        opt._step_count = int(np.asarray(step_t._data))
    for i, p in enumerate(engine.params):
        accum = opt._accumulators.get(id(p))
        if accum is None:
            continue
        for k in list(accum):
            t = state.get(f"accum_{i}_{k}")
            if t is not None:
                accum[k] = t._data


__all__ = [
    "save_state_dict", "load_state_dict", "AutoCheckpoint",
    "engine_state_dict", "engine_load_state_dict",
]
