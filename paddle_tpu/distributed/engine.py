"""Hybrid-parallel training engine.

Parity: the reference's hybrid train loop (fleet.distributed_model +
HybridParallelOptimizer + per-op NCCL collectives, SURVEY.md §3.4). TPU-native
formulation: ONE compiled XLA program per train step —

 * params carry NamedShardings from their PartitionSpecs (Megatron 'mp'
   column/row specs from mp_layers, ZeRO specs from sharding stages);
 * the batch is sharded over 'dp' (and 'sp' for sequence parallel);
 * GSPMD partitions every matmul and inserts the all-reduces /
   reduce-scatters / all-gathers the reference codes as c_allreduce_sum /
   partial_* ops, scheduled by XLA's latency-hiding scheduler over ICI;
 * optimizer state sharded over the ZeRO axis makes the weight update a
   sharded computation (ZeRO-1/2 semantics) with an all-gather of updated
   params — "Automatic Cross-Replica Sharding of Weight Update" (PAPERS.md).

The engine is the TPU replacement for the reference's per-op executor hot
loop + DDP reducer + sharding-stage hooks, collapsed into compile time.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import random as random_state
from ..core.engine import no_grad
from ..core.tensor import Tensor
from .mesh import global_mesh


def _sharding(mesh: Mesh, spec) -> NamedSharding:
    if spec is None:
        spec = P()
    valid_axes = set(mesh.axis_names)
    cleaned = []
    for s in tuple(spec):
        if s is None or (isinstance(s, str) and s in valid_axes):
            cleaned.append(s)
        elif isinstance(s, (list, tuple)):
            cleaned.append(tuple(a for a in s if a in valid_axes) or None)
        else:
            cleaned.append(None)
    return NamedSharding(mesh, P(*cleaned))


class HybridParallelEngine:
    """Compile (params, opt_state, batch) → (loss, params', opt_state') once;
    every subsequent step is one executable launch.

    ``loss_fn(model, *batch_tensors) -> scalar Tensor``.
    """

    def __init__(
        self,
        model,
        optimizer,
        loss_fn: Callable,
        mesh: Optional[Mesh] = None,
        batch_specs: Optional[Sequence] = None,
        dp_axes=("dp",),
        grad_accumulate: int = 1,
        donate: bool = True,
    ):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or global_mesh()
        self.batch_specs = batch_specs
        self.dp_axes = dp_axes
        self.donate = donate
        self.grad_accumulate = max(int(grad_accumulate), 1)
        self.params = [p for p in model.parameters() if not p.stop_gradient]
        self.buffers = list(model.buffers())
        self._jit = None
        self._placed = False
        # ZeRO-1 sharded weight update (FLAGS_shard_weight_update): built at
        # first step for pure-DP meshes; _dp_state holds the engine-resident
        # bucket-flat optimizer state, physically sharded over the dp axis.
        self._wus = None
        self._dp_state = None
        # stability sentinel (fault/sentinel.py); None keeps the zero-cost
        # path — one attribute check per train_step
        self._sentinel = None
        # OOM recovery ladder (fault/memory.py): degraded accumulate-step
        # executables keyed by accumulation factor, and the hbm.oom chaos
        # consult site name ("engine.step" until a sticky degrade moves the
        # primary dispatch onto the accum path)
        self._degraded = {}
        self._dispatch_op = "engine.step"

    def attach_sentinel(self, sentinel) -> None:
        """Hook a :class:`~paddle_tpu.fault.sentinel.StabilitySentinel` into
        the step path: ``train_step`` consults the ``loss.spike``/
        ``grad.spike`` chaos points at the step boundary and feeds the step's
        loss into the sentinel as a COMMITTED observation — the donated fused
        step has already applied the update by the time the loss is
        readable, so a trip escalates to rollback (never skip), restoring
        engine-resident ZeRO shards through ``engine_apply_state``."""
        self._sentinel = sentinel

    # -- placement ---------------------------------------------------------
    def place(self):
        """device_put params per their PartitionSpecs (GSPMD layout)."""
        if self._placed:
            return
        for p in self.params + self.buffers:
            spec = getattr(p, "pspec", None)
            p._set_data(jax.device_put(p._data, _sharding(self.mesh, spec)))
        self._placed = True

    def _opt_sharding(self, p):
        spec = getattr(p, "opt_state_pspec", None) or getattr(p, "pspec", None)
        return _sharding(self.mesh, spec)

    def _constrain_grads(self, grads):
        """ZeRO-2/3: pin each grad to its ``grad_pspec`` layout so XLA emits a
        reduce-scatter (grads land sharded over the 'sharding' axis) instead
        of a replicated all-reduce — reference sharding_stage2.py:290
        ``_get_reduce_fn`` reduce-to-owner, done by the partitioner."""
        out = []
        for p, g in zip(self.params, grads):
            spec = getattr(p, "grad_pspec", None)
            if g is None or spec is None:
                out.append(g)
            else:
                out.append(
                    jax.lax.with_sharding_constraint(g, _sharding(self.mesh, spec))
                )
        return out

    def _batch_sharding(self, i, arr):
        if self.batch_specs is not None and i < len(self.batch_specs):
            return _sharding(self.mesh, self.batch_specs[i])
        # default: shard dim0 over dp axes present in the mesh
        axes = tuple(a for a in self.dp_axes if a in self.mesh.axis_names)
        spec = [axes if axes else None] + [None] * (arr.ndim - 1)
        return _sharding(self.mesh, P(*spec))

    # -- compiled step -----------------------------------------------------
    def _make_loss_of(self):
        model, loss_fn = self.model, self.loss_fn
        params, buffers = self.params, self.buffers

        def make_loss_of(batch_arrays, key):
            """loss(p_arrays) with the model's params rebound to traced
            arrays — shared by the plain and grad-accumulate paths."""

            def loss_of(p_arrays):
                saved = [(t, t._data) for t in params + buffers]
                try:
                    for t, a in zip(params, p_arrays):
                        t._data = a
                    inputs = [Tensor(a, stop_gradient=True) for a in batch_arrays]
                    with random_state.traced_keys(key):
                        with no_grad():
                            out = loss_fn(model, *inputs)
                    return out._data if isinstance(out, Tensor) else out
                finally:
                    for t, a in saved:
                        t._data = a

            return loss_of

        return make_loss_of

    def _accum_step_fn(self, acc: int):
        """Gradient accumulation: lax.scan over ``acc`` chunks of the batch
        (dim0 split), grads averaged into a ZeRO-sharded accumulator, ONE
        optimizer update (reference GradientMergeOptimizer /
        HybridParallelEngine grad-accumulate semantics). A factory so the
        OOM recovery ladder can build the SAME computation at 2×/4× the
        configured accumulation — a degraded step is bit-identical to a run
        configured with that accumulation from the start."""
        make_loss_of = self._make_loss_of()
        opt, params = self.optimizer, self.params

        def accum_step_fn(param_arrays, opt_state, batch_arrays, lr, key):
            chunked = tuple(
                a.reshape((acc, a.shape[0] // acc) + a.shape[1:]) for a in batch_arrays
            )

            def body(carry, chunk):
                g_acc, loss_acc, k = carry
                k, sub = jax.random.split(k)
                loss_of = make_loss_of(chunk, sub)
                loss, grads = jax.value_and_grad(loss_of)(list(param_arrays))
                g_acc = [
                    a if g is None else a + (g / acc).astype(a.dtype)
                    for a, g in zip(g_acc, grads)
                ]
                g_acc = self._constrain_grads(g_acc)
                loss_acc = loss_acc + (loss / acc).astype(jnp.float32)
                return (g_acc, loss_acc, k), None

            g0 = self._constrain_grads(
                [jnp.zeros(a.shape, a.dtype) for a in param_arrays]
            )
            (grads, loss, _), _ = lax.scan(body, (g0, jnp.float32(0.0), key), chunked)
            new_params, new_state = opt._functional_update(
                param_arrays, grads, opt_state, lr, params=params
            )
            return loss, new_params, new_state

        return accum_step_fn

    def _build(self):
        opt, params = self.optimizer, self.params
        make_loss_of = self._make_loss_of()

        def step_fn(param_arrays, opt_state, batch_arrays, lr, key):
            loss_of = make_loss_of(batch_arrays, key)
            loss, grads = jax.value_and_grad(loss_of)(list(param_arrays))
            grads = self._constrain_grads(grads)
            new_params, new_state = opt._functional_update(
                param_arrays, grads, opt_state, lr, params=params
            )
            return loss, new_params, new_state

        donate = (0, 1) if self.donate else ()
        from .fleet.meta_optimizers.hybrid_parallel_optimizer import (
            ShardedWeightUpdate,
        )

        self._wus = ShardedWeightUpdate.maybe_build(
            opt, params, self.mesh, self.dp_axes, self.grad_accumulate
        )
        if self._wus is not None:
            self._jit = jax.jit(
                self._build_dp_sharded(make_loss_of), donate_argnums=donate
            )
            from .. import profiler

            profiler.counter_inc("wus_enabled", 0)  # ensure key exists
            return
        fn = (
            self._accum_step_fn(self.grad_accumulate)
            if self.grad_accumulate > 1
            else step_fn
        )
        self._jit = jax.jit(fn, donate_argnums=donate)

    def _build_dp_sharded(self, make_loss_of):
        """Communication-optimized pure-DP step: ONE shard_map over the dp
        axis — local forward/backward on the batch shard, bucketed gradient
        reduce-scatter (reverse-backward order so XLA overlaps sync with
        remaining backward compute), 1/dp-shard optimizer update, updated
        params all-gathered (ZeRO-1; arXiv:2004.13336)."""
        wus = self._wus
        axis = wus.axis
        from jax.sharding import PartitionSpec as P

        from .mesh import shard_map_compat

        _shard_map, _check = shard_map_compat()

        def spmd(p_arrays, dp_state, batch_local, lr, key):
            # independent per-replica randomness (dropout masks differ per
            # batch shard, like per-worker seeds in the reference DDP)
            k = jax.random.fold_in(key, lax.axis_index(axis))
            loss_of = make_loss_of(batch_local, k)
            loss, grads = jax.value_and_grad(loss_of)(list(p_arrays))
            new_params, new_state = wus.apply(p_arrays, grads, dp_state, lr)
            return lax.pmean(loss, axis), tuple(new_params), new_state

        valid = set(self.mesh.axis_names)

        def clean_spec(spec):
            out = []
            for s in tuple(spec):
                if isinstance(s, (tuple, list)):  # multi-axis entry
                    kept = tuple(a for a in s if a in valid)
                    out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
                else:
                    out.append(s if (s is None or s in valid) else None)
            return P(*out)

        def step_fn(param_arrays, dp_state, batch_arrays, lr, key):
            batch_specs = tuple(
                clean_spec(self.batch_specs[i])
                if self.batch_specs is not None and i < len(self.batch_specs)
                else P(axis)
                for i in range(len(batch_arrays))
            )
            fn = _shard_map(
                spmd,
                mesh=self.mesh,
                in_specs=(
                    tuple(P() for _ in param_arrays),
                    wus.state_specs(),
                    batch_specs,
                    P(),
                    P(),
                ),
                out_specs=(
                    P(),
                    tuple(P() for _ in param_arrays),
                    wus.state_specs(),
                ),
                **_check,
            )
            return fn(tuple(param_arrays), dp_state, tuple(batch_arrays), lr, key)

        return step_fn

    def prefetch(self, data, buffer_size=2):
        """Wrap a DataLoader (or any batch iterable) in a device-side
        double-buffer committed to THIS engine's batch shardings: batch k+1
        is transferred (and GSPMD-placed) by a background thread while step k
        executes, so ``_prepare``'s per-step ``device_put`` degenerates to a
        no-op (async runtime tentpole; reference buffered_reader.cc)."""
        from ..io import DevicePrefetcher

        self.place()

        def sharding_of(i, arr):
            return self._batch_sharding(i if i is not None else 0, arr)

        return DevicePrefetcher(data, buffer_size=buffer_size, sharding=sharding_of)

    def _prepare(self, *batch):
        self.place()
        if self._jit is None:
            self._build()
        batch_arrays = []
        for i, b in enumerate(batch):
            arr = b._data if isinstance(b, Tensor) else jnp.asarray(b)
            batch_arrays.append(jax.device_put(arr, self._batch_sharding(i, arr)))
        param_arrays = [p._data for p in self.params]
        if self._wus is not None:
            if self._dp_state is None:
                self._dp_state = self._wus.init_state(self.mesh)
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
            key = random_state.next_key()
            return param_arrays, self._dp_state, tuple(batch_arrays), lr, key
        opt_state = self._replicated_opt_state()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = random_state.next_key()
        return param_arrays, opt_state, tuple(batch_arrays), lr, key

    def _replicated_opt_state(self):
        """Optimizer state for the replicated (non-wus) step, accumulators
        ZeRO-sharded over the sharding axis. Shared by ``_prepare`` and the
        OOM ladder's degrade rung (a wus engine falling back to the
        accumulate path mid-step repacks through here)."""
        opt_state = self.optimizer._functional_state(self.params)
        opt_state["accums"] = [
            {k: jax.device_put(v, self._opt_sharding(p)) for k, v in st.items()}
            for p, st in zip(self.params, opt_state["accums"])
        ]
        return opt_state

    @no_grad()
    def lower_text(self, *batch) -> str:
        """StableHLO of the train step (introspection/tests: sharding
        constraints appear as @Sharding custom calls / sdy ops). Side-effect
        free: the global RNG stream is restored so introspection never
        perturbs subsequent training."""
        st = random_state._get()
        saved_key = st.key
        try:
            args = self._prepare(*batch)
            return self._jit.lower(*args).as_text()
        finally:
            st.key = saved_key

    @no_grad()
    def train_step(self, *batch):
        from ..profiler import spans as _spans
        from . import watchdog

        # progress publication for the distributed watchdog: a peer that
        # stops stepping is attributable from this table. No-op (two attr
        # checks) when no supervision session is configured.
        watchdog.publish(
            step=getattr(self.optimizer, "_step_count", None), phase="train_step"
        )
        with _spans.span("train_step", kind="engine") as sp:
            return self._train_step_impl(sp, *batch)

    def _train_step_impl(self, sp, *batch):
        param_arrays, opt_state, batch_arrays, lr, key = self._prepare(*batch)
        sp.set(wus=self._wus is not None, params=len(self.params))
        if self._sentinel is not None:
            # chaos spikes are applied to the batch device-side (a poisoned
            # batch is exactly what the sentinel exists to survive)
            batch_arrays = self._sentinel.maybe_spike(
                batch_arrays, step=self.optimizer._step_count + 1
            )
        try:
            loss, new_params, new_state = self._dispatch(
                param_arrays, opt_state, batch_arrays, lr, key
            )
        except Exception as e:
            if self._wus is not None and self._dp_state is not None:
                # the failed launch may have invalidated the donated sharded
                # state; drop it so the next step repacks from the
                # optimizer's accumulators (last synced/initial copy) instead
                # of passing deleted buffers forever
                deleted = any(
                    getattr(v, "is_deleted", lambda: False)()
                    for st in self._dp_state["accums"] for v in st.values()
                    if isinstance(v, jax.Array)
                )
                if deleted:
                    self._dp_state = None
            from ..fault import memory as _hbm

            if not _hbm.is_oom(e):
                raise
            # RESOURCE_EXHAUSTED on the fused step: free pressure → retry →
            # degrade through the accumulate scan path → halt (post-mortem)
            loss, new_params, new_state = self._recover_oom(
                e, param_arrays, opt_state, batch_arrays, lr, key, sp
            )
        for p, a in zip(self.params, new_params):
            p._set_data(a)
        if self._wus is not None:
            # bucket-flat sharded state stays engine-resident (per-replica
            # optimizer memory is 1/dp); sync_optimizer_state() unpacks it
            # into the optimizer's per-param accumulators on demand
            self._dp_state = new_state
            self.optimizer._step_count += 1
            from .. import profiler

            profiler.counter_inc("wus_enabled", 1 - profiler.counters().get("wus_enabled", 0))
            for k, v in self._wus.step_counters().items():
                profiler.counter_inc(k, v)
            self._observe_stability(loss)
            return Tensor(loss)
        self.optimizer._functional_restore(self.params, new_state)
        self.optimizer._step_count += 1
        self._observe_stability(loss)
        return Tensor(loss)

    def _dispatch(self, *args):
        """One fused-step launch, with the ``hbm.oom`` chaos point consulted
        at the dispatch site (the unarmed path is one module-attribute
        probe — the hook core/dispatch.py already maintains)."""
        from ..core import dispatch as _dsp

        if _dsp._fault_inject is not None:
            _dsp._fault_inject.maybe_hbm_oom(
                self._dispatch_op, step=self.optimizer._step_count + 1
            )
        return self._jit(*args)

    def _recover_oom(self, exc, param_arrays, opt_state, batch_arrays, lr,
                     key, sp):
        """Engine-level OOM recovery ladder (fault/memory.py), run with the
        step's ALREADY-PREPARED arguments — the RNG key is reused, not
        redrawn, so a recovered step consumes exactly the key a healthy (or
        configured-from-start) run would.

        classify → free pressure → retry once → degrade by re-running the
        failed step through the grad-accumulate scan path at 2×/4×
        microbatching (sticky: pressure persists, so the engine STAYS at
        the working accumulation — every later step is then bit-identical
        to a run configured with it from the start) → halt with a flight
        post-mortem carrying the census, the per-executable attributions
        and every attempt."""
        from ..fault import memory as _hbm
        from .. import profiler

        attempts = [{"action": "classify",
                     **_hbm.note_oom(self._dispatch_op, exc)}]

        def _args_dead():
            # donate_argnums=(0,1) donates params AND the optimizer/dp
            # state — a launch that died after invalidating ANY of them has
            # nothing intact to dispatch with. Re-checked before EVERY rung:
            # the retry/degrade launches donate too, so a failed rung can
            # invalidate what the original failure left alive.
            return any(
                getattr(a, "is_deleted", lambda: False)()
                for a in (list(param_arrays) + list(batch_arrays)
                          + jax.tree_util.tree_leaves(opt_state))
                if isinstance(a, jax.Array)
            )

        def _halt(why, cause):
            attempts.append({"action": "halt", "why": why})
            path = _hbm.post_mortem("engine.step", attempts, cause)
            raise _hbm.HbmExhausted("engine.step", attempts, path) from cause

        if _args_dead():
            # checkpoint/sentinel recovery owns it from here
            _halt("donated inputs invalidated", exc)
        attempts.append({"action": "free_pressure",
                         **_hbm.free_pressure("engine.step")})
        try:
            out = self._dispatch(param_arrays, opt_state, batch_arrays, lr, key)
            profiler.counter_inc("hbm_oom_recoveries")
            attempts.append({"action": "retry", "ok": True})
            if sp is not None:
                sp.set(hbm_oom_recovered="retry")
            return out
        except Exception as e2:
            if not _hbm.is_oom(e2):
                raise
            attempts.append({"action": "retry", "ok": False})
            exc = e2
        base = self.grad_accumulate
        for mult in (2, 4):
            if _args_dead():
                # the previous (donating) rung died after invalidation —
                # dispatching the dead arrays would mask the OOM behind a
                # deleted-array error
                _halt("donated inputs invalidated by a failed rung", exc)
            acc = base * mult
            if any(
                a.shape[0] % acc
                for a in batch_arrays
                if getattr(a, "ndim", 0) >= 1
            ):
                attempts.append({"action": f"degrade_x{mult}", "ok": False,
                                 "why": "batch dim0 not divisible"})
                continue
            if self._wus is not None:
                # the sharded weight update has no accumulate path (PR 3):
                # sync the shards back and fall to the replicated update —
                # exactly what a from-start accumulate config builds
                self.sync_optimizer_state()
                self._wus = None
                self._dp_state = None
                opt_state = self._replicated_opt_state()
            fn = self._degraded.get(acc)
            if fn is None:
                fn = self._degraded[acc] = jax.jit(
                    self._accum_step_fn(acc),
                    donate_argnums=(0, 1) if self.donate else (),
                )
            try:
                from ..core import dispatch as _dsp

                if _dsp._fault_inject is not None:
                    _dsp._fault_inject.maybe_hbm_oom(
                        "engine.accum", step=self.optimizer._step_count + 1
                    )
                out = fn(param_arrays, opt_state, batch_arrays, lr, key)
            except Exception as e3:
                if not _hbm.is_oom(e3):
                    raise
                attempts.append({"action": f"degrade_x{mult}", "ok": False})
                exc = e3
                continue
            self.grad_accumulate = acc
            self._jit = fn
            self._dispatch_op = "engine.accum"
            profiler.counter_inc("hbm_oom_recoveries")
            profiler.counter_inc("hbm_degraded_steps")
            attempts.append({"action": f"degrade_x{mult}", "ok": True})
            if sp is not None:
                sp.set(hbm_oom_recovered=f"accum_x{mult}", grad_accumulate=acc)
            return out
        path = _hbm.post_mortem("engine.step", attempts, exc)
        raise _hbm.HbmExhausted("engine.step", attempts, path) from exc

    def _observe_stability(self, loss) -> None:
        """Feed the committed step's loss to the attached sentinel (verdicts
        surface via ``sentinel.take_verdict()`` after ``train_step``
        returns). The loss is handed over as the in-flight device array —
        the sentinel defers the readback one step, so no host sync lands on
        the dispatch path."""
        if self._sentinel is not None:
            self._sentinel.observe(
                self.optimizer._step_count, loss=loss, committed=True, stash=True
            )

    def sync_optimizer_state(self):
        """Unpack the engine-resident ZeRO-1 sharded optimizer state into the
        optimizer's per-param accumulators (checkpoint save, inspection).
        No-op for the replicated path, which restores them every step."""
        if self._wus is not None and self._dp_state is not None:
            self._wus.sync_back(self._dp_state)

    def invalidate_dp_state(self):
        """Drop the engine-resident sharded state so the next step repacks it
        from the optimizer's accumulators (call after restoring a
        checkpoint into the optimizer)."""
        self._dp_state = None

    @no_grad()
    def eval_step(self, fn, *batch):
        self.place()
        arrays = [
            jax.device_put(
                b._data if isinstance(b, Tensor) else jnp.asarray(b),
                self._batch_sharding(i, b._data if isinstance(b, Tensor) else jnp.asarray(b)),
            )
            for i, b in enumerate(batch)
        ]
        inputs = [Tensor(a, stop_gradient=True) for a in arrays]
        return fn(self.model, *inputs)


def shard_model_params(model, mesh=None):
    """Apply each param's pspec placement without building an engine."""
    mesh = mesh or global_mesh()
    for p in model.parameters():
        p._set_data(jax.device_put(p._data, _sharding(mesh, getattr(p, "pspec", None))))
    for b in model.buffers():
        b._set_data(jax.device_put(b._data, _sharding(mesh, None)))
    return model
