"""Expert-parallel routing prims.

Parity: reference ``python/paddle/distributed/utils.py:57,179``
global_scatter/global_gather backed by C++ all-to-all-v ops
(``operators/collective/global_scatter_op.cc``). TPU-native: fixed-capacity
all_to_all (static shapes; tokens bucketed per expert with capacity factor) —
the standard TPU MoE formulation.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


def global_scatter(x, local_count, global_count, group=None):
    """Send token rows to expert owners across the ep axis (all-to-all)."""
    t = as_tensor(x)
    axis = group.axis_name if group is not None else None
    from .collective import _axis_bound
    if isinstance(t._data, jax.core.Tracer) and axis is not None and _axis_bound(axis):
        def fn(a):
            return lax.all_to_all(a, axis, split_axis=0, concat_axis=0, tiled=True)

        return eager_call("global_scatter", fn, [t])
    return t


def global_gather(x, local_count, global_count, group=None):
    t = as_tensor(x)
    axis = group.axis_name if group is not None else None
    from .collective import _axis_bound
    if isinstance(t._data, jax.core.Tracer) and axis is not None and _axis_bound(axis):
        def fn(a):
            return lax.all_to_all(a, axis, split_axis=0, concat_axis=0, tiled=True)

        return eager_call("global_gather", fn, [t])
    return t


def get_cluster_from_args(args, selected_gpus=None):
    raise NotImplementedError("single-controller runtime: use paddle_tpu.distributed.launch")
