"""paddle.distributed.spawn parity.

Reference: ``python/paddle/distributed/spawn.py`` — fork N single-GPU
processes. TPU-native single-controller runtime: one process drives all
chips, so spawn() runs the function once with the full mesh; multihost
launches go through paddle_tpu.distributed.launch (one process per host).
"""
from __future__ import annotations


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    func(*args)
    return None
